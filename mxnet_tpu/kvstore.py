"""KVStore: data-parallel parameter synchronization.

Parity surface: reference ``python/mxnet/kvstore.py`` + ``src/kvstore/``
(KVStoreLocal + Comm reduce/broadcast, kvstore_local.h:49-175, comm.h;
dist modes over ps-lite, kvstore_dist.h).

TPU-native redesign (SURVEY §2.5, §5.8): the parameter-server machinery is
replaced by collectives.  ``local``/``device`` keep reference semantics
in-process: ``push`` reduces a list of per-device arrays (the Comm::Reduce
tree-reduce becomes a jnp sum — XLA handles cross-device gathers), the
registered updater runs the optimizer, ``pull`` broadcasts.  ``dist_*``
modes map onto ``jax.distributed`` process groups where ``push+pull``
lowers to a psum across hosts (here: single-process rank 0 of 1 until
multi-host is attached; the *semantics* — aggregate-then-broadcast — are
identical and tested by the dist-invariant tests on one host).
"""
from __future__ import annotations

import pickle

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray
from . import optimizer as opt

__all__ = ["KVStore", "create"]


import jax
import jax.numpy as jnp

from . import profiler as _prof
from . import telemetry as _tel


def _stack_sum(arrs):
    """One fused XLA reduction over the per-device contributions."""
    return jnp.sum(jnp.stack(arrs), axis=0)


_stack_sum = _tel.watch_jit(jax.jit(_stack_sum), "kvstore_stack_sum")

# every kvstore-owned program is collective communication for the
# device-time step decomposition: blocked time under these names lands
# in the step timeline's collective segment (and overlap_ratio's
# denominator), not device-compute
_tel.device.register_collective("kvstore")


def _nd_nbytes(arr):
    return arr.size * arr.dtype.itemsize


# ---- bucketed gradient reduction (DDP-style flat buckets) -----------------
#
# One psum/reduce per parameter is O(n_params) collectives per step; the
# fused Trainer step instead flattens gradients into a small number of
# fixed-size, dtype-homogeneous buckets and reduces each bucket in ONE
# collective ("Automatic Cross-Replica Sharding of Weight Update in
# Data-Parallel Training", PAPERS.md — and every DDP implementation since).

_DEFAULT_BUCKET_BYTES = 4 << 20      # 4 MiB, the PyTorch-DDP default scale


def _env_bucket_bytes():
    import os
    try:
        return max(1, int(os.environ.get("MXNET_KVSTORE_BUCKET_BYTES",
                                         _DEFAULT_BUCKET_BYTES)))
    except ValueError:
        return _DEFAULT_BUCKET_BYTES


# cached at import (the JG006 cached-value pattern): _plan_buckets runs on
# every push and must not re-parse the environment per step
_BUCKET_BYTES = _env_bucket_bytes()


def refresh_from_env():
    """Re-read MXNET_KVSTORE_BUCKET_BYTES (tests / late configuration)."""
    global _BUCKET_BYTES
    _BUCKET_BYTES = _env_bucket_bytes()


def _bucket_bytes():
    return _BUCKET_BYTES


def _plan_buckets(metas, limit=None):
    """Greedy fixed-size bucket assignment.

    *metas*: list of ``(group_key, nbytes)`` in slot order — group_key is
    whatever must be homogeneous inside a bucket (dtype, or
    (dtype, n_copies)).  Returns a list of buckets, each a list of slot
    indices; slot order is preserved within a group, no bucket's payload
    exceeds *limit* bytes (a single oversize tensor gets its own bucket).
    """
    limit = limit or _bucket_bytes()
    open_buckets = {}                  # group_key -> [indices, bytes]
    plan = []
    for i, (gk, nbytes) in enumerate(metas):
        cur = open_buckets.get(gk)
        if cur is None or (cur[1] + nbytes > limit and cur[0]):
            cur = [[], 0]
            open_buckets[gk] = cur
            plan.append(cur)
        cur[0].append(i)
        cur[1] += nbytes
    return [b[0] for b in plan]


def _bucket_reduce(copies):
    """ONE XLA program for a whole bucket: flatten+concat each device
    copy, sum across copies, split back per key.

    *copies*: tuple (n_copies) of tuples (n_keys) of same-dtype arrays —
    copies[j][i] is device j's contribution for the bucket's i-th key.
    """
    flats = [jnp.concatenate([jnp.ravel(a) for a in copy])
             for copy in copies]
    total = flats[0] if len(flats) == 1 \
        else jnp.sum(jnp.stack(flats), axis=0)
    outs, off = [], 0
    for a in copies[0]:
        n = a.size
        outs.append(total[off:off + n].reshape(a.shape))
        off += n
    return tuple(outs)


_bucket_reduce = _tel.watch_jit(jax.jit(_bucket_reduce),
                                "kvstore_bucket_reduce")


def tracecheck_programs():
    """AOT specimens for graftcheck: the two owned kvstore programs — the
    per-key stack-sum and the bucketed flat reduce (two device copies,
    two keys of different shapes, like a real small bucket)."""
    a = jax.ShapeDtypeStruct((64, 32), jnp.float32)
    b = jax.ShapeDtypeStruct((128,), jnp.float32)
    # sharding metadata (JX202): both programs dispatch on the engine's
    # serialized collective lane — their per-axis collective order must
    # match the other lane members' (PR 13 canonical-order contract)
    lane = {"lane": "engine-collective"}
    return [
        ("kvstore_stack_sum", _stack_sum, ([a, a],), {}, lane),
        ("kvstore_bucket_reduce", _bucket_reduce, (((a, b), (a, b)),), {},
         lane),
    ]


def _ctx_group_sum(vals):
    """Reduce a list of NDArrays (possibly on different devices).

    Device path (reference ``CommDevice::Reduce``, comm.h:462-560): gather
    the shards onto the first array's device and run one jitted sum — no
    host round-trip.  XLA/PJRT handles the cross-device copies the way the
    reference used P2P + a merge buffer.
    """
    if len(vals) == 1:
        return vals[0]
    dev = next(iter(vals[0]._data.devices()))
    shards = [jax.device_put(v._data, dev) for v in vals]
    return NDArray(_stack_sum(shards), ctx=vals[0].context)


def _key_list(key, vals):
    if isinstance(key, (str, int)):
        return [key], [vals]
    assert len(key) == len(vals)
    return list(key), list(vals)


class KVStore:
    """Single-process kvstore with reference push/pull semantics."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store = {}          # key -> NDArray (the authoritative weight)
        self._updater = None
        self._optimizer = None
        self._barrier_count = 0

    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        import jax
        return getattr(jax, "process_index", lambda: 0)()

    @property
    def num_workers(self):
        import jax
        return getattr(jax, "process_count", lambda: 1)()

    def init(self, key, value):
        keys, vals = _key_list(key, value)
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):
                v = v[0]
            if str(k) in self._store:
                raise MXNetError("key %s already initialized" % k)
            self._store[str(k)] = v.copy()

    def _post_reduce(self, k, reduced):
        """What push does after the cross-copy reduce for one key."""
        if self._updater is not None:
            self._updater(_updater_key(k), reduced, self._store[k])
        else:
            self._store[k]._set_data(
                reduced.as_in_context(self._store[k].context)._data)

    def push(self, key, value, priority=0):
        keys, vals = _key_list(key, value)
        for k, v in zip(keys, vals):
            k = str(k)
            if k not in self._store:
                raise MXNetError("key %s not initialized" % k)
            vlist = v if isinstance(v, (list, tuple)) else [v]
            _prof.bump("kvstore_push")
            if len(vlist) > 1:
                _prof.bump("xla_program_calls")   # the per-key reduce
            if _tel.enabled():
                _tel.bump("kvstore_push_bytes",
                          sum(_nd_nbytes(c) for c in vlist))
            reduced = _ctx_group_sum(list(vlist))
            self._post_reduce(k, reduced)

    def pull(self, key, out=None, priority=0, row_ids=None,
             ignore_sparse=True):
        assert out is not None
        keys, outs = _key_list(key, out)
        for k, o in zip(keys, outs):
            k = str(k)
            if k not in self._store:
                raise MXNetError("key %s not initialized" % k)
            olist = o if isinstance(o, (list, tuple)) else [o]
            for dst in olist:
                _prof.bump("kvstore_pull")
                # each broadcast copy launches one program, mirroring the
                # reduce leg's accounting (push/pull symmetry)
                _prof.bump("xla_program_calls")
                if _tel.enabled():
                    _tel.bump("kvstore_pull_bytes", _nd_nbytes(dst))
                self._store[k].copyto(dst)

    # -- batched / bucketed entry points (fused Trainer step front end) ----

    def _normalize_all(self, keys, values):
        """-> ([str keys], [list-of-NDArray per key]) with init checks."""
        assert len(keys) == len(values)
        skeys, vlists = [], []
        for k, v in zip(keys, values):
            k = str(k)
            if k not in self._store:
                raise MXNetError("key %s not initialized" % k)
            skeys.append(k)
            vlists.append(list(v) if isinstance(v, (list, tuple)) else [v])
        return skeys, vlists

    def _reduce_all(self, vlists):
        """Bucketed cross-copy reduction over all keys at once.

        Single-copy keys are identity (no program — same contract as
        ``_ctx_group_sum``'s len-1 fast path).  Multi-copy keys are
        grouped into (dtype, n_copies)-homogeneous flat buckets and each
        bucket is reduced by ONE ``_bucket_reduce`` program instead of
        one ``_stack_sum`` per key.  Returns reduced NDArrays, bitwise
        equal to the per-key path (same copy order, same summation axis).
        """
        reduced = [None] * len(vlists)
        multi = []
        for i, vlist in enumerate(vlists):
            if len(vlist) == 1:
                reduced[i] = vlist[0]
            else:
                multi.append(i)
        if multi:
            from .parallel import collective as _coll
            # group key includes the leading copy's device: each key's
            # reduction must land where its own copy-0 lives (the per-key
            # _ctx_group_sum contract) — mixing devices in one bucket
            # would mislabel results' placement
            metas = [((str(vlists[i][0].dtype), len(vlists[i]),
                       next(iter(vlists[i][0]._data.devices()))),
                      vlists[i][0].size * vlists[i][0].dtype.itemsize)
                     for i in multi]
            for bucket in _plan_buckets(metas):
                idxs = [multi[b] for b in bucket]
                dev = next(iter(vlists[idxs[0]][0]._data.devices()))
                n_copies = len(vlists[idxs[0]])
                copies = tuple(
                    tuple(jax.device_put(vlists[i][j]._data, dev)
                          for i in idxs)
                    for j in range(n_copies))
                _prof.bump("kvstore_bucket_reduce")
                nbytes = sum(metas[b][1] for b in bucket)
                if _tel.enabled():
                    _tel.bump("kvstore_reduce_bytes", nbytes)
                    _tel.observe("bucket_bytes", nbytes)
                chunked = len(idxs) == 1 and nbytes > _coll.chunk_bytes()
                with _tel.span("kvstore_bucket_reduce", cat="kvstore",
                               args={"bytes": nbytes, "keys": len(idxs),
                                     "copies": n_copies,
                                     "chunked": chunked}):
                    if chunked:
                        # single-oversize-tensor bucket: pipelined
                        # chunked reduce (arXiv 2112.01075) — bounded
                        # peak memory, per-chunk program accounting
                        # inside the collective module
                        i = idxs[0]
                        flat = _coll.chunked_reduce(
                            [jnp.ravel(c[0]) for c in copies])
                        outs = (flat.reshape(vlists[i][0].shape),)
                    else:
                        _prof.bump("xla_program_calls")
                        outs = _bucket_reduce(copies)
                for i, o in zip(idxs, outs):
                    reduced[i] = NDArray(o, ctx=vlists[i][0].context)
        return reduced

    def push_all(self, keys, values, priority=0):
        """Batched push: one bucketed reduction program per (dtype,
        n_copies) bucket instead of one reduce per key."""
        skeys, vlists = self._normalize_all(keys, values)
        for k, r in zip(skeys, self._reduce_all(vlists)):
            _prof.bump("kvstore_push")
            self._post_reduce(k, r)

    def pull_all(self, keys, outs, priority=0):
        """Batched pull (reference broadcast leg)."""
        assert len(keys) == len(outs)
        for k, o in zip(keys, outs):
            self.pull(k, out=o, priority=priority)

    def push_pull_all(self, keys, values, outs=None, priority=0):
        """Fused bucketed reduce + broadcast over all keys: the gradient
        all-reduce a data-parallel ``Trainer.step`` actually needs, in
        O(n_buckets) programs instead of O(n_keys).

        Returns the reduced per-key NDArrays (and additionally writes
        them into *outs* when given).  With an updater installed this
        degrades to the reference push-then-pull semantics (the updater
        runs per key on the bucketed reduction's result).
        """
        skeys, vlists = self._normalize_all(keys, values)
        reduced = self._reduce_all(vlists)
        results = []
        if self._updater is not None:
            for k, r, v in zip(skeys, reduced, vlists):
                self._post_reduce(k, r)
                results.append(self._store[k])
        else:
            for k, r in zip(skeys, reduced):
                # rebind the authoritative copy — no program launched
                self._store[k]._set_data(
                    r.as_in_context(self._store[k].context)._data)
                results.append(r)
        if outs is not None:
            for r, o in zip(results, outs):
                for dst in (o if isinstance(o, (list, tuple)) else [o]):
                    if dst is not r:
                        _prof.bump("kvstore_pull")
                        _prof.bump("xla_program_calls")  # broadcast copy
                        r.copyto(dst)
        return results

    def reduce_scatter_all(self, keys, values, shardings, priority=0):
        """Bucketed reduce-scatter: the ZeRO-1 gradient leg
        (arXiv 2004.13336), beside :meth:`push_pull_all`.

        Cross-copy reduction runs through the same (dtype, n_copies)
        flat buckets as ``push_pull_all`` (one program per bucket), then
        each reduced value is *scattered* onto ``shardings[i]`` — a
        ``jax.Sharding`` placing the rows its owning replicas update, or
        None to leave the reduction where it landed.  The scatter is
        pure data movement (on a mesh backend each device keeps only its
        rows); no extra XLA program launches.  Like ``push_pull_all``
        this owns the whole round: per-key server slots and the callers'
        gradient buffers are NOT rewritten — the sharded results feed
        the fused sharded update directly.
        """
        skeys, vlists = self._normalize_all(keys, values)
        assert len(shardings) == len(skeys)
        reduced = self._reduce_all(vlists)
        _prof.bump("kvstore_reduce_scatter")
        return self._scatter(reduced, vlists, shardings)

    @staticmethod
    def _scatter(reduced, vlists, shardings):
        """Place reduced values onto their target shardings (one batched
        transfer; None entries pass through)."""
        placed = list(reduced)
        idxs = [i for i, s in enumerate(shardings) if s is not None]
        if idxs:
            outs = jax.device_put([reduced[i]._data for i in idxs],
                                  [shardings[i] for i in idxs])
            for i, o in zip(idxs, outs):
                placed[i] = NDArray(o, ctx=vlists[i][0].context)
        return placed

    def all_gather_all(self, keys, values, priority=0):
        """The inverse leg: materialize each (possibly update-sharded)
        value fully on its own context device — what a consumer outside
        the sharded step program (evaluation, host export) needs.  Pure
        data movement (chunked: an update-sharded value streams home
        shard by shard through ``parallel.collective.gather_home``
        instead of staging a full extra copy); returns new NDArrays."""
        from .parallel import collective as _coll
        skeys, vlists = self._normalize_all(keys, values)
        outs = []
        for k, vl in zip(skeys, vlists):
            v = vl[0]
            _prof.bump("kvstore_pull")
            outs.append(NDArray(_coll.gather_home(v._data,
                                                  v.context.jax_device),
                                ctx=v.context))
        return outs

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (reference kvstore.py:227)."""
        assert out is not None and row_ids is not None
        keys, outs = _key_list(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        for k, o, rid in zip(keys, outs, row_ids):
            k = str(k)
            src = self._store[k]
            olist = o if isinstance(o, (list, tuple)) else [o]
            # device-side gather/scatter (SURVEY §7 index+values design):
            # no host round trip of the full parameter
            import jax.numpy as jnp
            rows = jnp.asarray(rid._data).astype(jnp.int64)
            full = src._data
            picked = jnp.take(full, rows, axis=0)
            sparse = jnp.zeros_like(full).at[rows].set(picked)
            for dst in olist:
                placed = jax.device_put(sparse.astype(dst.dtype),
                                        dst.context.jax_device)
                dst._set_data(placed)
                dst._stype = "row_sparse"
                if hasattr(dst, "_seed_sparse"):
                    dst._seed_sparse(rows, jnp.take(placed, rows, axis=0))

    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Run optimizer 'on the server' (update_on_kvstore mode)."""
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        raise NotImplementedError(
            "gradient compression is not present in the reference revision")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states for distributed training"
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot load states for distributed training"
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())

    # -- checkpoint-state protocol (mxnet_tpu.checkpoint) ------------------
    # Server-side optimizer state (update_on_kvstore mode) as host bytes:
    # the sharded-checkpoint analogue of save/load_optimizer_states, so a
    # CheckpointManager captures the KVStore-resident Updater alongside
    # the params it updates.  None = nothing to save (no updater).

    def get_checkpoint_state(self):
        if self._updater is None:
            return None
        # include the update counts: the server optimizer's bias
        # correction (`t`) must survive a resume bitwise.  Keys pass
        # through untouched — updaters fed through _updater_key may be
        # keyed by int slot OR param-name string (the module
        # update_on_kvstore path), and pickle preserves either.
        blob = self._updater.get_states(dump_optimizer=False)
        counts = num_update = None
        srv_opt = getattr(self._updater, "optimizer", None)
        if srv_opt is not None:
            counts = dict(srv_opt._index_update_count)
            num_update = int(srv_opt.num_update)
        return pickle.dumps({"updater": blob,
                             "index_update_count": counts,
                             "num_update": num_update})

    def set_checkpoint_state(self, blob):
        if blob is None:
            return
        assert self._updater is not None, \
            "restoring kvstore optimizer state needs an updater installed"
        payload = pickle.loads(blob)
        self._updater.set_states(payload["updater"])
        srv_opt = getattr(self._updater, "optimizer", None)
        if srv_opt is not None \
                and payload.get("index_update_count") is not None:
            srv_opt._index_update_count = \
                dict(payload["index_update_count"])
            srv_opt.num_update = int(payload["num_update"])

    def barrier(self):
        self._barrier_count += 1

    def get_num_dead_node(self, node_id=0, timeout=60):
        """Dead-node count (ref kvstore.h:328); single-process stores have
        no failure surface — always 0."""
        return 0

    def _send_command_to_servers(self, head, body):
        pass

    def __del__(self):
        pass


class KVStoreDist(KVStore):
    """Multi-process distributed kvstore over the dist_ps transport.

    Reference counterpart: ``src/kvstore/kvstore_dist.h`` (worker) +
    ``kvstore_dist_server.h`` (server).  Semantics preserved:

    - ``dist_sync``: a push blocks until every worker's contribution for
      that (key, timestamp) is aggregated on the server and the update
      applied — so pull-after-push observes the globally updated value.
    - ``dist_async``: the server applies each worker's push immediately.
    - ``set_optimizer`` pickles the optimizer to the servers
      (update_on_kvstore mode); with no server optimizer the servers
      store the aggregated gradient for workers to pull and apply locally.
    - Big arrays are range-sharded across all servers
      (MXNET_KVSTORE_BIGARRAY_BOUND).

    In a process whose ``DMLC_ROLE`` is ``scheduler`` or ``server``,
    constructing the store runs that role's loop and exits — the launcher
    runs the same user script in every role, like the reference tracker.
    """

    def __init__(self, kind="dist_sync"):
        super().__init__(kind)
        import sys
        from . import dist_ps
        r = dist_ps.role()
        if r == "scheduler":
            dist_ps.run_scheduler()
            sys.exit(0)
        if r == "server":
            dist_ps.run_server()
            sys.exit(0)
        self._trans = dist_ps.WorkerTransport()
        self._shapes = {}
        self._dtypes = {}
        self._bucket_layouts = {}     # tuple(keys) -> bucket descriptors
        self._bucket_inited = set()   # bucket keys registered on servers
        if "async" in kind and self._trans.rank == 0:
            self._trans.set_sync(False)
        # all workers rendezvous here so no push can reach a server that
        # has not yet seen rank 0's set_sync
        self._trans.barrier()
        import atexit
        atexit.register(self._finalize)

    @property
    def rank(self):
        return self._trans.rank

    @property
    def num_workers(self):
        from . import dist_ps
        return dist_ps.num_workers()

    def init(self, key, value):
        keys, vals = _key_list(key, value)
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):
                v = v[0]
            k = str(k)
            self._shapes[k] = v.shape
            self._dtypes[k] = v.dtype
            if self.rank == 0:
                self._trans.init(k, v.asnumpy())
        self.barrier()

    def _is_sharded(self, k):
        from . import dist_ps
        return len(dist_ps.placement(k, self._shapes[k],
                                     self._trans.nservers)) > 1

    def push(self, key, value, priority=0):
        keys, vals = _key_list(key, value)
        for k, v in zip(keys, vals):
            k = str(k)
            if k not in self._shapes:
                raise MXNetError("key %s not initialized" % k)
            vlist = v if isinstance(v, (list, tuple)) else [v]
            _prof.bump("kvstore_push")
            if len(vlist) > 1:
                _prof.bump("xla_program_calls")   # the local reduce
            reduced = _ctx_group_sum(list(vlist))
            sparse = getattr(reduced, "stype", "default") == "row_sparse"
            if sparse and not self._is_sharded(k):
                dense = reduced.asnumpy()
                rows = np.nonzero(np.any(dense != 0, axis=tuple(
                    range(1, dense.ndim))))[0]
                self._trans.push(k, dense[rows], rows=rows)
            else:
                # dense keys, and row_sparse keys big enough to be
                # range-sharded across servers (row blocks don't map onto
                # flat ranges — ship the dense aggregate instead)
                self._trans.push(k, reduced.asnumpy())

    def pull(self, key, out=None, priority=0, row_ids=None,
             ignore_sparse=True):
        assert out is not None
        keys, outs = _key_list(key, out)
        for k, o in zip(keys, outs):
            k = str(k)
            olist = o if isinstance(o, (list, tuple)) else [o]
            val = self._trans.pull(k, self._shapes.get(k, olist[0].shape))
            for dst in olist:
                _prof.bump("kvstore_pull")
                _prof.bump("xla_program_calls")   # host->device upload
                dst._set_data(nd.array(val, ctx=dst.context,
                                       dtype=dst.dtype)._data)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        assert out is not None and row_ids is not None
        keys, outs = _key_list(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        for k, o, rid in zip(keys, outs, row_ids):
            k = str(k)
            olist = o if isinstance(o, (list, tuple)) else [o]
            rows = rid.asnumpy().astype(np.int64)
            shape = self._shapes[k]
            if self._is_sharded(k):
                block = self._trans.pull(k, shape)[rows]
            else:
                block = self._trans.pull_rows(k, shape, rows)
            sparse = np.zeros(shape, self._dtypes[k])
            sparse[rows] = block
            for dst in olist:
                dst._set_data(nd.array(sparse, ctx=dst.context,
                                       dtype=dst.dtype)._data)
                dst._stype = "row_sparse"

    def push_all(self, keys, values, priority=0):
        """Per-key on dist: a bucketed push would leave the per-key
        server slots stale for later per-key pulls.  The bucketed fast
        path is ``push_pull_all``, which owns both legs of the round."""
        for k, v in zip(keys, values):
            self.push(k, v, priority=priority)

    def pull_all(self, keys, outs, priority=0):
        for k, o in zip(keys, outs):
            self.pull(k, out=o, priority=priority)

    def _bucket_layout(self, keys):
        """Plan (and lazily server-init) flat buckets for a key tuple.

        Deterministic across workers: every rank derives the same layout
        from the same key/shape/dtype metadata, rank 0 registers the
        bucket keys server-side, everyone barriers.
        """
        kt = tuple(keys)
        layout = self._bucket_layouts.get(kt)
        if layout is None:
            import hashlib
            metas = [(str(np.dtype(self._dtypes[k])),
                      int(np.prod(self._shapes[k], dtype=np.int64))
                      * np.dtype(self._dtypes[k]).itemsize)
                     for k in keys]
            layout = []
            for idxs in _plan_buckets(metas):
                members = [keys[i] for i in idxs]
                dtype = np.dtype(self._dtypes[members[0]])
                sizes = [int(np.prod(self._shapes[k], dtype=np.int64))
                         for k in members]
                digest = hashlib.md5(";".join(
                    "%s:%s:%s" % (k, self._shapes[k], dtype)
                    for k in members).encode()).hexdigest()[:12]
                layout.append({"key": "__bucket__" + digest, "idxs": idxs,
                               "sizes": sizes, "dtype": dtype,
                               "total": sum(sizes)})
            self._bucket_layouts[kt] = layout
        fresh = [b for b in layout if b["key"] not in self._bucket_inited]
        if fresh:
            if self.rank == 0:
                for b in fresh:
                    self._trans.init(b["key"],
                                     np.zeros((b["total"],), b["dtype"]))
            self.barrier()
            self._bucket_inited.update(b["key"] for b in fresh)
        return layout

    def push_pull_all(self, keys, values, outs=None, priority=0):
        """Bucketed gradient all-reduce over the dist transport: one
        push+pull round per flat bucket instead of per key.

        Note: this path owns both legs of the round — the per-key server
        slots are NOT updated, so don't interleave it with per-key
        ``pull`` of the same keys (use push/pull for that).  Sparse
        values fall back to the per-key path.
        """
        skeys = [str(k) for k in keys]
        vlists = []
        for k, v in zip(skeys, values):
            if k not in self._shapes:
                raise MXNetError("key %s not initialized" % k)
            vlists.append(list(v) if isinstance(v, (list, tuple)) else [v])
        if self._optimizer is not None or any(
                getattr(v, "stype", "default") == "row_sparse"
                for vl in vlists for v in vl):
            # update_on_kvstore mode must run the server optimizer on the
            # real per-key slots, and sparse rows don't map onto flat
            # ranges — both take the reference per-key path
            results = []
            for k, vl in zip(skeys, vlists):
                self.push(k, vl, priority=priority)
                dst = vl[0]
                self.pull(k, out=dst, priority=priority)
                results.append(dst)
            return results
        # local cross-copy combine first (usually len-1 identity)
        for vl in vlists:
            if len(vl) > 1:
                _prof.bump("xla_program_calls")   # the local reduce
        local = [_ctx_group_sum(vl) for vl in vlists]
        layout = self._bucket_layout(skeys)
        for b in layout:
            flat = np.concatenate(
                [local[i].asnumpy().ravel() for i in b["idxs"]]) \
                if len(b["idxs"]) > 1 \
                else local[b["idxs"][0]].asnumpy().ravel()
            _prof.bump("kvstore_bucket_reduce")
            if _tel.enabled():
                _tel.bump("kvstore_reduce_bytes", int(flat.nbytes))
                _tel.observe("bucket_bytes", int(flat.nbytes))
            with _tel.span("kvstore_bucket_reduce", cat="kvstore",
                           args={"bytes": int(flat.nbytes),
                                 "keys": len(b["idxs"])}):
                self._trans.push(b["key"],
                                 flat.astype(b["dtype"], copy=False))
        results = [None] * len(skeys)
        for b in layout:
            flat = self._trans.pull(b["key"], (b["total"],))
            off = 0
            for i, n in zip(b["idxs"], b["sizes"]):
                k = skeys[i]
                val = flat[off:off + n].reshape(self._shapes[k])
                off += n
                _prof.bump("kvstore_pull")
                _prof.bump("xla_program_calls")   # host->device upload
                results[i] = nd.array(val, ctx=vlists[i][0].context,
                                      dtype=self._dtypes[k])
        if outs is not None:
            for r, o in zip(results, outs):
                for dst in (o if isinstance(o, (list, tuple)) else [o]):
                    if dst is not r:
                        _prof.bump("kvstore_pull")
                        _prof.bump("xla_program_calls")  # broadcast copy
                        dst._set_data(r.as_in_context(dst.context)._data)
        return results

    def reduce_scatter_all(self, keys, values, shardings, priority=0):
        """Dist reduce-scatter: the flat-bucket push+pull round of
        :meth:`push_pull_all` followed by the local scatter — each
        worker re-places the globally reduced value so only its owned
        rows stay resident for the sharded update."""
        results = self.push_pull_all(keys, values, priority=priority)
        _prof.bump("kvstore_reduce_scatter")
        vlists = [v if isinstance(v, (list, tuple)) else [v]
                  for v in values]
        return self._scatter(results, vlists, shardings)

    def set_optimizer(self, optimizer):
        """Ship the optimizer to the servers (reference kvstore.py:353:
        rank 0 pickles it; servers build an Updater)."""
        self._optimizer = optimizer
        if self.rank == 0:
            self._trans.set_optimizer(optimizer)
        self.barrier()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise MXNetError("Cannot save states for distributed training")

    def load_optimizer_states(self, fname):
        raise MXNetError("Cannot load states for distributed training")

    def get_checkpoint_state(self):
        """Snapshot every server's shard state (store + server-side
        updater) through the dist checkpoint-state protocol.

        Rank 0 gathers one opaque blob per server and returns the
        combined payload; other ranks return None (one copy in the
        checkpoint, the same division of labor as ``init``).  This is
        what makes a *restarted* server recoverable: the blob poured
        back via :meth:`set_checkpoint_state` restores its shard
        bitwise.
        """
        if self.rank != 0:
            return None
        states = [self._trans.server_state(s)
                  for s in range(self._trans.nservers)]
        return pickle.dumps({"version": 1, "kind": "dist_servers",
                             "nservers": len(states), "states": states},
                            protocol=pickle.HIGHEST_PROTOCOL)

    def set_checkpoint_state(self, blob):
        """Restore every server's shard state from a
        :meth:`get_checkpoint_state` blob (rank 0 performs the RPCs;
        other ranks pass blob=None and no-op).  Restoring clears the
        servers' sync-pending buffers — pair it with :meth:`reconnect`
        (all ranks) so worker push timestamps restart consistently."""
        if blob is None:
            return
        payload = pickle.loads(blob)
        if not isinstance(payload, dict) \
                or payload.get("kind") != "dist_servers":
            raise MXNetError("not a dist kvstore checkpoint-state blob")
        if len(payload["states"]) != self._trans.nservers:
            raise MXNetError(
                "checkpoint has %d server shards, transport has %d "
                "servers" % (len(payload["states"]),
                             self._trans.nservers))
        # a RESTARTED server has no updater yet: reinstall the optimizer
        # first or the poured-in state would silently degrade it to
        # overwrite semantics (set_optimizer is idempotent on survivors)
        if self._optimizer is not None:
            self._trans.set_optimizer(self._optimizer)
        for s, st in enumerate(payload["states"]):
            self._trans.restore_server_state(s, st)

    def reconnect(self, timeout=60.0):
        """Recover the transport after a :class:`~mxnet_tpu.dist_ps.
        PeerLost`: wait (bounded) for replacement servers to re-register
        with the scheduler, redial every server connection, and reset
        the push-timestamp counters.  EVERY worker must call this; rank
        0 then restores shard state via :meth:`set_checkpoint_state`
        before anyone pushes again."""
        self._trans.refresh_servers(timeout=timeout)
        self._trans.reset_timestamps()

    def peer_health(self):
        """The scheduler's live peer table (role/rank/heartbeat ages) —
        also cached for the introspection server's ``/peers`` view."""
        return self._trans.peer_health()

    def barrier(self):
        self._barrier_count += 1
        self._trans.barrier()

    def get_num_dead_node(self, node_id=0, timeout=60):
        """Workers whose link to the scheduler dropped without a clean
        finalize (ref kvstore.h:328)."""
        return self._trans.num_dead_nodes()

    def _finalize(self):
        t, self._trans = getattr(self, "_trans", None), None
        if t is not None:
            t.finalize()

    def __del__(self):
        pass


class KVStoreTPU(KVStore):
    """Mesh-collective kvstore: push records grad shards, pull materializes
    the psum'd result.  In-process it degenerates to local semantics; under
    pjit the push/pull pair lowers to one ``lax.psum`` over the mesh
    (see parallel/collectives.py for the in-step path)."""

    def __init__(self):
        super().__init__("tpu")


def create(name="local"):
    """Create a kvstore (reference kvstore.cc:34-60 factory semantics)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if name in ("local", "local_allreduce_cpu", "local_allreduce_device",
                "device"):
        return KVStore(name)
    if name == "tpu":
        return KVStoreTPU()
    if name.startswith("dist"):
        import os
        if "DMLC_ROLE" not in os.environ:
            # single-process run (no launcher): degrade to local semantics,
            # the same observable behavior as 1-worker dist
            return KVStore(name)
        return KVStoreDist(name)
    raise MXNetError("unknown kvstore type %s" % name)


def _updater_key(k):
    """Reference updaters key by int when possible (idx2name mapping)."""
    try:
        return int(k)
    except ValueError:
        return k
