"""KVStore: data-parallel parameter synchronization.

Parity surface: reference ``python/mxnet/kvstore.py`` + ``src/kvstore/``
(KVStoreLocal + Comm reduce/broadcast, kvstore_local.h:49-175, comm.h;
dist modes over ps-lite, kvstore_dist.h).

TPU-native redesign (SURVEY §2.5, §5.8): the parameter-server machinery is
replaced by collectives.  ``local``/``device`` keep reference semantics
in-process: ``push`` reduces a list of per-device arrays (the Comm::Reduce
tree-reduce becomes a jnp sum — XLA handles cross-device gathers), the
registered updater runs the optimizer, ``pull`` broadcasts.  ``dist_*``
modes map onto ``jax.distributed`` process groups where ``push+pull``
lowers to a psum across hosts (here: single-process rank 0 of 1 until
multi-host is attached; the *semantics* — aggregate-then-broadcast — are
identical and tested by the dist-invariant tests on one host).
"""
from __future__ import annotations

import pickle

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray
from . import optimizer as opt

__all__ = ["KVStore", "create"]


def _ctx_group_sum(vals):
    """Reduce a list of NDArrays (possibly on different devices)."""
    out = vals[0].asnumpy().copy() if len(vals) > 1 else None
    if out is None:
        return vals[0]
    for v in vals[1:]:
        out += v.asnumpy()
    return nd.array(out, ctx=vals[0].context, dtype=vals[0].dtype)


def _key_list(key, vals):
    if isinstance(key, (str, int)):
        return [key], [vals]
    assert len(key) == len(vals)
    return list(key), list(vals)


class KVStore:
    """Single-process kvstore with reference push/pull semantics."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store = {}          # key -> NDArray (the authoritative weight)
        self._updater = None
        self._optimizer = None
        self._barrier_count = 0

    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        import jax
        return getattr(jax, "process_index", lambda: 0)()

    @property
    def num_workers(self):
        import jax
        return getattr(jax, "process_count", lambda: 1)()

    def init(self, key, value):
        keys, vals = _key_list(key, value)
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):
                v = v[0]
            if str(k) in self._store:
                raise MXNetError("key %s already initialized" % k)
            self._store[str(k)] = v.copy()

    def push(self, key, value, priority=0):
        keys, vals = _key_list(key, value)
        for k, v in zip(keys, vals):
            k = str(k)
            if k not in self._store:
                raise MXNetError("key %s not initialized" % k)
            vlist = v if isinstance(v, (list, tuple)) else [v]
            reduced = _ctx_group_sum(list(vlist))
            if self._updater is not None:
                self._updater(_updater_key(k), reduced, self._store[k])
            else:
                self._store[k]._set_data(
                    reduced.as_in_context(self._store[k].context)._data)

    def pull(self, key, out=None, priority=0, row_ids=None,
             ignore_sparse=True):
        assert out is not None
        keys, outs = _key_list(key, out)
        for k, o in zip(keys, outs):
            k = str(k)
            if k not in self._store:
                raise MXNetError("key %s not initialized" % k)
            olist = o if isinstance(o, (list, tuple)) else [o]
            for dst in olist:
                self._store[k].copyto(dst)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (reference kvstore.py:227)."""
        assert out is not None and row_ids is not None
        keys, outs = _key_list(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        for k, o, rid in zip(keys, outs, row_ids):
            k = str(k)
            src = self._store[k]
            olist = o if isinstance(o, (list, tuple)) else [o]
            rows = rid.asnumpy().astype(np.int64)
            full = src.asnumpy()
            sparse = np.zeros_like(full)
            sparse[rows] = full[rows]
            for dst in olist:
                dst._set_data(nd.array(sparse, ctx=dst.context,
                                       dtype=dst.dtype)._data)
                dst._stype = "row_sparse"

    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Run optimizer 'on the server' (update_on_kvstore mode)."""
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        raise NotImplementedError(
            "gradient compression is not present in the reference revision")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states for distributed training"
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot load states for distributed training"
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())

    def barrier(self):
        self._barrier_count += 1

    def _send_command_to_servers(self, head, body):
        pass

    def __del__(self):
        pass


class KVStoreTPU(KVStore):
    """Mesh-collective kvstore: push records grad shards, pull materializes
    the psum'd result.  In-process it degenerates to local semantics; under
    pjit the push/pull pair lowers to one ``lax.psum`` over the mesh
    (see parallel/collectives.py for the in-step path)."""

    def __init__(self):
        super().__init__("tpu")


def create(name="local"):
    """Create a kvstore (reference kvstore.cc:34-60 factory semantics)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if name in ("local", "local_allreduce_cpu", "local_allreduce_device",
                "device"):
        return KVStore(name)
    if name == "tpu":
        return KVStoreTPU()
    if name.startswith("dist"):
        kv = KVStore(name)
        return kv
    raise MXNetError("unknown kvstore type %s" % name)


def _updater_key(k):
    """Reference updaters key by int when possible (idx2name mapping)."""
    try:
        return int(k)
    except ValueError:
        return k
