"""KVStore: data-parallel parameter synchronization.

Parity surface: reference ``python/mxnet/kvstore.py`` + ``src/kvstore/``
(KVStoreLocal + Comm reduce/broadcast, kvstore_local.h:49-175, comm.h;
dist modes over ps-lite, kvstore_dist.h).

TPU-native redesign (SURVEY §2.5, §5.8): the parameter-server machinery is
replaced by collectives.  ``local``/``device`` keep reference semantics
in-process: ``push`` reduces a list of per-device arrays (the Comm::Reduce
tree-reduce becomes a jnp sum — XLA handles cross-device gathers), the
registered updater runs the optimizer, ``pull`` broadcasts.  ``dist_*``
modes map onto ``jax.distributed`` process groups where ``push+pull``
lowers to a psum across hosts (here: single-process rank 0 of 1 until
multi-host is attached; the *semantics* — aggregate-then-broadcast — are
identical and tested by the dist-invariant tests on one host).
"""
from __future__ import annotations

import pickle

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray
from . import optimizer as opt

__all__ = ["KVStore", "create"]


import jax
import jax.numpy as jnp


@jax.jit
def _stack_sum(arrs):
    """One fused XLA reduction over the per-device contributions."""
    return jnp.sum(jnp.stack(arrs), axis=0)


def _ctx_group_sum(vals):
    """Reduce a list of NDArrays (possibly on different devices).

    Device path (reference ``CommDevice::Reduce``, comm.h:462-560): gather
    the shards onto the first array's device and run one jitted sum — no
    host round-trip.  XLA/PJRT handles the cross-device copies the way the
    reference used P2P + a merge buffer.
    """
    if len(vals) == 1:
        return vals[0]
    dev = next(iter(vals[0]._data.devices()))
    shards = [jax.device_put(v._data, dev) for v in vals]
    return NDArray(_stack_sum(shards), ctx=vals[0].context)


def _key_list(key, vals):
    if isinstance(key, (str, int)):
        return [key], [vals]
    assert len(key) == len(vals)
    return list(key), list(vals)


class KVStore:
    """Single-process kvstore with reference push/pull semantics."""

    def __init__(self, kind="local"):
        self._kind = kind
        self._store = {}          # key -> NDArray (the authoritative weight)
        self._updater = None
        self._optimizer = None
        self._barrier_count = 0

    @property
    def type(self):
        return self._kind

    @property
    def rank(self):
        import jax
        return getattr(jax, "process_index", lambda: 0)()

    @property
    def num_workers(self):
        import jax
        return getattr(jax, "process_count", lambda: 1)()

    def init(self, key, value):
        keys, vals = _key_list(key, value)
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):
                v = v[0]
            if str(k) in self._store:
                raise MXNetError("key %s already initialized" % k)
            self._store[str(k)] = v.copy()

    def push(self, key, value, priority=0):
        keys, vals = _key_list(key, value)
        for k, v in zip(keys, vals):
            k = str(k)
            if k not in self._store:
                raise MXNetError("key %s not initialized" % k)
            vlist = v if isinstance(v, (list, tuple)) else [v]
            reduced = _ctx_group_sum(list(vlist))
            if self._updater is not None:
                self._updater(_updater_key(k), reduced, self._store[k])
            else:
                self._store[k]._set_data(
                    reduced.as_in_context(self._store[k].context)._data)

    def pull(self, key, out=None, priority=0, row_ids=None,
             ignore_sparse=True):
        assert out is not None
        keys, outs = _key_list(key, out)
        for k, o in zip(keys, outs):
            k = str(k)
            if k not in self._store:
                raise MXNetError("key %s not initialized" % k)
            olist = o if isinstance(o, (list, tuple)) else [o]
            for dst in olist:
                self._store[k].copyto(dst)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        """Pull only the rows in row_ids (reference kvstore.py:227)."""
        assert out is not None and row_ids is not None
        keys, outs = _key_list(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        for k, o, rid in zip(keys, outs, row_ids):
            k = str(k)
            src = self._store[k]
            olist = o if isinstance(o, (list, tuple)) else [o]
            # device-side gather/scatter (SURVEY §7 index+values design):
            # no host round trip of the full parameter
            import jax.numpy as jnp
            rows = jnp.asarray(rid._data).astype(jnp.int64)
            full = src._data
            picked = jnp.take(full, rows, axis=0)
            sparse = jnp.zeros_like(full).at[rows].set(picked)
            for dst in olist:
                placed = jax.device_put(sparse.astype(dst.dtype),
                                        dst.context.jax_device)
                dst._set_data(placed)
                dst._stype = "row_sparse"
                if hasattr(dst, "_seed_sparse"):
                    dst._seed_sparse(rows, jnp.take(placed, rows, axis=0))

    def set_updater(self, updater):
        self._updater = updater

    _set_updater = set_updater

    def set_optimizer(self, optimizer):
        """Run optimizer 'on the server' (update_on_kvstore mode)."""
        self._optimizer = optimizer
        self._updater = opt.get_updater(optimizer)

    def set_gradient_compression(self, compression_params):
        raise NotImplementedError(
            "gradient compression is not present in the reference revision")

    def save_optimizer_states(self, fname, dump_optimizer=False):
        assert self._updater is not None, "Cannot save states for distributed training"
        with open(fname, "wb") as fout:
            fout.write(self._updater.get_states(dump_optimizer))

    def load_optimizer_states(self, fname):
        assert self._updater is not None, "Cannot load states for distributed training"
        with open(fname, "rb") as fin:
            self._updater.set_states(fin.read())

    def barrier(self):
        self._barrier_count += 1

    def get_num_dead_node(self, node_id=0, timeout=60):
        """Dead-node count (ref kvstore.h:328); single-process stores have
        no failure surface — always 0."""
        return 0

    def _send_command_to_servers(self, head, body):
        pass

    def __del__(self):
        pass


class KVStoreDist(KVStore):
    """Multi-process distributed kvstore over the dist_ps transport.

    Reference counterpart: ``src/kvstore/kvstore_dist.h`` (worker) +
    ``kvstore_dist_server.h`` (server).  Semantics preserved:

    - ``dist_sync``: a push blocks until every worker's contribution for
      that (key, timestamp) is aggregated on the server and the update
      applied — so pull-after-push observes the globally updated value.
    - ``dist_async``: the server applies each worker's push immediately.
    - ``set_optimizer`` pickles the optimizer to the servers
      (update_on_kvstore mode); with no server optimizer the servers
      store the aggregated gradient for workers to pull and apply locally.
    - Big arrays are range-sharded across all servers
      (MXNET_KVSTORE_BIGARRAY_BOUND).

    In a process whose ``DMLC_ROLE`` is ``scheduler`` or ``server``,
    constructing the store runs that role's loop and exits — the launcher
    runs the same user script in every role, like the reference tracker.
    """

    def __init__(self, kind="dist_sync"):
        super().__init__(kind)
        import sys
        from . import dist_ps
        r = dist_ps.role()
        if r == "scheduler":
            dist_ps.run_scheduler()
            sys.exit(0)
        if r == "server":
            dist_ps.run_server()
            sys.exit(0)
        self._trans = dist_ps.WorkerTransport()
        self._shapes = {}
        self._dtypes = {}
        if "async" in kind and self._trans.rank == 0:
            self._trans.set_sync(False)
        # all workers rendezvous here so no push can reach a server that
        # has not yet seen rank 0's set_sync
        self._trans.barrier()
        import atexit
        atexit.register(self._finalize)

    @property
    def rank(self):
        return self._trans.rank

    @property
    def num_workers(self):
        from . import dist_ps
        return dist_ps.num_workers()

    def init(self, key, value):
        keys, vals = _key_list(key, value)
        for k, v in zip(keys, vals):
            if isinstance(v, (list, tuple)):
                v = v[0]
            k = str(k)
            self._shapes[k] = v.shape
            self._dtypes[k] = v.dtype
            if self.rank == 0:
                self._trans.init(k, v.asnumpy())
        self.barrier()

    def _is_sharded(self, k):
        from . import dist_ps
        return len(dist_ps.placement(k, self._shapes[k],
                                     self._trans.nservers)) > 1

    def push(self, key, value, priority=0):
        keys, vals = _key_list(key, value)
        for k, v in zip(keys, vals):
            k = str(k)
            if k not in self._shapes:
                raise MXNetError("key %s not initialized" % k)
            vlist = v if isinstance(v, (list, tuple)) else [v]
            reduced = _ctx_group_sum(list(vlist))
            sparse = getattr(reduced, "stype", "default") == "row_sparse"
            if sparse and not self._is_sharded(k):
                dense = reduced.asnumpy()
                rows = np.nonzero(np.any(dense != 0, axis=tuple(
                    range(1, dense.ndim))))[0]
                self._trans.push(k, dense[rows], rows=rows)
            else:
                # dense keys, and row_sparse keys big enough to be
                # range-sharded across servers (row blocks don't map onto
                # flat ranges — ship the dense aggregate instead)
                self._trans.push(k, reduced.asnumpy())

    def pull(self, key, out=None, priority=0, row_ids=None,
             ignore_sparse=True):
        assert out is not None
        keys, outs = _key_list(key, out)
        for k, o in zip(keys, outs):
            k = str(k)
            olist = o if isinstance(o, (list, tuple)) else [o]
            val = self._trans.pull(k, self._shapes.get(k, olist[0].shape))
            for dst in olist:
                dst._set_data(nd.array(val, ctx=dst.context,
                                       dtype=dst.dtype)._data)

    def row_sparse_pull(self, key, out=None, priority=0, row_ids=None):
        assert out is not None and row_ids is not None
        keys, outs = _key_list(key, out)
        if isinstance(row_ids, NDArray):
            row_ids = [row_ids] * len(keys)
        for k, o, rid in zip(keys, outs, row_ids):
            k = str(k)
            olist = o if isinstance(o, (list, tuple)) else [o]
            rows = rid.asnumpy().astype(np.int64)
            shape = self._shapes[k]
            if self._is_sharded(k):
                block = self._trans.pull(k, shape)[rows]
            else:
                block = self._trans.pull_rows(k, shape, rows)
            sparse = np.zeros(shape, self._dtypes[k])
            sparse[rows] = block
            for dst in olist:
                dst._set_data(nd.array(sparse, ctx=dst.context,
                                       dtype=dst.dtype)._data)
                dst._stype = "row_sparse"

    def set_optimizer(self, optimizer):
        """Ship the optimizer to the servers (reference kvstore.py:353:
        rank 0 pickles it; servers build an Updater)."""
        self._optimizer = optimizer
        if self.rank == 0:
            self._trans.set_optimizer(optimizer)
        self.barrier()

    def save_optimizer_states(self, fname, dump_optimizer=False):
        raise MXNetError("Cannot save states for distributed training")

    def load_optimizer_states(self, fname):
        raise MXNetError("Cannot load states for distributed training")

    def barrier(self):
        self._barrier_count += 1
        self._trans.barrier()

    def get_num_dead_node(self, node_id=0, timeout=60):
        """Workers whose link to the scheduler dropped without a clean
        finalize (ref kvstore.h:328)."""
        return self._trans.num_dead_nodes()

    def _finalize(self):
        t, self._trans = getattr(self, "_trans", None), None
        if t is not None:
            t.finalize()

    def __del__(self):
        pass


class KVStoreTPU(KVStore):
    """Mesh-collective kvstore: push records grad shards, pull materializes
    the psum'd result.  In-process it degenerates to local semantics; under
    pjit the push/pull pair lowers to one ``lax.psum`` over the mesh
    (see parallel/collectives.py for the in-step path)."""

    def __init__(self):
        super().__init__("tpu")


def create(name="local"):
    """Create a kvstore (reference kvstore.cc:34-60 factory semantics)."""
    if not isinstance(name, str):
        raise TypeError("name must be a string")
    if name in ("local", "local_allreduce_cpu", "local_allreduce_device",
                "device"):
        return KVStore(name)
    if name == "tpu":
        return KVStoreTPU()
    if name.startswith("dist"):
        import os
        if "DMLC_ROLE" not in os.environ:
            # single-process run (no launcher): degrade to local semantics,
            # the same observable behavior as 1-worker dist
            return KVStore(name)
        return KVStoreDist(name)
    raise MXNetError("unknown kvstore type %s" % name)


def _updater_key(k):
    """Reference updaters key by int when possible (idx2name mapping)."""
    try:
        return int(k)
    except ValueError:
        return k
