"""mxnet_tpu.guardian: in-program NaN/Inf detection, dynamic loss
scaling, and auto-rollback to the last-good checkpoint.

The robustness capstone over the checkpoint (PR 7) and chaos (PR 8)
tiers: PRs 7–8 keep the job *up*; the guardian keeps it *correct*.

    mgr = checkpoint.CheckpointManager(dir, trainer=trainer, data_iter=it,
                                       every_steps=50)
    guard = guardian.TrainingGuardian(manager=mgr)   # installs itself
    for batch in loader:
        with autograd.record():
            loss = loss_fn(net(batch.data), batch.label)
            scaled = guard.scale_loss(loss)          # records + scales
        scaled.backward()
        trainer.step(batch_size)                     # verdict in-program
        if guard.last_step_skipped():
            ...                                      # optionally retry

See :mod:`.core` for the state machine (detect → skip → rescale →
roll back), :mod:`.health` for the shared on-device finiteness/norm
math, and docs/GUARDIAN.md for the recovery matrix.  The live view is
``GET /guardian`` on the introspection server.
"""
from __future__ import annotations

from . import health                                  # noqa: F401
from .core import (TrainingGuardian, current, install, uninstall,  # noqa: F401
                   enabled, refresh_from_env)
from .health import all_finite, global_norm, verdict_program  # noqa: F401
from .health import tracecheck_programs               # noqa: F401

__all__ = ["TrainingGuardian", "current", "install", "uninstall",
           "enabled", "refresh_from_env", "all_finite", "global_norm",
           "verdict_program", "tracecheck_programs", "http_view"]


def http_view():
    """The ``/guardian`` introspection payload: the installed guardian's
    description, or an inactive stub."""
    guard = current()
    if guard is None:
        return {"active": False, "env_enabled": enabled()}
    view = guard.describe()
    view["active"] = True
    return view
