"""Pure on-device health math: the finiteness verdict + global norm.

One tiny vocabulary shared by every consumer of "are these tensors
numerically sane?":

* the fused trainer step folds :func:`all_finite` over the gradient
  buckets (plus the recorded loss) INTO its donated program — the
  verdict is one extra ``reduce_and`` in a program that already exists,
  not a second XLA launch and not a host callback;
* the ``MXNET_FUSED_TRAINER=0`` per-slot oracle computes the identical
  verdict through :func:`verdict_program` (one small watched jit) so the
  two paths skip the exact same steps;
* ``gluon.utils.clip_global_norm`` reuses :func:`all_finite` /
  :func:`global_norm` instead of growing its own ``isfinite`` pass.

Everything here is trace-safe and 32-bit-clean: bool reductions and f32
accumulation only, so graftcheck's JX102 (dtype widening) and JX103
(host callback) stay at zero findings over the guarded programs.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .. import telemetry as _tel

__all__ = ["all_finite", "global_norm", "verdict_program",
           "tracecheck_programs"]


def all_finite(leaves):
    """ONE boolean scalar: every element of every leaf is finite.

    Integer leaves are vacuously finite (``jnp.isfinite`` returns an
    all-True array for them), so mixed pytrees need no special casing.
    """
    flags = [jnp.all(jnp.isfinite(leaf)) for leaf in leaves]
    return functools.reduce(jnp.logical_and, flags)


def global_norm(leaves):
    """The 2-norm over the concatenation of *leaves*, accumulated in f32
    (never f64 — the programs this runs inside are 32-bit; widening
    would trip JX102 and double HBM traffic on TPU).

    The cast happens BEFORE the reduction: an f16 vdot saturates at
    65504, reporting inf for perfectly finite half-precision gradients —
    which a clipper would then "fix" by scaling them all to zero.
    """
    def _sq(leaf):
        flat = leaf.ravel().astype(jnp.float32)
        return jnp.vdot(flat, flat)
    total = functools.reduce(jnp.add, [_sq(leaf) for leaf in leaves])
    return jnp.sqrt(total)


def _verdict(leaves):
    return all_finite(leaves)


# one watched jit for the whole process: jax keys its own cache on the
# leaves' shapes/dtypes, so every model shares this single entry point
_VERDICT_JIT = None


def verdict_program():
    """The per-slot oracle's finiteness program (lazy, process-wide)."""
    global _VERDICT_JIT
    if _VERDICT_JIT is None:
        _VERDICT_JIT = _tel.watch_jit(jax.jit(_verdict),
                                      "guardian_verdict")
    return _VERDICT_JIT


def tracecheck_programs():
    """AOT specimens for graftcheck: the oracle-path verdict program over
    a mixed two-leaf layout plus a loss scalar (exactly what
    ``Trainer._loop_step`` feeds it)."""
    import numpy as np
    leaves = [jnp.zeros((32, 16), jnp.float32),
              jnp.zeros((32,), jnp.float32),
              jnp.asarray(np.float32(0.0))]
    return [("guardian_verdict", verdict_program(), (leaves,), {})]
