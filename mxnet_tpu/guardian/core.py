"""The training guardian: detect → skip → rescale → roll back.

PRs 7–8 keep the *job* alive through preemption and dead peers; this
module keeps the job *correct* when the numbers go bad.  One
:class:`TrainingGuardian` instance per run watches every
``Trainer.step``:

1. **Detect.**  The fused trainer step computes an all-grads-finite
   scalar (plus the finiteness of the loss the loop recorded via
   :meth:`TrainingGuardian.scale_loss` / :meth:`observe_loss`) inside
   its own donated program — no extra XLA launch, no host callback.
2. **Skip.**  On a nonfinite verdict the update is suppressed
   *in-program* (``jnp.where`` keeps the donated buffers at their old
   values), the per-slot update counts are rolled back host-side, and
   the step boundary is NOT notified — a poisoned batch costs one
   skipped step, never a poisoned checkpoint.
3. **Rescale.**  ``MXNET_GUARDIAN_LOSS_SCALE=dynamic`` maintains a
   power-of-two loss scale: halve on overflow, double after
   ``growth_interval`` clean steps.  ``Trainer.step`` folds the inverse
   into ``rescale_grad`` (a traced scalar — scale changes never
   retrace), so bf16/f16 training self-heals.
4. **Roll back.**  An EWMA loss-spike detector flags divergence, and a
   consecutive-skip budget (``MXNET_GUARDIAN_MAX_SKIPS``), when
   exhausted, restores the ``last_good``-pinned checkpoint
   (:meth:`CheckpointManager.pin_last_good` — retention never evicts
   it), then advances the data iterator past the quarantined batch
   window so the run does not replay its own failure.

Off path: ``current()`` is one module-global read; with no guardian
installed nothing else runs.  ``MXNET_GUARDIAN=1`` auto-installs a
default instance at import (subprocess tests / zero-code adoption);
programs construct :class:`TrainingGuardian` directly to wire in a
checkpoint manager and data iterator.
"""
from __future__ import annotations

import math
import os
import threading

import numpy as np

from .. import telemetry as _tel
from ..lint import lockwitness as _lockwitness
from ..telemetry import flight as _flight

__all__ = ["TrainingGuardian", "current", "install", "uninstall",
           "enabled", "refresh_from_env"]

_TRUTHY = ("1", "true", "on", "yes")

# EWMA spike detector internals (deliberately not env knobs: the factor
# is the contract, the smoothing is an implementation detail)
_EWMA_BETA = 0.9
_EWMA_WARMUP = 10

_DEFAULT_DYNAMIC_SCALE = float(2 ** 16)
_MIN_SCALE = 1.0
_MAX_SCALE = float(2 ** 24)


def _env_truthy(name, default="0"):
    return os.environ.get(name, default).strip().lower() in _TRUTHY


def _env_float(name, default):
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return float(default)


def _env_int(name, default):
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return int(default)


def _env_loss_scale():
    """MXNET_GUARDIAN_LOSS_SCALE: 'dynamic' | <float> | '0'/unset = off."""
    raw = os.environ.get("MXNET_GUARDIAN_LOSS_SCALE", "0").strip().lower()
    if raw == "dynamic":
        return "dynamic"
    try:
        val = float(raw)
    except ValueError:
        return None
    return val if val > 0 else None


class TrainingGuardian:
    """One guardian per run; constructing it installs it process-wide
    (latest wins, like ``checkpoint.hooks``).  Call :meth:`close` when
    the run is over so later Trainers stop consulting it.

    *manager* (optional ``CheckpointManager``) enables auto-rollback and
    last-good pinning; *data_iter* (optional, defaults to the manager's)
    is the stream quarantined after a rollback.
    """

    def __init__(self, manager=None, data_iter=None, loss_scale=None,
                 growth_interval=None, max_skips=None, spike_factor=None):
        self._manager = manager
        self._data_iter = data_iter
        spec = loss_scale if loss_scale is not None else _env_loss_scale()
        if spec == "dynamic":
            self._dynamic = True
            self._scale = _DEFAULT_DYNAMIC_SCALE
        elif spec:
            self._dynamic = False
            self._scale = float(spec)
        else:
            self._dynamic = False
            self._scale = 1.0
        self._scaling = bool(spec)
        self._growth_interval = max(1, int(
            growth_interval if growth_interval is not None
            else _env_int("MXNET_GUARDIAN_GROWTH_INTERVAL", 2000)))
        self._max_skips = max(1, int(
            max_skips if max_skips is not None
            else _env_int("MXNET_GUARDIAN_MAX_SKIPS", 3)))
        self._spike_factor = float(
            spike_factor if spike_factor is not None
            else _env_float("MXNET_GUARDIAN_SPIKE_FACTOR", 10.0))

        self._lock = _lockwitness.make_lock("TrainingGuardian._lock")
        self._pending_loss = None      # raw scalar for the NEXT verdict
        self._last_loss = None         # host float for EWMA/description
        self._consec_skips = 0
        self._clean_streak = 0
        self._ewma = None
        self._warm = 0
        self._last_action = None       # "applied" | "skipped" | "rollback"
        self._last_rollback = None     # (from_step, to_step, quarantined)
        install(self)

    # -- lifecycle ---------------------------------------------------------

    def close(self):
        """Detach from the process hot path (Trainer.step stops seeing
        this guardian); the instance stays inspectable."""
        uninstall(self)

    # -- loss scaling ------------------------------------------------------

    @property
    def loss_scale(self):
        """The current loss scale (1.0 when scaling is off)."""
        return self._scale if self._scaling else 1.0

    def scale_loss(self, loss):
        """Record *loss* for this step's verdict/EWMA and return it
        multiplied by the current loss scale (the tensor to call
        ``backward()`` on).  With scaling off the loss passes through
        unchanged but is still recorded."""
        self.observe_loss(loss)
        if not self._scaling or self._scale == 1.0:
            return loss
        return loss * self._scale

    def observe_loss(self, loss):
        """Record *loss* (NDArray or raw array) for the next step's
        in-program finiteness check and the EWMA spike detector.  The
        raw array is handed to the step program as-is — its reduction
        happens INSIDE the existing program (no extra XLA launch); keep
        the loss shape stable across steps (a fixed batch size) or the
        changed input shape retraces the step once."""
        self._pending_loss = getattr(loss, "_data", loss)
        return loss

    def apply_rescale(self, rescale):
        """Fold the inverse loss scale into the optimizer's
        ``rescale_grad`` (a traced scalar: no retrace).  Power-of-two
        scales make scaled training bitwise-identical to unscaled."""
        if not self._scaling:
            return rescale
        return rescale / self._scale

    # -- the step verdict (called by the trainer paths) --------------------

    def take_loss_raw(self):
        """The recorded loss scalar for this step (raw jax array), or
        None; clears the pending slot so a stale loss never leaks into a
        later step's verdict."""
        raw, self._pending_loss = self._pending_loss, None
        if raw is not None:
            # keep a handle for the EWMA read in after_step (the float
            # conversion happens there, after the step program is in
            # flight, so it adds no extra sync point)
            self._last_loss = raw
        return raw

    def grads_finite(self, raw_grads, loss_raw=None):
        """The MXNET_FUSED_TRAINER=0 oracle's verdict: one small watched
        program over the gradient leaves (+ the loss scalar), identical
        in truth value to the fused program's folded check."""
        from . import health
        leaves = list(raw_grads)
        if loss_raw is not None:
            leaves.append(loss_raw)
        _tel.bump("xla_program_calls")     # the oracle's one extra program
        return bool(np.asarray(health.verdict_program()(leaves)))

    def after_step(self, finite):
        """Book one step's verdict: counters, scale update, spike
        detection, last-good pinning, and — on an exhausted skip
        budget — the automatic rollback.  Returns True iff the step was
        skipped (the caller must then NOT notify the step boundary)."""
        with self._lock:
            # the rollback path drains the checkpoint writer queue under
            # the guardian lock on purpose: rollback is a stop-the-world
            # recovery and verdicts racing past it would score against a
            # state about to be discarded
            return self._after_step_locked(bool(finite))  # graftlint: disable=JG010

    def _after_step_locked(self, finite):
        _tel.bump("guardian_checks")
        loss_val = self._take_last_loss_float()
        if not finite:
            self._last_action = "skipped"
            self._consec_skips += 1
            _tel.bump("guardian_skipped_steps")
            if self._dynamic:
                new = max(self._scale / 2.0, _MIN_SCALE)
                if new != self._scale:
                    self._scale = new
                    _tel.bump("guardian_scale_cuts")
                self._clean_streak = 0
            _flight.record("guardian", "skip",
                           consecutive=self._consec_skips,
                           loss_scale=self.loss_scale)
            if self._consec_skips >= self._max_skips:
                if self._rollback():
                    self._last_action = "rollback"
                    self._consec_skips = 0
            self._set_gauges()
            return True

        self._last_action = "applied"
        self._consec_skips = 0
        spiked = self._note_loss(loss_val)
        if self._dynamic:
            self._clean_streak += 1
            if self._clean_streak >= self._growth_interval:
                new = min(self._scale * 2.0, _MAX_SCALE)
                if new != self._scale:
                    self._scale = new
                    _tel.bump("guardian_scale_growths")
                self._clean_streak = 0
        if not spiked:
            self._pin_last_good()
        self._set_gauges()
        return False

    def _take_last_loss_float(self):
        raw, self._last_loss = self._last_loss, None
        if raw is None:
            # a direct after_step() without a trainer path in between
            # (tests, custom loops): consume the recorded loss here
            raw, self._pending_loss = self._pending_loss, None
        if raw is None:
            return None
        try:
            # host-side numpy sum over the (tiny) per-sample loss vector:
            # a transfer, not an XLA program
            return float(np.asarray(raw).sum())
        except Exception:
            return None

    def _note_loss(self, loss_val):
        """EWMA spike detection on an APPLIED step's loss.  A spike
        books a counter + flight event and blocks last-good pinning for
        this step; it never suppresses the already-applied update."""
        if loss_val is None or not math.isfinite(loss_val):
            return False
        if self._spike_factor <= 0:
            self._fold_ewma(loss_val)
            return False
        baseline = self._ewma
        if baseline is not None and self._warm >= _EWMA_WARMUP \
                and abs(loss_val) > self._spike_factor \
                * max(abs(baseline), 1e-12):
            _tel.bump("guardian_loss_spikes")
            _flight.record("guardian", "loss-spike", loss=loss_val,
                           ewma=baseline, factor=self._spike_factor)
            return True        # a spike does not feed the baseline
        self._fold_ewma(loss_val)
        return False

    def _fold_ewma(self, loss_val):
        self._ewma = loss_val if self._ewma is None \
            else _EWMA_BETA * self._ewma + (1.0 - _EWMA_BETA) * loss_val
        self._warm += 1

    def _pin_last_good(self):
        mgr = self._manager
        if mgr is None:
            return
        last = mgr.last_committed_step
        if last is not None and last != mgr.last_good_step:
            mgr.pin_last_good(last)

    # -- rollback ----------------------------------------------------------

    def _rollback(self):
        """Restore the last-good checkpoint and quarantine the batch
        window.  Called with the skip budget exhausted, mid-step (the
        boundary for the failing step will never fire, so the manager's
        step counter lands exactly on the restored step)."""
        mgr = self._manager
        if mgr is None:
            _flight.record("guardian", "budget-exhausted-no-manager",
                           skips=self._consec_skips)
            return False
        target = mgr.last_good_step
        if target is None:
            # nothing was ever verified healthy: restoring the NEWEST
            # checkpoint would load exactly the unverified state this
            # rollback is fleeing — keep skipping instead
            _flight.record("guardian", "rollback-no-last-good",
                           skips=self._consec_skips)
            return False
        fail_step = mgr.step + 1          # the step being skipped now
        restored = mgr.restore(step=target)
        if restored is None:
            _flight.record("guardian", "rollback-failed",
                           pinned=mgr.last_good_step)
            return False
        # quarantine: every batch consumed since the restored step plus
        # the failing window itself.  Over-skipping by up to the budget
        # (when the loop retried one batch in place) only drops data;
        # UNDER-skipping would replay the failure.
        # evict the abandoned timeline: checkpoints newer than the
        # restored step are unverified (possibly poisoned) state — left
        # on disk, a preemption right after this rollback would resume
        # from them newest-first and replay the failure
        mgr.discard_newer_than(restored)
        if mgr.last_good_step != restored:
            # a corrupt pin fell back to an older checkpoint: re-anchor
            # the pin on the state we actually (verifiably) loaded
            mgr.pin_last_good(restored)
        quarantined = max(0, fail_step - restored) + self._consec_skips
        it = self._data_iter if self._data_iter is not None \
            else getattr(mgr, "_data_iter", None)
        skipped = 0
        if it is not None and quarantined:
            skip = getattr(it, "skip_batches", None)
            if skip is not None:
                skipped = skip(quarantined)
        _tel.bump("guardian_rollbacks")
        _flight.record("guardian", "rollback", from_step=fail_step,
                       to_step=restored, quarantined=skipped)
        self._last_rollback = (fail_step, restored, skipped)
        self._ewma, self._warm = None, 0   # restored weights: re-warm
        self._clean_streak = 0
        return True

    # -- introspection -----------------------------------------------------

    def last_action(self):
        """'applied' | 'skipped' | 'rollback' | None (before any step)."""
        return self._last_action

    def last_step_skipped(self):
        """True when the most recent step's update was suppressed (the
        retrying-loop contract: redo the same batch, don't fetch)."""
        return self._last_action in ("skipped", "rollback")

    def _set_gauges(self):
        _tel.set_gauge("guardian_loss_scale", self.loss_scale)
        _tel.set_gauge("guardian_consecutive_skips", self._consec_skips)
        if self._ewma is not None:
            _tel.set_gauge("guardian_loss_ewma", self._ewma)

    def describe(self):
        """JSON-shaped view for the ``/guardian`` endpoint."""
        mgr = self._manager
        return {
            "loss_scale": self.loss_scale,
            "dynamic": self._dynamic and self._scaling,
            "scaling": self._scaling,
            "growth_interval": self._growth_interval,
            "max_skips": self._max_skips,
            "spike_factor": self._spike_factor,
            "consecutive_skips": self._consec_skips,
            "clean_streak": self._clean_streak,
            "loss_ewma": self._ewma,
            "last_action": self._last_action,
            "last_rollback": self._last_rollback,
            "last_good_step": None if mgr is None else mgr.last_good_step,
            "has_manager": mgr is not None,
            "counters": {name: _tel.counter(name) for name in
                         ("guardian_checks", "guardian_skipped_steps",
                          "guardian_loss_spikes", "guardian_rollbacks",
                          "guardian_scale_cuts",
                          "guardian_scale_growths")},
        }


# ---------------------------------------------------------------------------
# process-wide installation (the hot-path gate is one global read)
# ---------------------------------------------------------------------------

_CURRENT = None
_ENV_INSTALLED = None    # the instance refresh_from_env auto-installed


def current():
    """The installed guardian, or None — the Trainer hot paths' one and
    only check."""
    return _CURRENT


def install(guardian):
    """Make *guardian* the process guardian (latest wins)."""
    global _CURRENT
    _CURRENT = guardian
    return guardian


def uninstall(guardian):
    """Remove *guardian* if it is still the installed one."""
    global _CURRENT
    if _CURRENT is guardian:
        _CURRENT = None


def enabled():
    """Whether MXNET_GUARDIAN asked for an auto-installed guardian."""
    return _env_truthy("MXNET_GUARDIAN")


def refresh_from_env():
    """Re-read MXNET_GUARDIAN* (import-time default; tests/late config):
    installs a default guardian when enabled and none is installed,
    removes an auto-installed default when disabled (a programmatically
    constructed guardian is never touched)."""
    global _ENV_INSTALLED
    if enabled():
        if _CURRENT is None:
            _ENV_INSTALLED = TrainingGuardian()   # constructor installs
    elif _ENV_INSTALLED is not None:
        uninstall(_ENV_INSTALLED)
        _ENV_INSTALLED = None
    return _CURRENT


refresh_from_env()
