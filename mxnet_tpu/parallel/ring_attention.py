"""Ring attention: exact attention over sequence-sharded inputs.

The reference (2017) predates attention entirely — its long-sequence story
was bucketing (SURVEY §5.7).  The TPU build makes long-context first-class:
the sequence axis is sharded over a mesh axis, each device holds a local
block of Q/K/V, and K/V blocks rotate around the ring via ``lax.ppermute``
while an online-softmax accumulator (flash-attention numerics) combines
partial results.  Communication overlaps compute and rides ICI; memory per
device is O(S/n · S/n) per block instead of O(S²).

Use inside :func:`mesh.shard_map` over a mesh with the sequence axis bound
to ``axis_name``.  ``local_attention`` is the single-device exact reference
(also the per-block kernel).  ``ring_attention_sharded`` is the standalone
entry point: a watched jitted program per (mesh, axis, flags) — graftcheck
proves it via this module's ``tracecheck_programs`` provider.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax

from . import mesh as mesh_mod

__all__ = ["ring_attention", "local_attention", "ring_attention_sharded"]

_NEG = -1e30  # large-negative mask; avoids -inf NaN edge cases in exp


def local_attention(q, k, v, causal=False, sm_scale=None,
                    q_offset=0, k_offset=0):
    """Exact softmax attention on local blocks.

    q: [B, H, Sq, D]; k, v: [B, H, Sk, D].  ``q_offset``/``k_offset`` are
    the absolute sequence positions of the first row of each block (used
    for causal masking when blocks are shards of a longer sequence).
    """
    d = q.shape[-1]
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if causal:
        # int32 positions: sequence indices never exceed 2**31 and the
        # default int64 iota drags x64-widened compares into every
        # sharded step program (the JX102 finding)
        q_pos = q_offset + jnp.arange(q.shape[2], dtype=jnp.int32)
        k_pos = k_offset + jnp.arange(k.shape[2], dtype=jnp.int32)
        mask = q_pos[:, None] >= k_pos[None, :]
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


def ring_attention(q, k, v, axis_name, causal=False, sm_scale=None):
    """Ring attention over a sequence-sharded mesh axis.

    q, k, v: local shards [B, H, S_local, D]; the global sequence length is
    S_local * axis_size.  Must be called inside ``shard_map`` (or pmap) with
    ``axis_name`` bound.  Returns the local output shard [B, H, S_local, D].

    Algorithm: N = axis_size steps; at step t each device holds the K/V
    block that originated on device (idx - t) mod N, computes its partial
    attention with online-softmax rescaling, then rotates K/V to the next
    device (ppermute).  Exact — matches ``local_attention`` on the gathered
    sequence to float tolerance.
    """
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)
    b, h, s_loc, d = q.shape
    scale = sm_scale if sm_scale is not None else 1.0 / math.sqrt(d)
    # int32 indices throughout: under jax_enable_x64 a bare arange is
    # int64 and would widen the whole program (JX102)
    q_pos = idx * s_loc + jnp.arange(s_loc, dtype=jnp.int32)

    # scan carries must be device-varying over every mesh axis the inputs
    # vary on (not just the ring axis), or the carry types won't match;
    # on jax without varying-axis types these are identity shims
    vary_axes = mesh_mod.vma_axes(q, k, v, extra=(axis_name,))

    def _vary(x):
        return mesh_mod.pvary(x, vary_axes)
    acc = _vary(jnp.zeros((b, h, s_loc, d), dtype=jnp.float32))
    m = _vary(jnp.full((b, h, s_loc), _NEG, dtype=jnp.float32))
    l = _vary(jnp.zeros((b, h, s_loc), dtype=jnp.float32))

    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        acc, m, l, kb, vb = carry
        src = (idx - t) % n                      # origin shard of this block
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       kb.astype(jnp.float32)) * scale
        if causal:
            k_pos = src * s_loc + jnp.arange(s_loc, dtype=jnp.int32)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask, s, _NEG)
        blk_max = jnp.max(s, axis=-1)
        new_m = jnp.maximum(m, blk_max)
        corr = jnp.exp(m - new_m)
        p = jnp.exp(s - new_m[..., None])
        if causal:
            p = jnp.where(mask, p, 0.0)          # kill fully-masked rows
        new_l = l * corr + jnp.sum(p, axis=-1)
        new_acc = acc * corr[..., None] + jnp.einsum(
            "bhqk,bhkd->bhqd", p, vb.astype(jnp.float32))
        kb = lax.ppermute(kb, axis_name, perm)
        vb = lax.ppermute(vb, axis_name, perm)
        return (new_acc, new_m, new_l, kb, vb), None

    (acc, m, l, _, _), _ = lax.scan(step, (acc, m, l, k, v),
                                    jnp.arange(n, dtype=jnp.int32))
    out = acc / jnp.maximum(l, 1e-30)[..., None]
    return out.astype(q.dtype)


# one watched program per (mesh, axis, causal, sm_scale): a stable
# program identity is what makes the retrace watchdog and cost
# accounting meaningful (a fresh shard_map per call would recompile —
# and re-register — every step)
_SHARDED_PROGRAMS = {}


def _ring_program(mesh, axis_name, causal, sm_scale):
    key = (mesh, axis_name, causal, sm_scale)
    prog = _SHARDED_PROGRAMS.get(key)
    if prog is None:
        spec = mesh_mod.filter_spec(
            jax.sharding.PartitionSpec(None, None, axis_name, None), mesh)
        fn = mesh_mod.shard_map(
            functools.partial(ring_attention, axis_name=axis_name,
                              causal=causal, sm_scale=sm_scale),
            mesh=mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check=False)
        prog = mesh_mod.jit_sharded(fn, "ring_attention")
        _SHARDED_PROGRAMS[key] = prog
    return prog


def ring_attention_sharded(q, k, v, mesh, axis_name="seq", causal=False,
                           sm_scale=None):
    """Standalone entry point: the watched jitted shard_map ring over
    ``mesh``.

    q, k, v: global arrays [B, H, S, D]; the sequence dim is sharded over
    ``axis_name``, everything else replicated.
    """
    return _ring_program(mesh, axis_name, causal, sm_scale)(q, k, v)


def tracecheck_programs():
    """graftcheck provider: the sharded ring program over the live mesh."""
    mesh = mesh_mod.auto_mesh(("seq",))
    prog = _ring_program(mesh, "seq", True, None)
    s = 4 * mesh.shape["seq"]
    q = jax.ShapeDtypeStruct((2, 2, s, 8), jnp.float32)
    return [("ring_attention", prog, (q, q, q), {},
             {"mesh_axes": ("seq",)})]
