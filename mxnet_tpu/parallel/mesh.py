"""Device-mesh construction and management — THE sharding substrate.

The reference expresses multi-device placement as a context list handed to
``Module``/``DataParallelExecutorGroup`` (reference ``module/module.py:39``,
``executor_group.py:233``).  TPU-native, placement is a ``jax.sharding.Mesh``
with named axes; data parallelism shards the batch over ``"data"``, tensor
parallelism shards weights over ``"model"``, sequence parallelism shards the
sequence over ``"seq"``.  Collectives ride ICI within a slice and DCN across
slices — axis order puts the fastest-varying (innermost) axis on the
best-connected devices.

This module is the single owner of three things every SPMD consumer
(models, pipeline, ring attention, ZeRO placement, fused executor group)
used to carry privately:

1. **Mesh construction** — local single-host meshes (:func:`make_mesh`,
   :func:`auto_mesh`) and the multi-host topology where the
   jax.distributed process fleet is a first-class leading axis
   (:func:`multihost_mesh`); ``MXNET_MESH_SHAPE`` /
   ``MXNET_MESH_SPAN_HOSTS`` select a fleet-wide default without code
   changes (:func:`mesh_from_env`).
2. **Sharding helpers** — :func:`filter_spec` (one model definition runs
   on dp-only, dp+tp, or dp+tp+sp meshes), :func:`named_sharding`,
   :func:`replicated`, and :func:`shard_put` (multi-process-safe
   placement: each process materializes only its addressable shards).
3. **Program entry points** — :func:`shard_map`, a version-adaptive
   wrapper over jax's drifting shard_map surface (``jax.shard_map`` +
   ``check_vma`` on current jax, ``jax.experimental.shard_map`` +
   ``check_rep`` on older releases), plus the :func:`pvary` /
   :func:`vma_axes` capability shims its callers need; and
   :func:`jit_sharded`, ``jax.jit`` + ``watch_jit`` in one call so every
   SPMD program lands in the telemetry retrace watchdog, cost accounting
   and ``MXNET_DEVICE_TIME`` attribution from day one.

No other module in the tree may call ``shard_map`` directly — graftcheck's
coverage gate and tests/test_mesh.py enforce the single-substrate rule.
"""
from __future__ import annotations

import contextlib
import os
import threading

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

__all__ = ["make_mesh", "auto_mesh", "factor_devices", "current_mesh",
           "using_mesh", "shard_map", "pvary", "vma_axes", "filter_spec",
           "named_sharding", "replicated", "shard_put", "jit_sharded",
           "multihost_mesh", "mesh_from_env", "default_mesh", "topology",
           "refresh_from_env"]

_tls = threading.local()


def factor_devices(n, num_axes):
    """Factor ``n`` devices into ``num_axes`` near-balanced mesh dims.

    Largest factors go first (outermost); e.g. 8 devices, 3 axes →
    (2, 2, 2); 8 devices, 2 axes → (4, 2); 6, 2 → (3, 2).
    """
    dims = []
    remaining = n
    for i in range(num_axes - 1, 0, -1):
        # greedily peel the smallest factor > 1 for the innermost axes
        target = max(2, int(round(remaining ** (1.0 / (i + 1)))))
        f = 1
        for cand in range(target, 1, -1):
            if remaining % cand == 0:
                f = cand
                break
        if f == 1:
            for cand in range(target + 1, remaining + 1):
                if remaining % cand == 0:
                    f = cand
                    break
        dims.append(f)
        remaining //= f
    dims.append(remaining)
    return tuple(sorted(dims, reverse=True))


def make_mesh(axis_shapes, devices=None):
    """Create a ``Mesh`` from ``{axis_name: size}`` (insertion-ordered).

    ``-1`` for at most one axis means "all remaining devices".
    """
    if devices is None:
        devices = jax.devices()
    names = list(axis_shapes.keys())
    sizes = list(axis_shapes.values())
    n = len(devices)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError(
                "cannot infer -1 axis: %d devices not divisible by %d"
                % (n, known))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total > n:
        raise ValueError("mesh %s needs %d devices, only %d available"
                         % (dict(zip(names, sizes)), total, n))
    dev_array = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def auto_mesh(axis_names=("data",), n_devices=None, devices=None):
    """Mesh over all (or ``n_devices``) devices, balanced across axes."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    dims = factor_devices(len(devices), len(axis_names))
    return make_mesh(dict(zip(axis_names, dims)), devices)


def current_mesh():
    """The innermost active mesh (from ``using_mesh``), or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def using_mesh(mesh):
    """Activate ``mesh`` for the enclosed scope (and as jax's global mesh)."""
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    _tls.stack.append(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        _tls.stack.pop()


# --------------------------------------------------------------------------
# Multi-host topology: the jax.distributed fleet as a first-class axis
# --------------------------------------------------------------------------

def multihost_mesh(axis_shapes=None, host_axis="host", devices=None,
                   n_hosts=None):
    """A mesh spanning every jax.distributed process, with the process
    fleet as the leading ``host_axis`` and ``axis_shapes`` (default
    ``{"data": -1}``) laid over each host's devices.

    This is the dist_ps worker fleet become a mesh dimension: collectives
    over ``host_axis`` ride DCN between processes, the inner axes ride
    ICI within each host.  ``devices``/``n_hosts`` are injectable so a
    faked multi-host topology (one process, N virtual hosts) is testable
    on CPU — production callers pass neither and get the live
    ``jax.devices()`` / ``jax.process_count()`` fleet.
    """
    if devices is None:
        devices = jax.devices()
    if n_hosts is None:
        n_hosts = jax.process_count()
    n_hosts = max(1, int(n_hosts))
    if len(devices) % n_hosts:
        raise ValueError(
            "multihost mesh: %d devices not divisible by %d hosts"
            % (len(devices), n_hosts))
    shapes = {host_axis: n_hosts}
    for name, size in (axis_shapes or {"data": -1}).items():
        if name == host_axis:
            raise ValueError("axis %r collides with host axis" % name)
        shapes[name] = size
    return make_mesh(shapes, devices)


def topology():
    """One JSON-shaped dict describing the device fleet this process can
    build meshes over (the MULTICHIP dryrun and docs/SPMD.md contract)."""
    devices = jax.devices()
    return {
        "n_devices": len(devices),
        "n_local_devices": len(jax.local_devices()),
        "n_hosts": jax.process_count(),
        "process_index": jax.process_index(),
        "platform": devices[0].platform if devices else None,
    }


# --------------------------------------------------------------------------
# Env-selected default mesh (MXNET_MESH_* knobs)
# --------------------------------------------------------------------------

def _parse_mesh_shape(text):
    """``"data=-1,model=2"`` → {"data": -1, "model": 2} (ordered)."""
    shapes = {}
    for part in text.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                "MXNET_MESH_SHAPE entry %r is not name=size" % part)
        name, _, size = part.partition("=")
        shapes[name.strip()] = int(size)
    if not shapes:
        raise ValueError("MXNET_MESH_SHAPE set but empty")
    return shapes


def _env_mesh_config():
    shape = os.environ.get("MXNET_MESH_SHAPE", "").strip()
    span = os.environ.get("MXNET_MESH_SPAN_HOSTS", "0").strip()
    return (_parse_mesh_shape(shape) if shape else None,
            span not in ("", "0", "false", "False"))


# cached at import (the JG006 pattern); refresh_from_env re-reads
_ENV_SHAPE, _ENV_SPAN_HOSTS = _env_mesh_config()


def refresh_from_env():
    """Re-read MXNET_MESH_SHAPE / MXNET_MESH_SPAN_HOSTS (tests / late
    configuration)."""
    global _ENV_SHAPE, _ENV_SPAN_HOSTS
    _ENV_SHAPE, _ENV_SPAN_HOSTS = _env_mesh_config()


def mesh_from_env(devices=None):
    """The fleet-selected mesh, or None when ``MXNET_MESH_SHAPE`` is
    unset.  ``MXNET_MESH_SHAPE="data=-1,model=2"`` names the axes and
    sizes (one ``-1`` = all remaining devices);
    ``MXNET_MESH_SPAN_HOSTS=1`` prepends the jax.distributed process
    fleet as a leading ``host`` axis (:func:`multihost_mesh`)."""
    if _ENV_SHAPE is None:
        return None
    if _ENV_SPAN_HOSTS:
        return multihost_mesh(_ENV_SHAPE, devices=devices)
    return make_mesh(_ENV_SHAPE, devices=devices)


def default_mesh(axis_names=("data",)):
    """The mesh an SPMD consumer should use when none was passed: the
    innermost ``using_mesh``, else the ``MXNET_MESH_*`` env selection,
    else all devices balanced over ``axis_names``."""
    mesh = current_mesh()
    if mesh is not None:
        return mesh
    mesh = mesh_from_env()
    if mesh is not None:
        return mesh
    return auto_mesh(axis_names)


# --------------------------------------------------------------------------
# Sharding helpers
# --------------------------------------------------------------------------

def filter_spec(spec, mesh):
    """Drop axis names the mesh doesn't have (lets one model definition
    run on dp-only, dp+tp, or dp+tp+sp meshes)."""
    if mesh is None:
        return spec
    names = mesh.axis_names
    return P(*[a if a in names else None for a in spec])


def named_sharding(mesh, spec):
    """``NamedSharding(mesh, filter_spec(spec, mesh))`` — the one spelling
    of "this spec, on this mesh, minus axes the mesh lacks"."""
    return NamedSharding(mesh, filter_spec(spec, mesh))


def replicated(mesh):
    """Fully replicated NamedSharding on ``mesh``."""
    return NamedSharding(mesh, P())


def shard_put(value, sharding, spec=None):
    """Place a host value under *sharding*, working in multi-process SPMD
    too: each process materializes only its addressable shards
    (jax.make_array_from_callback), so the same call serves one host or a
    jax.distributed fleet.  ``sharding`` may be a Mesh when ``spec`` is
    given."""
    if isinstance(sharding, Mesh):
        sharding = named_sharding(sharding, P() if spec is None else spec)
    if jax.process_count() == 1:
        return jax.device_put(value, sharding)
    host = np.asarray(value)
    return jax.make_array_from_callback(host.shape, sharding,
                                        lambda idx: host[idx])


# --------------------------------------------------------------------------
# Program entry points: shard_map (version-adaptive) and watched jit
# --------------------------------------------------------------------------

def _resolve_shard_map():
    fn = getattr(jax, "shard_map", None)
    if fn is not None:
        return fn, "check_vma"          # current jax: top-level API
    from jax.experimental.shard_map import shard_map as _sm
    return _sm, "check_rep"             # older jax: experimental API


_SHARD_MAP, _CHECK_KW = _resolve_shard_map()


def shard_map(fn, mesh=None, in_specs=None, out_specs=None, check=None):
    """Map ``fn`` over mesh shards with explicit collectives — the ONE
    shard_map entry point in the tree.

    jax renamed both the callable (``jax.experimental.shard_map`` →
    ``jax.shard_map``) and the replication-check kwarg (``check_rep`` →
    ``check_vma``) across releases; this wrapper presents one stable
    surface (``check=False`` disables the replication/varying-manual-axes
    checker on either API).  ``mesh`` defaults to the innermost
    :func:`using_mesh` scope.
    """
    if mesh is None:
        mesh = current_mesh()
        if mesh is None:
            raise ValueError(
                "shard_map: no mesh passed and no using_mesh() scope "
                "active")
    kwargs = {}
    if check is not None:
        kwargs[_CHECK_KW] = check
    return _SHARD_MAP(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)


def vma_axes(*arrays, extra=()):
    """The union of mesh axes ``arrays`` are device-varying over, plus
    ``extra`` — the axes a shard_map scan carry must be cast to.  On jax
    without the varying-manual-axes type system (no ``jax.typeof``) the
    answer is just ``extra``: the old ``check_rep`` tracker needs no
    explicit casts."""
    axes = set(extra)
    typeof = getattr(jax, "typeof", None)
    if typeof is not None:
        for a in arrays:
            axes |= set(getattr(typeof(a), "vma", ()) or ())
    return tuple(sorted(axes))


def pvary(x, axes):
    """Cast ``x`` to be device-varying over ``axes`` inside shard_map.
    Identity on jax versions whose shard_map has no varying-axis types
    (their replication checker infers it, or ``check=False`` skips it)."""
    if not axes:
        return x
    pcast = getattr(jax.lax, "pcast", None)
    if pcast is not None:
        return pcast(x, tuple(axes), to="varying")
    pv = getattr(jax.lax, "pvary", None)
    if pv is not None:
        return pv(x, tuple(axes))
    return x


def jit_sharded(fn, name, **jit_kwargs):
    """``watch_jit(jax.jit(fn, **jit_kwargs), name)`` — every SPMD
    program the framework owns goes through here so it lands in the
    retrace watchdog, XLA cost accounting, MXNET_DEVICE_TIME attribution
    and the MXNET_TRACECHECK hook with one line."""
    from .. import telemetry as _tel
    return _tel.watch_jit(jax.jit(fn, **jit_kwargs), name)
