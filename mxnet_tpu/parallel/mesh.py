"""Device-mesh construction and management.

The reference expresses multi-device placement as a context list handed to
``Module``/``DataParallelExecutorGroup`` (reference ``module/module.py:39``,
``executor_group.py:233``).  TPU-native, placement is a ``jax.sharding.Mesh``
with named axes; data parallelism shards the batch over ``"data"``, tensor
parallelism shards weights over ``"model"``, sequence parallelism shards the
sequence over ``"seq"``.  Collectives ride ICI within a slice and DCN across
slices — axis order puts the fastest-varying (innermost) axis on the
best-connected devices.
"""
from __future__ import annotations

import contextlib
import threading

import numpy as np
import jax
from jax.sharding import Mesh

__all__ = ["make_mesh", "auto_mesh", "factor_devices", "current_mesh",
           "using_mesh"]

_tls = threading.local()


def factor_devices(n, num_axes):
    """Factor ``n`` devices into ``num_axes`` near-balanced mesh dims.

    Largest factors go first (outermost); e.g. 8 devices, 3 axes →
    (2, 2, 2); 8 devices, 2 axes → (4, 2); 6, 2 → (3, 2).
    """
    dims = []
    remaining = n
    for i in range(num_axes - 1, 0, -1):
        # greedily peel the smallest factor > 1 for the innermost axes
        target = max(2, int(round(remaining ** (1.0 / (i + 1)))))
        f = 1
        for cand in range(target, 1, -1):
            if remaining % cand == 0:
                f = cand
                break
        if f == 1:
            for cand in range(target + 1, remaining + 1):
                if remaining % cand == 0:
                    f = cand
                    break
        dims.append(f)
        remaining //= f
    dims.append(remaining)
    return tuple(sorted(dims, reverse=True))


def make_mesh(axis_shapes, devices=None):
    """Create a ``Mesh`` from ``{axis_name: size}`` (insertion-ordered).

    ``-1`` for at most one axis means "all remaining devices".
    """
    if devices is None:
        devices = jax.devices()
    names = list(axis_shapes.keys())
    sizes = list(axis_shapes.values())
    n = len(devices)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        if n % known:
            raise ValueError(
                "cannot infer -1 axis: %d devices not divisible by %d"
                % (n, known))
        sizes[sizes.index(-1)] = n // known
    total = int(np.prod(sizes))
    if total > n:
        raise ValueError("mesh %s needs %d devices, only %d available"
                         % (dict(zip(names, sizes)), total, n))
    dev_array = np.asarray(devices[:total]).reshape(sizes)
    return Mesh(dev_array, tuple(names))


def auto_mesh(axis_names=("data",), n_devices=None, devices=None):
    """Mesh over all (or ``n_devices``) devices, balanced across axes."""
    if devices is None:
        devices = jax.devices()
    if n_devices is not None:
        devices = devices[:n_devices]
    dims = factor_devices(len(devices), len(axis_names))
    return make_mesh(dict(zip(axis_names, dims)), devices)


def current_mesh():
    """The innermost active mesh (from ``using_mesh``), or None."""
    stack = getattr(_tls, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def using_mesh(mesh):
    """Activate ``mesh`` for the enclosed scope (and as jax's global mesh)."""
    if not hasattr(_tls, "stack"):
        _tls.stack = []
    _tls.stack.append(mesh)
    try:
        with mesh:
            yield mesh
    finally:
        _tls.stack.pop()
