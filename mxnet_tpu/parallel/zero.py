"""ZeRO-1 cross-replica weight-update sharding: the shared core.

"Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (PAPERS.md arxiv 2004.13336) observes that in data-parallel
training every replica redundantly computes the identical weight update
and redundantly holds the identical optimizer state.  The fix is pure
placement: shard the update computation and its state across replicas,
reduce-scatter the gradient in, all-gather the updated weight out — the
numbers are bit-identical, only *where* they are computed changes.

This module is the one implementation of that placement, consumed by
three sites that each used to carry a bespoke copy:

* ``gluon.fused_trainer`` — the production path: ``MXNET_ZERO=1`` runs
  the whole-model fused optimizer program with per-replica state shards
  (see docs/ZERO.md).
* ``parallel.sharded.ShardedTrainer(shard_weight_update=True)`` — the
  SPMD trainer's in-step update.
* ``models.transformer.make_train_step_zero1`` — the MULTICHIP dryrun
  flagship.

The unit of sharding is the leading axis of each weight-shaped array
(the XLA-friendly choice from the paper: the SPMD partitioner turns the
constraints into reduce-scatter / 1-of-N update / all-gather with no
manual collectives).  Slot→checkpoint-shard assignment stays the
round-robin ``checkpoint/reshard.py`` layout — a sharded state leaf is
written from its per-device rows without ever being gathered on device.
"""
from __future__ import annotations

import numpy as np
import jax
from jax.sharding import NamedSharding, PartitionSpec as P

__all__ = ["zero1_update_spec", "zero1_axis_mesh", "update_sharding",
           "sharded_update", "shard_state_tree_spec", "state_bytes"]


def zero1_update_spec(shape, current_spec, ndata, batch_axis="data"):
    """The ZeRO-1 (arXiv:2004.13336) update PartitionSpec for a weight,
    or None when it must fall back to the replicated update: the param
    must currently be replicated (no TP sharding), the data axis must
    have >1 replica, and the leading dim must divide evenly."""
    replicated = all(s is None for s in tuple(current_spec or ()))
    if replicated and shape and ndata > 1 and shape[0] % ndata == 0:
        return P(*((batch_axis,) + (None,) * (len(shape) - 1)))
    return None


def zero1_axis_mesh(n_shards, axis="zero", devices=None):
    """A 1-D mesh of the first *n_shards* local devices — the replica
    axis the fused Trainer's sharded update lives on."""
    from . import mesh as mesh_mod
    if devices is None:
        devices = jax.local_devices()
    n = max(1, min(int(n_shards), len(devices)))
    return mesh_mod.make_mesh({axis: n}, devices[:n])


def update_sharding(mesh, shape, axis, current_spec=None):
    """NamedSharding for one weight's sharded update on *mesh*, or None
    for the replicated fallback (TP-sharded weight, indivisible leading
    dim, a scalar, or a mesh without the replica axis at all)."""
    spec = zero1_update_spec(shape, current_spec,
                             mesh.shape.get(axis, 1), axis)
    if spec is None:
        return None
    return NamedSharding(mesh, spec)


def shard_state_tree_spec(state_leaf_shape, weight_shape, upd_sharding,
                          replicated):
    """Placement for one optimizer-state leaf: weight-shaped leaves ride
    the weight's update sharding; scalar/odd-shaped schedule state (e.g.
    Nadam's mu product) stays replicated."""
    if upd_sharding is not None \
            and tuple(state_leaf_shape) == tuple(weight_shape):
        return upd_sharding
    return replicated


def sharded_update(update_fn, p, g, state, hyper, upd_sharding,
                   param_sharding):
    """One weight's update with ZeRO-1 placement constraints.

    ``update_fn(p, g, state, hyper) -> (new_p, new_state)`` is the pure
    optimizer core (``Optimizer.update_step`` or an inline formula).
    With ``upd_sharding`` set, the gradient and weight are constrained to
    the update sharding (the reduce-scatter point — each replica keeps
    only its 1/N of the rows), the update runs on the shard, weight-
    shaped state leaves are pinned to the shard, and the new weight is
    constrained back to ``param_sharding`` (the all-gather).  With
    ``upd_sharding=None`` the update is untouched (replicated fallback).
    Numerically exact either way: elementwise update math on a row slice
    produces the same bits as on the full array.
    """
    if upd_sharding is None:
        return update_fn(p, g, state, hyper)
    wsc = jax.lax.with_sharding_constraint
    wshape = tuple(p.shape)
    g = wsc(g, upd_sharding)                       # reduce-scatter point
    p_sh = wsc(p, upd_sharding)
    new_p, new_state = update_fn(p_sh, g, state, hyper)
    new_state = jax.tree_util.tree_map(
        lambda x: wsc(x, upd_sharding)
        if tuple(x.shape) == wshape else x, new_state)
    if param_sharding is not None:
        new_p = wsc(new_p, param_sharding)         # all-gather back
    return new_p, new_state


def state_bytes(leaves, n_shards):
    """(per_device_bytes, replicated_bytes) for a list of (leaf_shape,
    leaf_dtype, is_sharded) descriptors — the ``zero_optimizer_bytes_*``
    gauge arithmetic, shared by the trainer and ``tools/zero_bench.py``.
    """
    per_dev = total = 0
    n = max(1, int(n_shards))
    for shape, dtype, sharded in leaves:
        nbytes = int(np.prod(shape, dtype=np.int64)) * np.dtype(dtype).itemsize
        total += nbytes
        per_dev += nbytes // n if sharded else nbytes
    return per_dev, total
