"""Parallelism: device meshes, collectives, sharded training, ring attention.

This package is the TPU-native replacement for the reference's entire
distribution stack (SURVEY §2.5, §5.8):

- ``mesh``        — THE sharding substrate: `jax.sharding.Mesh`
                    construction (local + multi-host topology), sharding
                    helpers, and the one ``shard_map``/``jit_sharded``
                    program entry point; replaces context lists +
                    `DataParallelExecutorGroup` device slicing
                    (reference ``module/executor_group.py:233-258``).
- ``sharded``     — one jitted SPMD train step over a mesh with
                    data/tensor-parallel shardings; replaces per-device
                    executor groups + kvstore push/pull
                    (reference ``model.py:105-140``).
- ``collective``  — the communication surface: named in-program
                    collectives (psum/all_gather/reduce_scatter/ppermute)
                    over ICI/DCN replacing ps-lite + Comm (reference
                    ``src/kvstore/comm.h``, ``kvstore_dist.h``), plus
                    chunked device-side redistribution (pipelined
                    all-gather / reduce-scatter per arXiv 2112.01075)
                    shared by kvstore buckets, the ZeRO-1 weight
                    all-gather, and elastic checkpoint restore.
- ``ring_attention`` — sequence/context parallelism via ppermute rings
                    (beyond the reference, which only had bucketing;
                    SURVEY §5.7).
- ``multihost``   — jax.distributed bring-up from the launcher env
                    contract; replaces the dmlc tracker rendezvous
                    (reference ``tools/launch.py:22-30``).
"""
from .mesh import (make_mesh, auto_mesh, factor_devices, current_mesh,
                   using_mesh, shard_map, named_sharding, filter_spec,
                   replicated, shard_put, jit_sharded, multihost_mesh,
                   mesh_from_env, default_mesh, topology)
from .collective import (psum, pmean, pmax, all_gather, reduce_scatter,
                         ppermute_shift, all_to_all, axis_index, axis_size,
                         barrier, host_allreduce)
from .sharded import (ShardedTrainer, block_pure_fn, sharded_data,
                      zero1_update_spec)
from .ring_attention import (ring_attention, local_attention,
                             ring_attention_sharded)
from .pipeline import pipeline_apply
from . import collective
from . import multihost
from .multihost import init_from_env

__all__ = [
    "make_mesh", "auto_mesh", "factor_devices", "current_mesh", "using_mesh",
    "shard_map", "named_sharding", "filter_spec", "replicated", "shard_put",
    "jit_sharded", "multihost_mesh", "mesh_from_env", "default_mesh",
    "topology",
    "psum", "pmean", "pmax", "all_gather", "reduce_scatter", "ppermute_shift",
    "all_to_all", "axis_index", "axis_size", "barrier", "host_allreduce",
    "ShardedTrainer", "block_pure_fn", "sharded_data", "zero1_update_spec",
    "ring_attention", "local_attention", "ring_attention_sharded",
    "pipeline_apply",
    "collective", "multihost", "init_from_env",
]
