"""Pipeline parallelism: GPipe-style microbatch streaming over a mesh
axis.

Beyond-reference capability (the reference's closest feature is
PartialForward, a debug tool — SURVEY §2.5 marks PP absent): layers are
grouped into S stages, the stage dimension is sharded over the ``pipe``
mesh axis (one stage's parameters per device), and microbatches stream
through the stages with ``lax.ppermute`` hops. The schedule is the
classic GPipe fill-drain loop: ``M + S - 1`` ticks for M microbatches,
each device computing its stage on whatever activation sits in its slot.
Implemented with the substrate's ``shard_map`` so the collective is
explicit and the whole schedule stays inside one jitted program;
differentiable end to end (``ppermute`` has a transpose rule), so
``jax.grad`` of a pipelined loss trains all stages.

Each (stage_fn, mesh, schedule) pair compiles to ONE watched jitted
program (``pipeline_apply``) — stable identity for the retrace watchdog,
cost accounting, and graftcheck's ledger; calling it under an outer
``jax.jit`` trace simply inlines it.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import PartitionSpec as P

from . import mesh as mesh_mod
from .collective import ppermute_shift

__all__ = ["pipeline_apply"]


def _build_spmd(stage_fn, n_stages, n_microbatches, axis):
    def spmd(params_local, micro_all):
        # params_local: this stage's leaves with leading dim 1
        params_here = jax.tree_util.tree_map(lambda p: p[0], params_local)
        stage_id = lax.axis_index(axis)
        n_ticks = n_microbatches + n_stages - 1

        state = jnp.zeros_like(micro_all[0])       # activation in my slot
        outs = jnp.zeros_like(micro_all)

        def tick(carry, t):
            state, outs = carry
            # stage 0 ingests microbatch t (when one remains)
            feed = micro_all[jnp.minimum(t, n_microbatches - 1)]
            state = jnp.where(stage_id == 0,
                              jnp.where(t < n_microbatches, feed, state),
                              state)
            y = stage_fn(params_here, state)
            # last stage banks its finished microbatch (t - (S-1))
            out_idx = t - (n_stages - 1)
            valid = (stage_id == n_stages - 1) & (out_idx >= 0)
            outs = lax.cond(
                valid,
                lambda o: lax.dynamic_update_index_in_dim(
                    o, y, jnp.maximum(out_idx, 0), 0),
                lambda o: o, outs)
            # shift activations one stage forward (ring; stage 0's
            # incoming value is ignored — overwritten by the next feed)
            y = ppermute_shift(y, axis)
            return (y, outs), None

        # int32 tick counter: under jax_enable_x64 a bare arange is int64
        # and would widen the whole program (JX102)
        (state, outs), _ = lax.scan(tick, (state, outs),
                                    jnp.arange(n_ticks, dtype=jnp.int32))
        # only the last stage's `outs` is real; broadcast it to every
        # shard so the out_spec can be replicated
        outs = lax.psum(
            jnp.where(stage_id == n_stages - 1, outs,
                      jnp.zeros_like(outs)), axis)
        return outs

    return spmd


# (stage_fn, mesh, params treedef, M, axis) -> watched jitted program
_PROGRAMS = {}


def _pipeline_program(stage_fn, stage_params, mesh, n_microbatches, axis):
    treedef = jax.tree_util.tree_structure(stage_params)
    key = (stage_fn, mesh, treedef, n_microbatches, axis)
    prog = _PROGRAMS.get(key)
    if prog is None:
        spec_params = jax.tree_util.tree_map(lambda _: P(axis),
                                             stage_params)
        spmd = _build_spmd(stage_fn, mesh.shape[axis], n_microbatches,
                           axis)
        fn = mesh_mod.shard_map(
            spmd, mesh=mesh,
            in_specs=(spec_params, P()), out_specs=P(),
            check=False)
        prog = mesh_mod.jit_sharded(fn, "pipeline_apply")
        _PROGRAMS[key] = prog
    return prog


def pipeline_apply(stage_fn, stage_params, x, mesh, n_microbatches,
                   axis="pipe"):
    """Apply S pipeline stages to ``x`` with microbatch streaming.

    Parameters
    ----------
    stage_fn : callable(params_slice, activation) -> activation; the
        per-stage computation. ``params_slice`` is one stage's leaves
        (leading stage dim removed); activations keep one shape across
        stages.
    stage_params : pytree whose leaves have a leading stage dim of size
        S == mesh.shape[axis] (stack per-stage params with
        ``jnp.stack``).
    x : [B, ...] batch; B must divide by ``n_microbatches``.
    mesh : jax.sharding.Mesh containing ``axis``.
    n_microbatches : GPipe M; ≥ S keeps the bubble fraction at
        (S-1)/(M+S-1).

    Returns the full-batch output, numerically identical to applying
    the stages sequentially.
    """
    b = x.shape[0]
    assert b % n_microbatches == 0, "batch must divide into microbatches"
    mb = b // n_microbatches
    micro = x.reshape((n_microbatches, mb) + x.shape[1:])
    prog = _pipeline_program(stage_fn, stage_params, mesh, n_microbatches,
                             axis)
    outs = prog(stage_params, micro)
    return outs.reshape((b,) + outs.shape[2:])


def _tracecheck_stage(params, h):
    return jnp.tanh(h @ params["w"] + params["b"])


def tracecheck_programs():
    """graftcheck provider: one representative GPipe program (S = pipe
    axis size of the live mesh, M = 2·S microbatches)."""
    mesh = mesh_mod.auto_mesh(("pipe",))
    s = mesh.shape["pipe"]
    m = 2 * s
    stage_params = {
        "w": jnp.zeros((s, 8, 8), jnp.float32),
        "b": jnp.zeros((s, 8), jnp.float32),
    }
    prog = _pipeline_program(_tracecheck_stage, stage_params, mesh, m,
                             "pipe")
    micro = jax.ShapeDtypeStruct((m, 4, 8), jnp.float32)
    return [("pipeline_apply", prog, (stage_params, micro), {},
             {"mesh_axes": ("pipe",)})]
