"""Named collectives over the device mesh.

This module replaces the reference's entire communication backend
(SURVEY §5.8): ps-lite ZPush/ZPull RPC (``src/kvstore/kvstore_dist.h:253-313``)
and the Comm reduce/broadcast trees (``src/kvstore/comm.h:90-560``) become
XLA collectives compiled into the program — riding ICI within a slice and
DCN across slices, with no parameter-server round-trip.

Two levels:
- *in-program* wrappers (``psum`` …) used inside ``shard_map``/``pjit``-traced
  code, thin over ``jax.lax`` so user code reads like the scaling-book recipe;
- *host-level* helpers (``host_allreduce``, ``barrier``) used by the KVStore
  facade and multi-host setup.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["psum", "pmean", "pmax", "all_gather", "reduce_scatter",
           "ppermute_shift", "all_to_all", "axis_index", "axis_size",
           "barrier", "host_allreduce"]


def psum(x, axis_name):
    """All-reduce sum over a mesh axis (replaces Comm::Reduce+Broadcast)."""
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    return lax.pmean(x, axis_name)


def pmax(x, axis_name):
    return lax.pmax(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    """Gather shards along ``axis`` from every device on the mesh axis."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    """Sum-reduce then scatter shards along ``axis`` (psum_scatter)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ppermute_shift(x, axis_name, shift=1):
    """Rotate shards around the ring by ``shift`` (the ring-attention and
    pipeline primitive). Positive shift sends to the next-higher index."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis):
    """All-to-all (the Ulysses/DeepSpeed sequence-parallel primitive)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    return lax.psum(1, axis_name)


def barrier(name="barrier"):
    """Cross-host barrier (reference ``KVStore::Barrier``, kvstore.h:339).

    Single-process: no-op.  Multi-host: sync over all global devices.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


def host_allreduce(arrays):
    """Sum a list of per-device host arrays into one (kvstore local reduce).

    The reference staged through pinned CPU memory with an OMP tree-reduce
    (comm.h:301-436); here the arrays are summed by one fused XLA program
    on the first array's device.
    """
    if len(arrays) == 1:
        return arrays[0]
    out = arrays[0]
    for a in arrays[1:]:
        out = out + jax.device_put(a, out.devices().pop())
    return out


def _tree_psum(tree, axis_name):
    return jax.tree_util.tree_map(lambda x: lax.psum(x, axis_name), tree)
