"""Sharded SPMD training: one jitted train step over a device mesh.

This replaces the reference's entire data-parallel path — the per-device
executor fan-out (``module/executor_group.py:233-430``), the kvstore grad
reduce (``kvstore_local.h:149-175``, ``comm.h:90-560``) and the per-device
optimizer replay (``model.py:105-140``, ``gluon/trainer.py:148-192``) —
with ONE XLA program: forward + loss + backward + optimizer update compiled
together, batch sharded over the ``data`` mesh axis, gradients all-reduced
by XLA-inserted collectives over ICI, weights updated in place via buffer
donation.  Tensor parallelism falls out of the same machinery: give
``param_rules`` regex → ``PartitionSpec`` and XLA partitions the matmuls.

``block_pure_fn`` extracts the pure ``(params, aux, inputs) -> outputs``
function from any Gluon block by the same handle-swap the CachedOp tracer
uses — so the whole Gluon layer zoo is shardable unchanged.
"""
from __future__ import annotations

import math
import re

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from .. import autograd
from .. import random as _random
from ..ndarray.ndarray import NDArray, _wrap
from . import mesh as mesh_mod
from .mesh import auto_mesh
from .zero import sharded_update, zero1_update_spec

__all__ = ["ShardedTrainer", "block_pure_fn", "sharded_data",
           "zero1_update_spec"]


def _deactivate_hybrid(block, saved=None):
    """Temporarily force eager dispatch so tracing sees the op graph."""
    if saved is None:
        saved = []
    if hasattr(block, "_active"):
        saved.append((block, block._active))
        block._active = False
    for c in getattr(block, "_children", []):
        _deactivate_hybrid(c, saved)
    return saved


def block_pure_fn(block):
    """Extract a pure function from a Gluon block.

    Returns ``(fn, grad_names, aux_names)`` where
    ``fn(params: dict, aux: dict, inputs: tuple, key, train) ->
    (outputs: tuple, new_aux: dict)`` is traceable by jax (the same
    handle-swap trick as the CachedOp jit path; reference analogue:
    ``src/imperative/cached_op.cc:25-135`` graph extraction).
    """
    pd = {p.name: p for p in block.collect_params().values()}
    grad_names = [n for n, p in pd.items() if p.grad_req != "null"]
    aux_names = [n for n, p in pd.items() if p.grad_req == "null"]

    def fn(params, aux, inputs, key, train):
        saved_data = {}
        for name, v in list(params.items()) + list(aux.items()):
            p = pd[name]
            saved_data[name] = p._data
            p._data = _wrap(v)
        saved_active = _deactivate_hybrid(block)
        try:
            with autograd.pause(train_mode=train), _random.key_scope(key):
                ins = [_wrap(v) for v in inputs]
                out = block(*ins)
                if not isinstance(out, (list, tuple)):
                    out = [out]
                out_vals = tuple(o._data for o in out)
                new_aux = {n: pd[n]._data._data for n in aux_names}
        finally:
            for name, old in saved_data.items():
                pd[name]._data = old
            for b, a in saved_active:
                b._active = a
        return out_vals, new_aux

    return fn, grad_names, aux_names


def _state_get(state):
    """Optimizer state (None | NDArray | tuple) → pytree of jax arrays."""
    if state is None:
        return None
    if isinstance(state, NDArray):
        return state._data
    return tuple(_state_get(s) for s in state)


def sharded_data(x, mesh, spec=None, axis="data"):
    """Place a host batch on the mesh, sharded over the batch axis."""
    if spec is None:
        spec = P(axis)
    arr = x._data if isinstance(x, NDArray) else jnp.asarray(
        np.asarray(x, dtype=getattr(x, "dtype", np.float32)))
    return mesh_mod.shard_put(arr, mesh_mod.named_sharding(mesh, spec))


class ShardedTrainer:
    """Data/tensor-parallel trainer over a mesh.

    Parameters
    ----------
    block : gluon.Block — the model (params must be initialized).
    loss : gluon.loss.Loss or callable(outputs_nd, label_nd) -> NDArray.
    optimizer : mxnet_tpu.optimizer.Optimizer instance or name string.
    mesh : jax.sharding.Mesh, default = all devices on one ``data`` axis.
    param_rules : list[(regex, PartitionSpec)] — tensor-parallel shardings
        for matching parameter names; unmatched params are replicated.
    batch_axis : mesh axis name the input batch is sharded over.
    shard_weight_update : bool — cross-replica weight-update sharding
        (ZeRO-1; arXiv:2004.13336): optimizer state and the update
        computation are sharded over the batch axis; gradients arrive
        via reduce-scatter and updated shards re-replicate via
        all-gather, both inserted by the XLA SPMD partitioner from the
        sharding constraints. Numerically exact — same update,
        different placement. Applies per parameter, only where it can:
        a param falls back to the replicated update when it matches a
        tensor-parallel rule or its leading dim is not divisible by the
        batch-axis size; state leaves whose shape differs from the
        weight's (e.g. scalar schedule state) stay replicated too.
    """

    def __init__(self, block, loss, optimizer, mesh=None, param_rules=None,
                 batch_axis="data", optimizer_params=None,
                 shard_weight_update=False):
        from .. import optimizer as opt_mod
        self._block = block
        self._loss = loss
        if isinstance(optimizer, str):
            optimizer = opt_mod.create(optimizer, **(optimizer_params or {}))
        self._opt = optimizer
        self._mesh = mesh if mesh is not None else auto_mesh((batch_axis,))
        self._batch_axis = batch_axis
        self._rules = [(re.compile(pat), spec)
                       for pat, spec in (param_rules or [])]

        self._fn, self._grad_names, self._aux_names = block_pure_fn(block)
        from ..base import mirror_enabled
        if mirror_enabled():
            # MXNET_BACKWARD_DO_MIRROR → remat the whole block in backward
            # (train flag is arg 4, a static python bool)
            self._fn = jax.checkpoint(self._fn, static_argnums=(4,))
        pd = {p.name: p for p in block.collect_params().values()}
        self._pd = pd
        if not getattr(optimizer, "idx2name", None):
            optimizer.idx2name = {i: n for i, n in enumerate(self._grad_names)}
        self._index = {n: i for i, n in enumerate(self._grad_names)}

        # --- place params/aux on the mesh ---
        def shard_for(name, val):
            spec = self._tp_spec(name)
            if spec is not None:
                return mesh_mod.named_sharding(self._mesh, spec)
            return mesh_mod.replicated(self._mesh)
        # jnp.copy first: device_put may alias the source buffer as one
        # shard, and the jitted step donates these — donating an aliased
        # buffer would invalidate the block's own parameters.
        self.params = {
            n: jax.device_put(jnp.copy(pd[n]._data._data),
                              shard_for(n, pd[n]._data))
            for n in self._grad_names}
        self.aux = {
            n: jax.device_put(jnp.copy(pd[n]._data._data),
                              mesh_mod.replicated(self._mesh))
            for n in self._aux_names}

        # --- optimizer state: sharded like its weight, or (ZeRO-1)
        # split over the batch axis when the leading dim divides evenly
        self._ndata = self._mesh.shape[batch_axis]
        self._update_shardings = {}
        if shard_weight_update:
            for n in self._grad_names:
                spec = zero1_update_spec(pd[n]._data.shape,
                                         self._tp_spec(n) or P(),
                                         self._ndata, batch_axis)
                if spec is not None:
                    self._update_shardings[n] = NamedSharding(self._mesh,
                                                              spec)
        replicated = mesh_mod.replicated(self._mesh)
        self.states = {}
        for n in self._grad_names:
            st = optimizer.create_state(self._index[n], pd[n]._data)
            tree = _state_get(st)
            wshape = pd[n]._data.shape
            base = self._update_shardings.get(n, self.params[n].sharding)

            def place(x, base=base, wshape=wshape):
                # only weight-shaped leaves take the weight's sharding;
                # scalar/odd-shaped schedule state stays replicated
                s = base if tuple(x.shape) == tuple(wshape) else replicated
                return jax.device_put(x, s)

            self.states[n] = jax.tree_util.tree_map(place, tree)

        self._num_update = 0
        self._step_fn = None

    def _tp_spec(self, name):
        """The tensor-parallel PartitionSpec for a param name, or None."""
        for pat, spec in self._rules:
            if pat.search(name):
                return spec
        return None

    # -- the pure, jitted step --------------------------------------------
    def _build_step(self):
        fn = self._fn
        loss_obj = self._loss
        opt = self._opt
        index = self._index
        grad_names = self._grad_names

        def loss_of(params, aux, data, label, key):
            outs, new_aux = fn(params, aux, (data,), key, True)
            out_nd = _wrap(outs[0])
            label_nd = _wrap(label)
            with autograd.pause(train_mode=True):
                l = loss_obj(out_nd, label_nd)
            return jnp.mean(l._data), new_aux

        upd_shardings = self._update_shardings
        param_shardings = {n: self.params[n].sharding for n in grad_names}

        def apply_updates(params, grads, states, lrs, wds, ts):
            # Pure functional core: the same update_step the eager Updater
            # runs, traced here with lr/wd/t entering as scalars so one
            # cached program serves every step of the schedule.  Under
            # weight-update sharding ``parallel.zero.sharded_update``
            # constrains grad/weight/state so the XLA partitioner
            # reduce-scatters the gradient, runs the update on 1/N of
            # the rows per replica, and all-gathers the result
            # (arXiv:2004.13336) — the same shared core the fused
            # Trainer's MXNET_ZERO path compiles.
            new_p, new_s = {}, {}
            for n in grad_names:
                hyper = {"lr": lrs[n], "wd": wds[n], "t": ts[n]}
                new_p[n], new_s[n] = sharded_update(
                    opt.update_step, params[n], grads[n], states[n], hyper,
                    upd_shardings.get(n), param_shardings[n])
            return new_p, new_s

        def step(params, states, aux, data, label, key, lrs, wds, ts):
            (loss, new_aux), grads = jax.value_and_grad(
                loss_of, has_aux=True)(params, aux, data, label, key)
            new_params, new_states = apply_updates(
                params, grads, states, lrs, wds, ts)
            return new_params, new_states, new_aux, loss

        return mesh_mod.jit_sharded(step, "sharded_train_step",
                                    donate_argnums=(0, 1, 2))

    def step(self, data, label):
        """Run one sharded train step; returns the scalar loss (host float).

        ``data``/``label`` may be NDArray or numpy; they are sharded over
        the batch axis of the mesh.
        """
        if self._step_fn is None:
            self._step_fn = self._build_step()
        data = sharded_data(data, self._mesh, axis=self._batch_axis)
        lspec = P(self._batch_axis)
        label = sharded_data(label, self._mesh, spec=lspec)
        self._num_update += 1
        opt = self._opt
        # host-side lr/wd/step-count schedule (keeps the jitted program
        # schedule-agnostic: all schedule values enter as traced scalars)
        lrs, wds, ts = {}, {}, {}
        for n, i in self._index.items():
            opt._update_count(i)
            lrs[n] = jnp.asarray(opt._get_lr(i), dtype=jnp.float32)
            wds[n] = jnp.asarray(opt._get_wd(i), dtype=jnp.float32)
            ts[n] = jnp.asarray(opt._index_update_count[i], dtype=jnp.int32)
        key = _random.next_key()
        self.params, self.states, self.aux, loss = self._step_fn(
            self.params, self.states, self.aux, data, label, key, lrs, wds,
            ts)
        return float(loss)

    def forward(self, data):
        """Sharded inference forward (no grad, no update)."""
        fn = self._fn
        if not hasattr(self, "_fwd_fn"):
            def fwd(params, aux, data, key):
                outs, _ = fn(params, aux, (data,), key, False)
                return outs[0] if len(outs) == 1 else outs
            self._fwd_fn = mesh_mod.jit_sharded(fwd, "sharded_forward")
        data = sharded_data(data, self._mesh, axis=self._batch_axis)
        out = self._fwd_fn(self.params, self.aux, data, _random.next_key())
        return _wrap(out)

    def sync_to_block(self):
        """Write trained params back into the Gluon block (for save/eval).

        Values are de-sharded onto each parameter's original device so the
        block stays usable on the eager single-device path.
        """
        for n in self._grad_names + self._aux_names:
            src = self.params.get(n, self.aux.get(n))
            old = self._pd[n]._data._data
            dev = next(iter(old.devices())) if hasattr(old, "devices") \
                else jax.devices()[0]
            self._pd[n]._data._set_data(
                jax.device_put(np.asarray(src), dev))


# the provider's programs close over a live trainer; keep it alive until
# the driver traces (same idiom as gluon/fused_trainer)
_TRACECHECK_KEEPALIVE = []


def tracecheck_programs():
    """graftcheck provider: the SPMD train step and inference forward of
    a tiny Dense regression over the live mesh."""
    from .. import init as mx_init
    from .. import gluon
    net = gluon.nn.Dense(4)
    net.initialize(mx_init.Xavier())
    x_host = np.zeros((8, 4), np.float32)
    y_host = np.zeros((8, 4), np.float32)
    net(_wrap(jnp.asarray(x_host)))          # shape-infer the params
    st = ShardedTrainer(net, gluon.loss.L2Loss(), "sgd",
                        optimizer_params={"learning_rate": 0.1})
    step = st._build_step()
    data = sharded_data(x_host, st._mesh, axis=st._batch_axis)
    label = sharded_data(y_host, st._mesh, spec=P(st._batch_axis))
    key = jax.random.PRNGKey(0)
    one = jnp.float32(0.1)
    lrs = {n: one for n in st._grad_names}
    wds = {n: jnp.float32(0.0) for n in st._grad_names}
    ts = {n: jnp.int32(1) for n in st._grad_names}

    def fwd(params, aux, data, key):
        outs, _ = st._fn(params, aux, (data,), key, False)
        return outs[0]

    fwd_prog = mesh_mod.jit_sharded(fwd, "sharded_forward")
    _TRACECHECK_KEEPALIVE.append(st)
    axes = {"mesh_axes": (st._batch_axis,)}
    return [
        ("sharded_train_step", step,
         (st.params, st.states, st.aux, data, label, key, lrs, wds, ts),
         {}, axes),
        ("sharded_forward", fwd_prog,
         (st.params, st.aux, data, key), {}, axes),
    ]
