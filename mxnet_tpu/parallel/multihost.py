"""Multi-host runtime: jax.distributed over the launcher's env contract.

Reference analogue: ps-lite's scheduler/server/worker rendezvous driven by
the dmlc tracker env vars (``tools/launch.py:22-30``,
``src/kvstore/kvstore_dist.h``). TPU-native replacement (SURVEY §5.8): all
processes call ``jax.distributed.initialize`` against one coordinator,
after which every host sees the global device set and ``pjit`` programs
run SPMD with XLA collectives over ICI/DCN — there are no parameter
servers to place.

Env contract (either namespace works; the launcher sets both):

    MXNET_COORDINATOR   host:port of process 0   (DMLC_PS_ROOT_URI/_PORT)
    MXNET_NUM_PROCESSES world size               (DMLC_NUM_WORKER)
    MXNET_PROCESS_ID    this process's rank      (DMLC_WORKER_RANK)
"""
from __future__ import annotations

import os

import jax

__all__ = ["init_from_env", "is_initialized", "rank", "num_processes",
           "local_devices", "global_devices", "barrier"]

_initialized = False


def _env(*names, default=None):
    for n in names:
        # one-shot rendezvous read at init, not a hot path
        # graftlint: disable=JG006
        v = os.environ.get(n)
        if v not in (None, ""):
            return v
    return default


def init_from_env(force=False):
    """Initialize jax.distributed when the launcher env vars are present.

    Returns (rank, world_size); (0, 1) when not launched distributed.
    Idempotent — safe to call from library code and user scripts alike.
    """
    global _initialized
    world = int(_env("MXNET_NUM_PROCESSES", "DMLC_NUM_WORKER", default="1"))
    if world <= 1 and not force:
        return 0, 1
    if _initialized:
        return rank(), num_processes()

    # OMPI_COMM_WORLD_RANK / PMI_RANK: the mpi launcher exports one env
    # for the whole worker group, so the per-process rank comes from the
    # MPI runtime itself (ref dmlc_tracker/mpi.py contract)
    proc_id = int(_env("MXNET_PROCESS_ID", "DMLC_WORKER_RANK",
                       "OMPI_COMM_WORLD_RANK", "PMI_RANK", default="0"))
    coord = _env("MXNET_COORDINATOR")
    if coord is None:
        host = _env("DMLC_PS_ROOT_URI", default="127.0.0.1")
        port = _env("MXNET_COORDINATOR_PORT", "DMLC_PS_ROOT_PORT",
                    default="49151")
        coord = "%s:%s" % (host, port)

    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=world, process_id=proc_id)
    _initialized = True
    return proc_id, world


def is_initialized():
    return _initialized


def rank():
    """This process's index (ref kvstore.h:309 get_rank)."""
    return jax.process_index()


def num_processes():
    """World size (ref kvstore.h:316 get_group_size)."""
    return jax.process_count()


def local_devices():
    return jax.local_devices()


def global_devices():
    return jax.devices()


def barrier(name="mx_barrier"):
    """Block until every process arrives (ref kvstore.h:339 Barrier).

    Implemented as a tiny all-reduce across one device per process —
    completion of the collective is the synchronisation.
    """
    if jax.process_count() == 1:
        return
    import numpy as np
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)
