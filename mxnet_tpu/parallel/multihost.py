"""Multi-host runtime: jax.distributed over the launcher's env contract.

Reference analogue: ps-lite's scheduler/server/worker rendezvous driven by
the dmlc tracker env vars (``tools/launch.py:22-30``,
``src/kvstore/kvstore_dist.h``). TPU-native replacement (SURVEY §5.8): all
processes call ``jax.distributed.initialize`` against one coordinator,
after which every host sees the global device set and ``pjit`` programs
run SPMD with XLA collectives over ICI/DCN — there are no parameter
servers to place.

Env contract (either namespace works; the launcher sets both):

    MXNET_COORDINATOR   host:port of process 0   (DMLC_PS_ROOT_URI/_PORT)
    MXNET_NUM_PROCESSES world size               (DMLC_NUM_WORKER)
    MXNET_PROCESS_ID    this process's rank      (DMLC_WORKER_RANK)
"""
from __future__ import annotations

import os

import jax

__all__ = ["init_from_env", "is_initialized", "rank", "num_processes",
           "local_devices", "global_devices", "barrier"]

_initialized = False


def _env(*names, default=None):
    for n in names:
        # one-shot rendezvous read at init, not a hot path
        # graftlint: disable=JG006
        v = os.environ.get(n)
        if v not in (None, ""):
            return v
    return default


def init_from_env(force=False):
    """Initialize jax.distributed when the launcher env vars are present.

    Returns (rank, world_size); (0, 1) when not launched distributed.
    Idempotent — safe to call from library code and user scripts alike.
    """
    global _initialized
    world = int(_env("MXNET_NUM_PROCESSES", "DMLC_NUM_WORKER", default="1"))
    if world <= 1 and not force:
        return 0, 1
    if _initialized:
        return rank(), num_processes()

    # OMPI_COMM_WORLD_RANK / PMI_RANK: the mpi launcher exports one env
    # for the whole worker group, so the per-process rank comes from the
    # MPI runtime itself (ref dmlc_tracker/mpi.py contract)
    proc_id = int(_env("MXNET_PROCESS_ID", "DMLC_WORKER_RANK",
                       "OMPI_COMM_WORLD_RANK", "PMI_RANK", default="0"))
    coord = _env("MXNET_COORDINATOR")
    if coord is None:
        host = _env("DMLC_PS_ROOT_URI", default="127.0.0.1")
        port = _env("MXNET_COORDINATOR_PORT", "DMLC_PS_ROOT_PORT",
                    default="49151")
        coord = "%s:%s" % (host, port)

    jax.distributed.initialize(coordinator_address=coord,
                               num_processes=world, process_id=proc_id)
    _initialized = True
    return proc_id, world


def is_initialized():
    return _initialized


def rank():
    """This process's index (ref kvstore.h:309 get_rank)."""
    return jax.process_index()


def num_processes():
    """World size (ref kvstore.h:316 get_group_size)."""
    return jax.process_count()


def local_devices():
    return jax.local_devices()


def global_devices():
    return jax.devices()


def _coord_client():
    """The jax.distributed coordination-service client, or None.  Its
    barrier/KV ops are plain gRPC to the coordinator — no XLA program, so
    they work on backends whose compiler can't span processes (CPU
    before jaxlib 0.5)."""
    try:
        from jax._src import distributed
        return distributed.global_state.client
    except Exception:
        return None


def barrier(name="mx_barrier", timeout_ms=600_000):
    """Block until every process arrives (ref kvstore.h:339 Barrier).

    Prefers the coordination-service barrier (host-level, backend-
    independent); falls back to a tiny all-reduce whose completion is
    the synchronisation.
    """
    if jax.process_count() == 1:
        return
    client = _coord_client()
    if client is not None:
        client.wait_at_barrier(name, timeout_in_ms=timeout_ms)
        return
    from jax.experimental import multihost_utils
    multihost_utils.sync_global_devices(name)


def host_gather_floats(name, value, timeout_ms=600_000):
    """Every process contributes one float; returns the rank-ordered
    list on all of them.  Rides the coordination-service KV store
    (host-level), so it agrees values across processes even when the
    backend can't compile a cross-process program."""
    world = jax.process_count()
    if world == 1:
        return [float(value)]
    client = _coord_client()
    if client is None:
        raise RuntimeError("host_gather_floats needs jax.distributed")
    client.key_value_set("%s/%d" % (name, jax.process_index()),
                         repr(float(value)))
    return [float(client.blocking_key_value_get(
        "%s/%d" % (name, r), timeout_ms)) for r in range(world)]
