"""Chunked device-side collectives: redistribution that never
materializes a fully-gathered intermediate.

"Memory-efficient array redistribution through portable collective
communication" (PAPERS.md, arXiv 2112.01075) decomposes every
all-gather / reduce-scatter / resharding into a pipelined schedule of
bounded *chunks*, so peak memory is ``output + one chunk`` instead of
``output + a full extra copy per participant``.  ``checkpoint/
reshard.py``'s ``redistribution_plan`` is the host-side, file-at-a-time
sketch of that schedule; this module is its promotion to device
granularity, shared by the three sites that used to move whole arrays
at once:

* **kvstore buckets** — ``KVStore._reduce_all`` routes any
  single-tensor bucket larger than the chunk size through
  :func:`chunked_reduce` instead of one monolithic concat+sum, and
  :func:`chunked_reduce_scatter` gives the uneven-tail shard split the
  ZeRO-1 gradient leg needs.
* **the ZeRO-1 weight all-gather** — ``gluon/fused_trainer.py``'s
  ``_ZeroPlan`` gathers sharded optimizer state home
  (:func:`gather_home`) and re-places state onto a changed mesh
  (:func:`redistribute`) chunk by chunk.
* **elastic restore** — ``checkpoint/manager.py`` uploads restored
  host leaves through :func:`chunked_device_put`, so a restore onto a
  different shard count streams instead of staging full arrays.

Every reduction chunk runs through ONE watched program
(``collective_chunk_sum``): chunks are padded to the fixed chunk length
(zero padding — exact for a sum) so a single compiled signature serves
every chunk including the uneven tail, and the pad is sliced off before
any caller can observe it.  Assembly streams through a second watched
program (``collective_chunk_write``): off-CPU each chunk is written in
place into the one DONATED output buffer as it arrives, so peak memory
is ``output + one chunk``; on CPU — where XLA ignores donation, the
same reason the fused trainer only donates off-CPU — assembly falls
back to one concatenate (peak ``output + pieces``).  All results are
bitwise-identical to the unchunked path: chunking only reorders *data
movement*, never the per-element summation order.

``MXNET_OVERLAP_CHUNK_BYTES`` (default 1 MiB) sizes the chunk; cached
at import (the JG006 pattern), :func:`refresh_from_env` re-reads.

This module also owns the *named in-program collectives* (``psum`` …):
thin ``jax.lax`` wrappers used inside ``mesh.shard_map``-traced code,
plus the host-level ``barrier``/``host_allreduce`` helpers — the whole
communication surface in one module (the stale ``collectives.py`` twin
was merged here; two near-name modules was a footgun).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from .. import profiler as _prof
from .. import telemetry as _tel

__all__ = ["chunk_bytes", "refresh_from_env", "chunk_bounds",
           "shard_bounds", "redistribution_schedule", "chunked_reduce",
           "chunked_reduce_scatter", "chunked_all_gather",
           "chunked_device_put", "gather_home", "redistribute",
           "tracecheck_programs",
           "psum", "pmean", "pmax", "all_gather", "reduce_scatter",
           "ppermute_shift", "all_to_all", "axis_index", "axis_size",
           "barrier", "host_allreduce"]


# ---------------------------------------------------------------------------
# named in-program collectives (the scaling-book surface)
# ---------------------------------------------------------------------------
#
# These replace the reference's communication backend (SURVEY §5.8):
# ps-lite ZPush/ZPull RPC (``src/kvstore/kvstore_dist.h:253-313``) and the
# Comm reduce/broadcast trees (``src/kvstore/comm.h:90-560``) become XLA
# collectives compiled into the program — riding ICI within a slice and
# DCN across slices, with no parameter-server round-trip.

def psum(x, axis_name):
    """All-reduce sum over a mesh axis (replaces Comm::Reduce+Broadcast)."""
    return lax.psum(x, axis_name)


def pmean(x, axis_name):
    return lax.pmean(x, axis_name)


def pmax(x, axis_name):
    return lax.pmax(x, axis_name)


def all_gather(x, axis_name, axis=0, tiled=True):
    """Gather shards along ``axis`` from every device on the mesh axis."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name, axis=0):
    """Sum-reduce then scatter shards along ``axis`` (psum_scatter)."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


def ppermute_shift(x, axis_name, shift=1):
    """Rotate shards around the ring by ``shift`` (the ring-attention and
    pipeline primitive). Positive shift sends to the next-higher index."""
    n = lax.psum(1, axis_name)
    perm = [(i, (i + shift) % n) for i in range(n)]
    return lax.ppermute(x, axis_name, perm)


def all_to_all(x, axis_name, split_axis, concat_axis):
    """All-to-all (the Ulysses/DeepSpeed sequence-parallel primitive)."""
    return lax.all_to_all(x, axis_name, split_axis=split_axis,
                          concat_axis=concat_axis, tiled=True)


def axis_index(axis_name):
    return lax.axis_index(axis_name)


def axis_size(axis_name):
    return lax.psum(1, axis_name)


def barrier(name="barrier"):
    """Cross-host barrier (reference ``KVStore::Barrier``, kvstore.h:339).

    Single-process: no-op.  Multi-host: sync over all global devices.
    """
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


def host_allreduce(arrays):
    """Sum a list of per-device host arrays into one (kvstore local reduce).

    The reference staged through pinned CPU memory with an OMP tree-reduce
    (comm.h:301-436); here the arrays are summed by one fused XLA program
    on the first array's device.
    """
    if len(arrays) == 1:
        return arrays[0]
    out = arrays[0]
    for a in arrays[1:]:
        out = out + jax.device_put(a, out.devices().pop())
    return out

_DEFAULT_CHUNK_BYTES = 1 << 20


def _env_chunk_bytes():
    import os
    try:
        return max(1, int(os.environ.get("MXNET_OVERLAP_CHUNK_BYTES",
                                         _DEFAULT_CHUNK_BYTES)))
    except ValueError:
        return _DEFAULT_CHUNK_BYTES


# cached at import: the chunk size is consulted on every bucket reduce
_CHUNK_BYTES = _env_chunk_bytes()


def refresh_from_env():
    """Re-read MXNET_OVERLAP_CHUNK_BYTES (tests / late configuration)."""
    global _CHUNK_BYTES
    _CHUNK_BYTES = _env_chunk_bytes()


def chunk_bytes():
    return _CHUNK_BYTES


def chunk_elems(dtype, limit=None):
    """Elements per chunk for *dtype* under the byte budget."""
    return max(1, int(limit or _CHUNK_BYTES) // np.dtype(dtype).itemsize)


def chunk_bounds(n_elems, n_chunk):
    """``[(lo, hi), ...]`` covering ``[0, n_elems)`` in steps of
    *n_chunk* — the last chunk carries the uneven tail."""
    n_elems, n_chunk = int(n_elems), max(1, int(n_chunk))
    return [(lo, min(lo + n_chunk, n_elems))
            for lo in range(0, n_elems, n_chunk)]


def shard_bounds(n_elems, n_shards):
    """Contiguous shard ranges for a flat payload: ceil-sized leading
    shards, uneven tail on the last — every element lands in exactly one
    shard even when ``n_elems % n_shards != 0``."""
    n_elems, n_shards = int(n_elems), max(1, int(n_shards))
    per = -(-n_elems // n_shards)        # ceil division
    return [(min(k * per, n_elems), min((k + 1) * per, n_elems))
            for k in range(n_shards)]


def redistribution_schedule(n_elems, n_from, n_to, n_chunk):
    """The arXiv-2112.01075 transfer schedule at element granularity:
    ``[(src_shard, dst_shard, lo, hi), ...]`` chunk moves taking a flat
    payload from ``n_from`` contiguous shards to ``n_to``, each move no
    larger than *n_chunk* and never crossing a shard boundary on either
    side.  The device-side promotion of ``checkpoint/reshard.py``'s
    slot-granular ``redistribution_plan``: executing the moves one at a
    time bounds peak traffic at one chunk, and tests pin that every
    element lands in exactly one destination shard."""
    src = shard_bounds(n_elems, n_from)
    moves = []
    for dst_idx, (dlo, dhi) in enumerate(shard_bounds(n_elems, n_to)):
        for src_idx, (slo, shi) in enumerate(src):
            lo, hi = max(dlo, slo), min(dhi, shi)
            if lo >= hi:
                continue
            for clo, chi in chunk_bounds(hi - lo, n_chunk):
                moves.append((src_idx, dst_idx, lo + clo, lo + chi))
    return moves


# ---------------------------------------------------------------------------
# the one owned program: sum a fixed-length chunk across participants
# ---------------------------------------------------------------------------

def _chunk_sum(chunks):
    """ONE XLA program per chunk: elementwise sum of the participants'
    same-length slices (tuple arity + length are static per trace)."""
    return jnp.sum(jnp.stack(chunks), axis=0)


_chunk_sum = _tel.watch_jit(jax.jit(_chunk_sum), "collective_chunk_sum")


def _chunk_write(buf, piece, lo):
    """In-place assembly step: write one chunk into the donated output
    buffer at row offset *lo* (traced — one compiled signature per
    piece shape, never per offset).  Donation makes this a true
    in-place update off-CPU: streaming assembly peaks at
    ``output + one chunk`` instead of ``output + all pieces``."""
    start = (lo,) + (0,) * (buf.ndim - 1)
    return jax.lax.dynamic_update_slice(buf, piece, start)


_chunk_write = _tel.watch_jit(
    jax.jit(_chunk_write, donate_argnums=(0,)), "collective_chunk_write")

# chunked collectives are communication for the step-timeline
# decomposition, exactly like the kvstore programs they stand in for
_tel.device.register_collective("collective")


def tracecheck_programs():
    """AOT specimens for graftcheck: the per-chunk sum over two
    participants (the shape every chunk of every reduction lowers to)
    and the donated in-place assembly write."""
    c = jax.ShapeDtypeStruct((4096,), jnp.float32)
    buf = jax.ShapeDtypeStruct((8192,), jnp.float32)
    lo = jax.ShapeDtypeStruct((), jnp.int32)
    # sharding metadata (JX202): the chunk programs share the engine's
    # serialized collective lane with the kvstore reducers
    lane = {"lane": "engine-collective"}
    return [("collective_chunk_sum", _chunk_sum, ((c, c),), {}, lane),
            ("collective_chunk_write", _chunk_write, (buf, c, lo), {},
             lane)]


def _streams(device):
    """Whether the donated in-place assembly engages: XLA CPU ignores
    buffer donation (each write would copy the whole buffer — the same
    reason the fused trainer only donates off-CPU), so CPU keeps the
    one-concatenate assembly and its pieces+output peak."""
    return device is not None and getattr(device, "platform", "cpu") != "cpu"


def _assemble(piece_iter, n_rows, trailing, dtype, device):
    """Assemble ``(row_offset, piece)`` chunks into one array on
    *device*.  Off-CPU: a zeros buffer is built once and every chunk is
    written in place through the donated ``collective_chunk_write``
    program as it arrives — peak memory is the output plus ONE chunk.
    On CPU: chunks are collected and concatenated (donation is a no-op
    there; peak is output + pieces)."""
    shape = (n_rows,) + tuple(trailing)
    if _streams(device):
        buf = jax.device_put(jnp.zeros(shape, dtype), device)
        for lo, piece in piece_iter:
            buf = _chunk_write(buf, jax.device_put(piece, device),
                               jnp.int32(lo))
        return buf
    pieces = [jax.device_put(p, device) if device is not None else p
              for _, p in piece_iter]
    if len(pieces) == 1:
        return pieces[0]
    return jnp.concatenate(pieces, axis=0)


def _pad_to(arr, n):
    """Zero-pad a 1-D slice up to the fixed chunk length (exact for a
    sum; sliced back off before anything observes it)."""
    short = n - arr.shape[0]
    if short <= 0:
        return arr
    return jnp.concatenate([arr, jnp.zeros((short,), arr.dtype)])


def chunked_reduce(flats, limit=None):
    """Sum a list of same-length 1-D arrays chunk by chunk.

    Peak extra memory is ``n_participants x one chunk`` (plus the
    output), not ``n_participants x full length``.  Every chunk runs the
    same compiled ``collective_chunk_sum`` signature — the uneven tail
    is zero-padded up to the chunk length and the pad sliced off, so an
    odd payload costs neither a retrace nor a pad leak.  Bitwise equal
    to ``sum(stack(flats))``: per-element summation order is the
    participant order either way.
    """
    flats = list(flats)
    if len(flats) == 1:
        return flats[0]
    n = int(flats[0].shape[0])
    nc = chunk_elems(flats[0].dtype, limit)
    bounds = chunk_bounds(n, nc)
    if len(bounds) <= 1:
        # one whole-payload program; no pad needed
        return _one_chunk_sum(tuple(flats))
    try:
        dev = next(iter(flats[0].devices()))
    except AttributeError:
        dev = None

    def gen():
        for lo, hi in bounds:
            chunk = tuple(_pad_to(f[lo:hi], nc) for f in flats)
            piece = _one_chunk_sum(chunk)
            yield lo, (piece[:hi - lo] if hi - lo < nc else piece)

    return _assemble(gen(), n, (), flats[0].dtype, dev)


def _one_chunk_sum(chunk):
    _prof.bump("collective_chunk_programs")
    _prof.bump("xla_program_calls")
    return _chunk_sum(chunk)


def chunked_reduce_scatter(flats, n_shards, limit=None):
    """Reduce-scatter a flat payload: returns one reduced 1-D segment
    per shard (``shard_bounds`` ranges — the last carries the uneven
    tail, possibly empty).  Each shard's segment reduces chunk by chunk,
    so no step materializes the fully reduced payload; zero padding
    inside :func:`chunked_reduce` never leaks into a segment."""
    flats = list(flats)
    n = int(flats[0].shape[0])
    segments = []
    for lo, hi in shard_bounds(n, n_shards):
        if hi <= lo:
            segments.append(flats[0][0:0])
            continue
        segments.append(chunked_reduce([f[lo:hi] for f in flats], limit))
    return segments


def chunked_all_gather(segments, device=None, limit=None):
    """The inverse leg: materialize the concatenation of per-shard
    segments on *device*, streaming one chunk at a time — off-CPU the
    chunks write in place into the one donated output buffer, so
    neither side ever holds a second fully-gathered copy."""
    total = sum(int(s.shape[0]) for s in segments)
    if total == 0:
        return segments[0] if segments else None
    nc = chunk_elems(segments[0].dtype, limit)

    def gen():
        off = 0
        for seg in segments:
            n = int(seg.shape[0])
            for lo, hi in chunk_bounds(n, nc):
                yield off + lo, seg[lo:hi]
            off += n

    return _assemble(gen(), total, (), segments[0].dtype, device)


def chunked_device_put(host_arr, device, limit=None):
    """Host→device upload in bounded chunks (the elastic-restore leg):
    a restored leaf streams onto its device row-block by row-block,
    writing in place into the one donated output buffer off-CPU — the
    device never stages a second full copy beside the target.  Small
    arrays take the direct path."""
    host_arr = np.asarray(host_arr)
    nc = chunk_elems(host_arr.dtype, limit)
    if host_arr.size <= nc or host_arr.ndim == 0:
        return jax.device_put(host_arr, device)
    row = int(np.prod(host_arr.shape[1:], dtype=np.int64)) or 1
    rows_per_chunk = max(1, nc // row)

    def gen():
        for lo, hi in chunk_bounds(host_arr.shape[0], rows_per_chunk):
            yield lo, host_arr[lo:hi]

    return _assemble(gen(), host_arr.shape[0], host_arr.shape[1:],
                     host_arr.dtype, device)


def _axis0_shards(arr):
    """Addressable shards sorted by their axis-0 start, or None when the
    layout is not a clean axis-0 split (fall back to a whole-array
    move)."""
    try:
        shards = list(arr.addressable_shards)
    except AttributeError:
        return None
    if len(shards) <= 1:
        return shards or None
    keyed = []
    starts = set()
    for s in shards:
        idx = s.index
        if len(idx) != arr.ndim:
            return None
        for d, sl in enumerate(idx[1:], start=1):
            if (sl.start or 0) != 0 or \
                    (sl.stop is not None and sl.stop != arr.shape[d]):
                return None
        start = idx[0].start or 0
        keyed.append((start, s))
        starts.add(start)
    if len(starts) != len(keyed):
        return None                    # replicated copies, not a split
    keyed.sort(key=lambda t: t[0])
    return [s for _, s in keyed]


def gather_home(arr, jax_device, limit=None):
    """Chunked all-gather of a (possibly sharded) array onto ONE device.

    A shard already resident on *jax_device* is returned as a view (no
    copy); an axis-0 sharded array is reassembled shard by shard in
    bounded chunks; anything else degrades to a whole-array
    ``device_put``.  Pure data movement — bitwise."""
    shards = None
    try:
        sharding = arr.sharding
        if len(arr.devices()) == 1:
            if jax_device in arr.devices():
                return arr
            return jax.device_put(arr, jax_device)
        if sharding.is_fully_replicated:
            for s in arr.addressable_shards:
                if s.device == jax_device:
                    return s.data
            return jax.device_put(arr.addressable_shards[0].data,
                                  jax_device)
        shards = _axis0_shards(arr)
    except AttributeError:
        pass
    if shards is None:
        return jax.device_put(arr, jax_device)
    nc = chunk_elems(arr.dtype, limit)
    row = int(np.prod(arr.shape[1:], dtype=np.int64)) or 1
    rows_per_chunk = max(1, nc // row)

    def gen():
        off = 0
        for s in shards:
            data = s.data
            for lo, hi in chunk_bounds(int(data.shape[0]),
                                       rows_per_chunk):
                yield off + lo, data[lo:hi]
            off += int(data.shape[0])

    _prof.bump("collective_gather_home")
    return _assemble(gen(), int(arr.shape[0]), arr.shape[1:],
                     arr.dtype, jax_device)


def redistribute(arr, target, limit=None):
    """Move *arr* onto *target* sharding chunk by chunk.

    The device-side redistribution path: an axis-0 ``NamedSharding``
    target is assembled per destination shard from bounded chunk
    transfers (``jax.make_array_from_single_device_arrays``), so a
    resharding (e.g. the ZeRO plan re-placing restored state onto a
    changed mesh) never stages a full extra copy per device.  Targets
    this schedule cannot express degrade to a plain ``device_put`` —
    same bits, just not chunked."""
    from jax.sharding import NamedSharding, PartitionSpec
    if not isinstance(target, NamedSharding):
        return jax.device_put(arr, target)
    spec = tuple(target.spec) + (None,) * (arr.ndim - len(target.spec))
    if arr.ndim == 0 or any(s is not None for s in spec[1:]) \
            or spec[0] is None:
        return jax.device_put(arr, target)
    dev_map = target.devices_indices_map(tuple(arr.shape))
    n0 = int(arr.shape[0])
    nc = chunk_elems(arr.dtype, limit)
    row = int(np.prod(arr.shape[1:], dtype=np.int64)) or 1
    rows_per_chunk = max(1, nc // row)
    shards = []
    try:
        for dev, idx in dev_map.items():
            lo = idx[0].start or 0
            hi = idx[0].stop if idx[0].stop is not None else n0

            def gen(lo=lo, hi=hi, dev=dev):
                for clo, chi in chunk_bounds(hi - lo, rows_per_chunk):
                    yield clo, jax.device_put(arr[clo + lo:chi + lo],
                                              dev)

            shards.append(jax.device_put(
                _assemble(gen(), hi - lo, arr.shape[1:], arr.dtype,
                          dev), dev))
        _prof.bump("collective_redistribute")
        return jax.make_array_from_single_device_arrays(
            tuple(arr.shape), target, shards)
    except Exception:
        # the generic mover is always correct; the schedule is an
        # optimization, never a requirement
        return jax.device_put(arr, target)
