"""Learning-rate schedules keyed on the optimizer's update count.

API parity with the reference ``python/mxnet/lr_scheduler.py`` (Factor :21,
MultiFactor :62) plus the poly/cosine decays commonly used with it.
"""
from __future__ import annotations

import logging
import math

__all__ = ["LRScheduler", "FactorScheduler", "MultiFactorScheduler",
           "PolyScheduler", "CosineScheduler"]


class LRScheduler:
    """Maps ``num_update`` → learning rate; mutates ``base_lr`` as it decays."""

    def __init__(self, base_lr=0.01):
        self.base_lr = base_lr

    def __call__(self, num_update):
        raise NotImplementedError()


class FactorScheduler(LRScheduler):
    """Multiply lr by ``factor`` once per ``step`` updates, flooring at
    ``stop_factor_lr`` (ref lr_scheduler.py:21)."""

    def __init__(self, step, factor=1, stop_factor_lr=1e-8):
        super().__init__()
        if step < 1:
            raise ValueError("schedule step must be >= 1")
        if factor > 1.0:
            raise ValueError("factor must be <= 1 so the lr decays")
        self.step, self.factor = step, factor
        self.stop_factor_lr, self.count = stop_factor_lr, 0

    def __call__(self, num_update):
        # catch up on every boundary the update counter has crossed
        while self.count + self.step < num_update:
            self.count += self.step
            decayed = self.base_lr * self.factor
            if decayed < self.stop_factor_lr:
                self.base_lr = self.stop_factor_lr
                logging.info("Update[%d]: now learning rate arrived at "
                             "%0.5e, will not change in the future",
                             num_update, self.base_lr)
            else:
                self.base_lr = decayed
                logging.info("Update[%d]: Change learning rate to %0.5e"
                             % (num_update, self.base_lr))
        return self.base_lr


class MultiFactorScheduler(LRScheduler):
    """Multiply lr by ``factor`` at each boundary in an increasing list
    (ref lr_scheduler.py:62)."""

    def __init__(self, step, factor=1):
        super().__init__()
        if not isinstance(step, list) or not step:
            raise ValueError("step must be a non-empty list")
        for prev, nxt in zip(step, step[1:]):
            if nxt <= prev:
                raise ValueError("schedule steps must strictly increase")
        if step[0] < 1:
            raise ValueError("schedule step must be >= 1")
        if factor > 1.0:
            raise ValueError("factor must be <= 1 so the lr decays")
        self.step, self.factor = step, factor
        self.cur_step_ind, self.count = 0, 0

    def __call__(self, num_update):
        while self.cur_step_ind < len(self.step) \
                and num_update > self.step[self.cur_step_ind]:
            self.count = self.step[self.cur_step_ind]
            self.cur_step_ind += 1
            self.base_lr = self.base_lr * self.factor
            logging.info("Update[%d]: Change learning rate to %0.5e"
                         % (num_update, self.base_lr))
        return self.base_lr


class PolyScheduler(LRScheduler):
    """Polynomial decay from base_lr to final_lr over max_update steps."""

    def __init__(self, max_update, base_lr=0.01, pwr=2, final_lr=0):
        super().__init__(base_lr)
        self.max_update = max_update
        self.power = pwr
        self.final_lr = final_lr
        self.base_lr_orig = base_lr

    def __call__(self, num_update):
        if num_update <= self.max_update:
            frac = 1.0 - float(num_update) / self.max_update
            span = self.base_lr_orig - self.final_lr
            self.base_lr = self.final_lr + span * frac ** self.power
        return self.base_lr


class CosineScheduler(LRScheduler):
    """Half-cosine decay from base_lr to final_lr over max_update steps."""

    def __init__(self, max_update, base_lr=0.01, final_lr=0):
        super().__init__(base_lr)
        self.max_update = max_update
        self.final_lr = final_lr
        self.base_lr_orig = base_lr

    def __call__(self, num_update):
        if num_update <= self.max_update:
            phase = math.pi * num_update / self.max_update
            span = self.base_lr_orig - self.final_lr
            self.base_lr = self.final_lr + span * (1 + math.cos(phase)) / 2
        return self.base_lr
