"""Python side of the general C API (native/c_api.cc).

The C library embeds CPython (same mechanism as the predict ABI,
``native/predict_api.cc``) and calls these helpers; every NDArrayHandle
the C side holds is a strong reference to an :class:`NDArray`. Keeping
the logic here keeps the C layer to reference-counting and buffer copies.

Reference analogue: the glue inside ``src/c_api/c_api.cc`` behind
MXNDArrayCreateEx / MXNDArraySyncCopy{From,To}CPU / MXImperativeInvoke /
MXListAllOpNames / MXNDArraySave / MXNDArrayLoad.
"""
from __future__ import annotations

import numpy as np

from .base import CODE_TO_DTYPE, DTYPE_TO_CODE, MXNetError
from .context import Context
from .ndarray import NDArray, invoke, load, save, zeros
from .ops.registry import get_op, list_ops, parse_attr_string

__all__ = ["create", "dtype_code", "itemsize", "shape_of",
           "copy_from_bytes", "to_bytes", "imperative_invoke",
           "copy_into", "all_op_names", "save_list", "load_file",
           "version_number", "random_seed", "notify_shutdown"]

_DEV = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 6: "tpu"}


def create(shape, dev_type, dev_id, dtype_code_):
    ctx = Context(_DEV.get(int(dev_type), "cpu"), int(dev_id))
    dtype = np.dtype(CODE_TO_DTYPE[int(dtype_code_)])
    return zeros(tuple(int(s) for s in shape), ctx=ctx, dtype=dtype)


def dtype_code(arr):
    return int(DTYPE_TO_CODE[np.dtype(arr.dtype)])


def itemsize(arr):
    return int(np.dtype(arr.dtype).itemsize)


def shape_of(arr):
    return tuple(int(d) for d in arr.shape)


def copy_from_bytes(arr, raw):
    data = np.frombuffer(raw, dtype=arr.dtype)
    if data.size != int(np.prod(arr.shape)):
        raise MXNetError(
            "SyncCopyFromCPU: buffer has %d elements, array needs %d"
            % (data.size, int(np.prod(arr.shape))))
    arr[:] = data.reshape(arr.shape)
    return arr


def to_bytes(arr):
    return np.ascontiguousarray(arr.asnumpy()).tobytes()


def imperative_invoke(op_name, inputs, keys, vals):
    """Run a registered operator on NDArray handles (MXImperativeInvoke).

    String attr values arrive stringified exactly like symbol-JSON attrs
    and parse through the same rules.
    """
    op = get_op(op_name)
    attrs = {k: parse_attr_string(v) for k, v in zip(keys, vals)}
    out = invoke(op, list(inputs), attrs)
    return list(out)


def copy_into(dst, src):
    """Write `src` into the caller-preallocated `dst` (MXImperativeInvoke
    with *num_outputs != 0 on entry — reference out-array semantics)."""
    if tuple(dst.shape) != tuple(src.shape):
        raise MXNetError(
            "preallocated output has shape %s, op produced %s"
            % (dst.shape, src.shape))
    src.copyto(dst)
    return dst


def all_op_names():
    return list_ops()


def version_number():
    """MAJOR*10000 + MINOR*100 + PATCH (reference MXNET_VERSION shape)."""
    from . import __version__
    major, minor, patch = (int(x) for x in __version__.split(".")[:3])
    return major * 10000 + minor * 100 + patch


def random_seed(seed):
    from . import random as random_mod
    random_mod.seed(int(seed))


def notify_shutdown():
    """Drain outstanding async work (reference MXNotifyShutdown)."""
    from . import ndarray as nd_mod
    nd_mod.waitall()
    from . import engine
    engine.wait_for_all()   # module-level: no-ops when no engine exists


def save_list(fname, arrays, keys):
    if keys:
        save(fname, dict(zip(keys, arrays)))
    else:
        save(fname, list(arrays))


def load_file(fname):
    """Returns (arrays, names) — names empty for list-style files."""
    loaded = load(fname)
    if isinstance(loaded, dict):
        names = list(loaded.keys())
        return [loaded[n] for n in names], names
    return list(loaded), []


# ---------------------------------------------------------------------------
# symbol surface (behind MXSymbol*, native/c_api.cc)
# ---------------------------------------------------------------------------

def symbol_from_json(json_str):
    from .symbol.symbol import load_json
    return load_json(json_str)


def symbol_from_file(fname):
    from . import symbol as sym_mod
    return sym_mod.load(fname)


def symbol_to_json(sym):
    return sym.tojson()


def symbol_save_file(sym, fname):
    sym.save(fname)


def symbol_variable(name):
    from . import symbol as sym_mod
    return sym_mod.Variable(name)


class _AtomicSymbol(object):
    """Uncomposed op application (reference CreateAtomicSymbol result):
    holds (op name, attrs) until MXSymbolCompose supplies inputs."""

    __slots__ = ("op_name", "attrs")

    def __init__(self, op_name, attrs):
        self.op_name = op_name
        self.attrs = attrs


def symbol_create_atomic(op_name, keys, vals):
    get_op(op_name)          # fail fast on unknown names
    return _AtomicSymbol(op_name, dict(zip(keys, vals)))


def symbol_compose(atom, name, keys, args):
    """Apply inputs to an atomic symbol; returns the composed Symbol
    (the C side rebinds the handle, mirroring in-place Compose)."""
    from . import symbol as sym_mod
    if not isinstance(atom, _AtomicSymbol):
        raise MXNetError("Compose target is already composed")
    fn = getattr(sym_mod, atom.op_name, None) or \
        getattr(sym_mod._internal, atom.op_name)
    kwargs = {k: parse_attr_string(v) for k, v in atom.attrs.items()}
    if name:
        kwargs["name"] = name
    if keys:
        kwargs.update(dict(zip(keys, args)))
        return fn(**kwargs)
    return fn(*args, **kwargs)


def symbol_list(sym, what):
    if what == "arguments":
        return list(sym.list_arguments())
    if what == "outputs":
        return list(sym.list_outputs())
    return list(sym.list_auxiliary_states())


def symbol_infer_shape(sym, keys, shapes):
    """Returns (arg_shapes, out_shapes, aux_shapes, complete)."""
    kwargs = {k: tuple(int(d) for d in s) for k, s in zip(keys, shapes)}
    try:
        arg, out, aux = sym.infer_shape(**kwargs)
    except MXNetError:
        arg, out, aux = sym.infer_shape_partial(**kwargs)
    complete = all(s is not None for s in (arg or []) + (aux or []))
    fix = lambda ss: [tuple(int(d) for d in (s or ())) for s in (ss or [])]
    return fix(arg), fix(out), fix(aux), bool(complete and arg)


# ---------------------------------------------------------------------------
# executor surface (behind MXExecutor*, native/c_api.cc)
# ---------------------------------------------------------------------------

def executor_simple_bind(sym, dev_type, dev_id, keys, shapes, grad_req):
    ctx = Context(_DEV.get(int(dev_type), "cpu"), int(dev_id))
    shape_kwargs = {k: tuple(int(d) for d in s)
                    for k, s in zip(keys, shapes)}
    return sym.simple_bind(ctx, grad_req=grad_req, **shape_kwargs)


def executor_forward(ex, is_train):
    ex.forward(is_train=bool(is_train))


def executor_backward(ex, ograds):
    ex.backward(out_grads=list(ograds) if ograds else None)


def executor_outputs(ex):
    return list(ex.outputs)


def executor_array(ex, kind, name):
    if kind == "arg":
        table = ex.arg_dict
    elif kind == "grad":
        table = {n: g for n, g in ex.grad_dict.items() if g is not None}
    else:
        table = ex.aux_dict
    if name not in table:
        raise MXNetError("executor has no %s array %r (have: %s)"
                         % (kind, name, sorted(table)))
    return table[name]


# ---------------------------------------------------------------------------
# autograd surface (behind MXAutograd*, native/c_api.cc)
# ---------------------------------------------------------------------------

def autograd_set_recording(flag):
    from . import autograd
    prev = autograd.set_recording(bool(flag))
    if flag and not prev:
        # fresh outermost session: drop stale tape nodes, exactly like
        # the Python record() scope does (autograd.py:67 _clear_tape)
        autograd._clear_tape()
    return int(bool(prev))


def autograd_set_training(flag):
    from . import autograd
    return int(bool(autograd.set_training(bool(flag))))


def autograd_is_recording():
    from . import autograd
    return int(bool(autograd.is_recording()))


def autograd_is_training():
    from . import autograd
    return int(bool(autograd.is_training()))


def autograd_mark_variables(variables, gradients, reqs):
    from . import autograd
    autograd.mark_variables(list(variables), list(gradients),
                            [str(r) for r in reqs])


def autograd_backward(outputs, ograds, retain_graph, train_mode):
    from . import autograd
    from .ndarray import ones
    outputs = list(outputs)
    if ograds:
        # a None slot means ones_like for that head (reference
        # MXAutogradBackwardEx per-head default)
        ograds = [g if g is not None
                  else ones(o.shape, ctx=o.context, dtype=o.dtype)
                  for g, o in zip(ograds, outputs)]
    else:
        ograds = None
    autograd.backward(outputs, ograds,
                      retain_graph=bool(retain_graph),
                      train_mode=bool(train_mode))


# ---------------------------------------------------------------------------
# data-iterator surface (behind MXDataIter*, native/c_api.cc)
# ---------------------------------------------------------------------------

_ITER_CREATORS = ("CSVIter", "LibSVMIter", "MNISTIter", "ImageRecordIter")


def list_data_iters():
    return list(_ITER_CREATORS)


def data_iter_create(name, keys, vals):
    """Param-string creator (reference MXDataIterCreateIter): attrs
    arrive stringified and parse through the symbol-attr rules."""
    from . import io as io_mod
    from . import image as image_mod
    if name not in _ITER_CREATORS:
        raise MXNetError("unknown data iter %r (have %s)"
                         % (name, _ITER_CREATORS))
    table = {"CSVIter": io_mod.CSVIter,
             "LibSVMIter": io_mod.LibSVMIter,
             "MNISTIter": getattr(io_mod, "MNISTIter", None),
             "ImageRecordIter": image_mod.ImageRecordIter}
    cls = table.get(name)
    if cls is None:
        raise MXNetError("data iter %r unavailable in this build" % name)
    kwargs = {k: parse_attr_string(v) for k, v in zip(keys, vals)}
    return cls(**kwargs)


def data_iter_before_first(it):
    it.reset()


def data_iter_next(it):
    """1 if a batch was produced (stash it on the iter), else 0."""
    try:
        it._c_current = next(it)
        return 1
    except StopIteration:
        it._c_current = None
        return 0


def data_iter_get(it, what):
    batch = getattr(it, "_c_current", None)
    if batch is None:
        raise MXNetError("no current batch: call MXDataIterNext first")
    arrs = batch.data if what == "data" else batch.label
    if not arrs:
        raise MXNetError("current batch has no %s" % what)
    return arrs[0]


def data_iter_pad(it):
    batch = getattr(it, "_c_current", None)
    if batch is None:
        raise MXNetError("no current batch: call MXDataIterNext first")
    return int(batch.pad or 0)


# ---------------------------------------------------------------------------
# kvstore surface (behind MXKVStore*, native/c_api.cc)
# ---------------------------------------------------------------------------

def kv_create(kind):
    from . import kvstore
    return kvstore.create(kind)


def kv_type(kv):
    return kv.type


def kv_rank(kv):
    return int(kv.rank)


def kv_group_size(kv):
    return int(kv.num_workers)


def kv_init(kv, keys, values):
    kv.init(list(keys) if len(keys) > 1 else keys[0],
            list(values) if len(values) > 1 else values[0])


def kv_push(kv, keys, values, priority):
    kv.push(list(keys) if len(keys) > 1 else keys[0],
            list(values) if len(values) > 1 else values[0],
            priority=int(priority))


def kv_pull(kv, keys, outs, priority):
    kv.pull(list(keys) if len(keys) > 1 else keys[0],
            out=list(outs) if len(outs) > 1 else outs[0],
            priority=int(priority))


def kv_barrier(kv):
    kv.barrier()


def executor_copy_params(ex, names, arrays):
    arg, aux = {}, {}
    for n, a in zip(names, arrays):
        if n.startswith("aux:"):
            aux[n[4:]] = a
        elif n.startswith("arg:"):
            arg[n[4:]] = a
        else:
            (aux if n in ex.aux_dict else arg)[n] = a
    arg = {n: a for n, a in arg.items() if n in ex.arg_dict}
    aux = {n: a for n, a in aux.items() if n in ex.aux_dict}
    ex.copy_params_from(arg, aux, allow_extra_params=True)
