"""Python side of the general C API (native/c_api.cc).

The C library embeds CPython (same mechanism as the predict ABI,
``native/predict_api.cc``) and calls these helpers; every NDArrayHandle
the C side holds is a strong reference to an :class:`NDArray`. Keeping
the logic here keeps the C layer to reference-counting and buffer copies.

Reference analogue: the glue inside ``src/c_api/c_api.cc`` behind
MXNDArrayCreateEx / MXNDArraySyncCopy{From,To}CPU / MXImperativeInvoke /
MXListAllOpNames / MXNDArraySave / MXNDArrayLoad.
"""
from __future__ import annotations

import numpy as np

from .base import CODE_TO_DTYPE, DTYPE_TO_CODE, MXNetError
from .context import Context
from .ndarray import NDArray, invoke, load, save, zeros
from .ops.registry import get_op, list_ops, parse_attr_string

__all__ = ["create", "dtype_code", "itemsize", "shape_of",
           "copy_from_bytes", "to_bytes", "imperative_invoke",
           "copy_into", "all_op_names", "save_list", "load_file"]

_DEV = {1: "cpu", 2: "gpu", 3: "cpu_pinned", 6: "tpu"}


def create(shape, dev_type, dev_id, dtype_code_):
    ctx = Context(_DEV.get(int(dev_type), "cpu"), int(dev_id))
    dtype = np.dtype(CODE_TO_DTYPE[int(dtype_code_)])
    return zeros(tuple(int(s) for s in shape), ctx=ctx, dtype=dtype)


def dtype_code(arr):
    return int(DTYPE_TO_CODE[np.dtype(arr.dtype)])


def itemsize(arr):
    return int(np.dtype(arr.dtype).itemsize)


def shape_of(arr):
    return tuple(int(d) for d in arr.shape)


def copy_from_bytes(arr, raw):
    data = np.frombuffer(raw, dtype=arr.dtype)
    if data.size != int(np.prod(arr.shape)):
        raise MXNetError(
            "SyncCopyFromCPU: buffer has %d elements, array needs %d"
            % (data.size, int(np.prod(arr.shape))))
    arr[:] = data.reshape(arr.shape)
    return arr


def to_bytes(arr):
    return np.ascontiguousarray(arr.asnumpy()).tobytes()


def imperative_invoke(op_name, inputs, keys, vals):
    """Run a registered operator on NDArray handles (MXImperativeInvoke).

    String attr values arrive stringified exactly like symbol-JSON attrs
    and parse through the same rules.
    """
    op = get_op(op_name)
    attrs = {k: parse_attr_string(v) for k, v in zip(keys, vals)}
    out = invoke(op, list(inputs), attrs)
    return list(out)


def copy_into(dst, src):
    """Write `src` into the caller-preallocated `dst` (MXImperativeInvoke
    with *num_outputs != 0 on entry — reference out-array semantics)."""
    if tuple(dst.shape) != tuple(src.shape):
        raise MXNetError(
            "preallocated output has shape %s, op produced %s"
            % (dst.shape, src.shape))
    src.copyto(dst)
    return dst


def all_op_names():
    return list_ops()


def save_list(fname, arrays, keys):
    if keys:
        save(fname, dict(zip(keys, arrays)))
    else:
        save(fname, list(arrays))


def load_file(fname):
    """Returns (arrays, names) — names empty for list-style files."""
    loaded = load(fname)
    if isinstance(loaded, dict):
        names = list(loaded.keys())
        return [loaded[n] for n in names], names
    return list(loaded), []
