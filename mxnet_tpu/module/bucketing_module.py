"""BucketingModule: one Module per sequence-length bucket, shared params.

API parity with the reference ``python/mxnet/module/bucketing_module.py:35-106``.
TPU note (SURVEY §5.7): each bucket key is simply a distinct jit
specialization — the first batch of a bucket compiles its XLA program, later
batches reuse it; parameters are shared across buckets by name through the
leader (default-bucket) module.
"""
from __future__ import annotations

import logging
import warnings

from .base_module import BaseModule
from .module import Module

__all__ = ["BucketingModule"]


class BucketingModule(BaseModule):
    """Routes each batch to the Module bound for its ``bucket_key``.

    The default bucket's module is the *leader*: it owns the canonical
    parameter dicts and the optimizer; other buckets alias both.
    """

    def __init__(self, sym_gen, default_bucket_key=None, logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)
        if default_bucket_key is None:
            raise ValueError("default_bucket_key is required")
        self._sym_gen = sym_gen
        self._default_bucket_key = default_bucket_key
        self._module_kwargs = dict(
            logger=logger, context=context, work_load_list=work_load_list,
            fixed_param_names=fixed_param_names, state_names=state_names)
        self._by_key = {}
        self._active_key = None
        self._params_dirty = False

    # ---- internals ----

    @property
    def _active(self):
        return self._by_key.get(self._active_key)

    @property
    def _leader(self):
        return self._by_key.get(self._default_bucket_key)

    def _generate(self, bucket_key):
        """Call sym_gen → (symbol, data_names, label_names)."""
        return self._sym_gen(bucket_key)

    def _spawn(self, bucket_key, data_shapes, label_shapes, shared):
        """Create and bind a Module for *bucket_key*."""
        sym, data_names, label_names = self._generate(bucket_key)
        mod = Module(sym, data_names, label_names, **self._module_kwargs)
        mod.bind(data_shapes, label_shapes,
                 for_training=self.for_training,
                 inputs_need_grad=self.inputs_need_grad,
                 shared_module=shared,
                 grad_req=getattr(self, "_grad_req", "write"))
        self._by_key[bucket_key] = mod
        return mod

    # ---- properties (delegate to the active module) ----

    @property
    def data_names(self):
        if self.binded:
            return self._active.data_names
        return self._generate(self._default_bucket_key)[1]

    @property
    def output_names(self):
        if self.binded:
            return self._active.output_names
        return self._generate(self._default_bucket_key)[0].list_outputs()

    @property
    def data_shapes(self):
        self._require_bound()
        return self._active.data_shapes

    @property
    def label_shapes(self):
        self._require_bound()
        return self._active.label_shapes

    @property
    def output_shapes(self):
        self._require_bound()
        return self._active.output_shapes

    @property
    def symbol(self):
        self._require_bound()
        return self._active.symbol

    def _require_bound(self):
        if not self.binded:
            raise AssertionError("BucketingModule is not bound")

    # ---- parameters ----

    def get_params(self):
        self._require_ready()
        self._active._params_dirty = self._params_dirty
        out = self._active.get_params()
        self._params_dirty = False
        return out

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        self._require_bound()
        self._active.init_params(initializer=initializer,
                                 arg_params=arg_params, aux_params=aux_params,
                                 allow_missing=allow_missing,
                                 force_init=force_init,
                                 allow_extra=allow_extra)
        self._params_dirty = False
        self.params_initialized = True

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=False,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            warnings.warn("set_params ignored: already initialized "
                          "(pass force_init=True to override)", stacklevel=2)
            return
        self._active.set_params(arg_params, aux_params,
                                allow_missing=True, force_init=force_init,
                                allow_extra=allow_extra)
        self._params_dirty, self.params_initialized = True, True

    def get_states(self, merge_multi_context=True):
        self._require_ready()
        return self._active.get_states(merge_multi_context)

    def set_states(self, states=None, value=None):
        self._require_ready()
        self._active.set_states(states, value)

    # ---- binding / bucket switching ----

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if shared_module is not None:
            raise ValueError("BucketingModule does not accept shared_module")
        if force_rebind:
            self.binded = False
            self._by_key, self._active_key = {}, None
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        self.binded = True
        self._spawn(self._default_bucket_key, data_shapes, label_shapes,
                    shared=None)
        self._active_key = self._default_bucket_key

    def switch_bucket(self, bucket_key, data_shapes, label_shapes=None):
        """Make *bucket_key* active, binding its module on first use
        against the leader's parameter pool."""
        self._require_bound()
        if bucket_key not in self._by_key:
            self._spawn(bucket_key, data_shapes, label_shapes,
                        shared=self._leader)
        self._active_key = bucket_key
        if self.params_initialized and self._active is not self._leader:
            leader = self._leader
            mod = self._active
            mod._arg_params, mod._aux_params = (leader._arg_params,
                                                leader._aux_params)
            mod.params_initialized = True
            if getattr(mod, "_shares_device_params", False):
                # device arrays are ALIASED with the leader's: the switch
                # is free (the reference's shared-pool behavior,
                # bucketing_module.py:35-106)
                mod._params_dirty = leader._params_dirty
            else:
                # fallback (heterogeneous bucket graphs): refresh device
                # copies from the leader's host dicts — sync them down
                # first or the new bucket resumes from pre-update weights
                if leader._params_dirty:
                    leader._sync_params_from_devices()
                mod._exec_group.set_params(leader._arg_params,
                                           leader._aux_params)
        if self.optimizer_initialized and \
                not self._active.optimizer_initialized:
            self._lend_optimizer(self._active)

    # ---- optimizer ----

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._require_ready()
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        self._active.init_optimizer(kvstore, optimizer, optimizer_params,
                                    force_init=force_init)
        self.optimizer_initialized = True

    def _lend_optimizer(self, mod):
        """Point *mod* at the leader's optimizer/kvstore/updater."""
        leader = self._leader
        mod._optimizer, mod._updater = leader._optimizer, leader._updater
        mod._kvstore = leader._kvstore
        mod._update_on_kvstore = leader._update_on_kvstore
        mod.optimizer_initialized = True

    # ---- computation ----

    def prepare(self, data_batch):
        self._require_ready()
        key = getattr(data_batch, "bucket_key", None)
        if key is not None:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)

    def forward(self, data_batch, is_train=None):
        self._require_ready()
        self._switch_for_batch(data_batch)
        self._active.forward(data_batch, is_train=is_train)

    def _switch_for_batch(self, data_batch):
        """Activate the batch's bucket (binding + optimizer-lending on
        first use happen inside switch_bucket)."""
        key = getattr(data_batch, "bucket_key", None)
        if key is not None and key != self._active_key:
            self.switch_bucket(key, data_batch.provide_data,
                               data_batch.provide_label)

    def _sync_active_to_leader(self):
        """Keep the leader authoritative for later bucket switches."""
        if self._active_key == self._default_bucket_key:
            return
        if getattr(self._active, "_shares_device_params", False):
            # aliased device arrays: the leader already sees the update;
            # only its host dicts are now stale
            self._leader._params_dirty = True
            return
        arg, aux = self._active.get_params()
        leader = self._leader
        leader._arg_params, leader._aux_params = arg, aux
        leader._exec_group.set_params(arg, aux)
        leader._params_dirty = False

    def _fit_step(self, data_batch):
        """Per-bucket fused step: switch to the batch's bucket, then one
        donated fwd+bwd+update program on that bucket's module (each
        bucket keeps its own compiled step)."""
        self._require_ready()
        self._switch_for_batch(data_batch)
        self._params_dirty = True
        self._active._fit_step(data_batch)
        self._sync_active_to_leader()

    def backward(self, out_grads=None):
        self._require_ready()
        self._active.backward(out_grads=out_grads)

    def update(self):
        self._require_ready()
        if not self.optimizer_initialized:
            raise AssertionError("init_optimizer must run before update")
        self._params_dirty = True
        self._active.update()
        self._sync_active_to_leader()

    def get_outputs(self, merge_multi_context=True):
        self._require_ready()
        return self._active.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        self._require_ready()
        if not self.inputs_need_grad:
            raise AssertionError("bind with inputs_need_grad=True first")
        return self._active.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._require_ready()
        self._active.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        self._require_bound()
        for mod in self._by_key.values():
            mod.install_monitor(mon)

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        self._leader.save_checkpoint(prefix, epoch, save_optimizer_states)
