"""Module: the symbol + context-list training unit.

API parity with the reference ``python/mxnet/module/module.py:39`` (bind /
init_params / init_optimizer / forward / backward / update / checkpointing
incl. optimizer state), built independently around a DataParallelExecutorGroup
and the kvstore helpers in ``model.py``.
"""
from __future__ import annotations

import logging
import os
import warnings

import numpy as np

from .. import context as ctx_mod
from .. import ndarray as nd
from .. import optimizer as opt
from ..initializer import Uniform, InitDesc
from ..io import DataDesc
from ..model import (_create_kvstore, _initialize_kvstore, _update_params,
                     _update_params_on_kvstore, load_checkpoint)
from .base_module import BaseModule, _check_input_names
from .executor_group import DataParallelExecutorGroup

__all__ = ["Module"]


def _as_descs(shapes):
    """Normalise a list of (name, shape) tuples / DataDesc into DataDesc."""
    if shapes is None:
        return None
    return [s if isinstance(s, DataDesc) else DataDesc(*s) for s in shapes]


class Module(BaseModule):
    """Intermediate-level module over one symbol replicated on a ctx list."""

    def __init__(self, symbol, data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 context=None, work_load_list=None, fixed_param_names=None,
                 state_names=None):
        super().__init__(logger=logger)

        ctxs = context if context is not None else ctx_mod.current_context()
        if isinstance(ctxs, ctx_mod.Context):
            ctxs = [ctxs]
        self._context = ctxs
        self._work_load_list = work_load_list or [1] * len(ctxs)
        if len(self._work_load_list) != len(ctxs):
            raise ValueError("work_load_list must have one entry per context")

        self._symbol = symbol
        self._partition_names(symbol, data_names, label_names,
                              fixed_param_names, state_names)
        _check_input_names(symbol, self._data_names, "data", True)

        # Host-side canonical parameter copies; device copies live in the
        # executor group and are flagged dirty after each update().
        self._arg_params = self._aux_params = None
        self._params_dirty = False
        self._optimizer = self._updater = self._kvstore = None
        self._update_on_kvstore = self._preload_opt_states = None
        self._exec_group = self._data_shapes = self._label_shapes = None

    def _partition_names(self, symbol, data_names, label_names,
                         fixed_param_names, state_names):
        """Split symbol arguments into data / label / parameter groups."""
        data_names = list(data_names or [])
        label_names = list(label_names or [])
        args = symbol.list_arguments()
        inputs = set(data_names) | set(label_names)
        self._data_names = data_names
        self._label_names = [n for n in label_names if n in args]
        self._param_names = [a for a in args if a not in inputs]
        self._fixed_param_names = list(fixed_param_names or [])
        self._state_names = list(state_names or [])
        self._aux_names = symbol.list_auxiliary_states()
        self._output_names = symbol.list_outputs()

    # ---- checkpointing ----

    @staticmethod
    def load(prefix, epoch, load_optimizer_states=False, **kwargs):
        """Rebuild a Module from ``prefix-symbol.json`` + ``prefix-NNNN.params``."""
        sym, args, auxs = load_checkpoint(prefix, epoch)
        mod = Module(sym, **kwargs)
        mod._arg_params, mod._aux_params = args, auxs
        mod.params_initialized = True
        if load_optimizer_states:
            mod._preload_opt_states = "%s-%04d.states" % (prefix, epoch)
        return mod  # optimizer states attach lazily at init_optimizer

    def save_checkpoint(self, prefix, epoch, save_optimizer_states=False):
        """Write symbol json + params (+ optimizer states) for *epoch*."""
        self._symbol.save(prefix + "-symbol.json")
        params_file = "%s-%04d.params" % (prefix, epoch)
        self.save_params(params_file)
        logging.info('Saved checkpoint to "%s"', params_file)
        if save_optimizer_states:
            states_file = "%s-%04d.states" % (prefix, epoch)
            self.save_optimizer_states(states_file)
            logging.info('Saved optimizer state to "%s"', states_file)

    def save_optimizer_states(self, fname):
        if not self.optimizer_initialized:
            raise AssertionError("optimizer not initialized")
        if self._update_on_kvstore:
            self._kvstore.save_optimizer_states(fname)
        else:
            with open(fname, "wb") as fh:
                fh.write(self._updater.get_states())

    def load_optimizer_states(self, fname):
        if not self.optimizer_initialized:
            raise AssertionError("optimizer not initialized")
        if self._update_on_kvstore:
            self._kvstore.load_optimizer_states(fname)
        else:
            with open(fname, "rb") as fh:
                self._updater.set_states(fh.read())

    # ---- properties ----

    output_names = property(lambda self: self._output_names)
    data_names = property(lambda self: self._data_names)
    label_names = property(lambda self: self._label_names)

    @property
    def data_shapes(self):
        self._require_bound()
        return self._data_shapes

    @property
    def label_shapes(self):
        self._require_bound()
        return self._label_shapes

    @property
    def output_shapes(self):
        self._require_bound()
        execs = self._exec_group.execs
        try:
            outs = execs[0].outputs if execs else []
            return list(zip(self._output_names, (o.shape for o in outs)))
        except Exception:
            # before the first forward: infer symbolically from the bound
            # input shapes (the reference caches these at bind time)
            shapes = {d.name: d.shape for d in self._data_shapes}
            if self._label_shapes:
                shapes.update({d.name: d.shape for d in self._label_shapes})
            _, out_shapes, _ = self._symbol.infer_shape(**shapes)
            return list(zip(self._output_names, out_shapes))

    def _require_bound(self):
        if not self.binded:
            raise AssertionError("module is not bound")

    def _shape_key(self):
        """Cache key for the exec-group-per-shape-signature cache."""
        req = getattr(self, "_grad_req", "write")
        if isinstance(req, dict):
            req = tuple(sorted(req.items()))
        elif isinstance(req, (list, tuple)):
            req = tuple(req)
        # dtype is part of a group's identity: _bind_execs passes type_dict
        # into simple_bind, so same-shape/different-dtype must not collide
        def _dt(d):
            dt = getattr(d, "dtype", None)
            try:                       # canonical spelling: np.float32 and
                return str(np.dtype(dt))  # "float32" must hit the same key
            except TypeError:
                return str(dt)

        return (tuple((d.name, tuple(d.shape), _dt(d))
                      for d in self._data_shapes),
                tuple((d.name, tuple(d.shape), _dt(d))
                      for d in (self._label_shapes or ())),
                self.for_training, self.inputs_need_grad, req)

    # ---- parameters ----

    def get_params(self):
        self._require_ready()
        if self._params_dirty:
            self._sync_params_from_devices()
        return self._arg_params, self._aux_params

    def _alloc_host_params(self):
        """Allocate zeroed host-side copies shaped like executor 0's arrays."""
        proto = self._exec_group.execs[0]
        if self._arg_params is None:
            self._arg_params = {
                n: nd.zeros(proto.arg_dict[n].shape,
                            dtype=proto.arg_dict[n].dtype)
                for n in self._param_names}
        if self._aux_params is None:
            self._aux_params = {
                n: nd.zeros(proto.aux_dict[n].shape,
                            dtype=proto.aux_dict[n].dtype)
                for n in self._aux_names}

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        """Fill parameters from *arg_params*/*aux_params* or *initializer*.

        Contract (ref module.py:246): provided dicts win; missing entries fall
        back to the initializer when ``allow_missing``, else raise.
        """
        if self.params_initialized and not force_init:
            warnings.warn("init_params ignored: already initialized "
                          "(pass force_init=True to override)", stacklevel=2)
            return
        self._require_bound()
        if initializer is None:
            initializer = Uniform(0.01)
        self._alloc_host_params()
        attrs = self._symbol.attr_dict()

        for target, source in ((self._arg_params, arg_params),
                               (self._aux_params, aux_params)):
            for name in sorted(target):
                desc = InitDesc(name, attrs.get(name))
                arr = target[name]
                if source is None:
                    initializer(desc, arr)
                elif name in source:
                    if source[name] is not arr:
                        source[name].copyto(arr)
                elif allow_missing:
                    if initializer is not None:
                        initializer(desc, arr)
                else:
                    raise RuntimeError("%s is not presented" % name)

        self.params_initialized, self._params_dirty = True, False
        self._exec_group.set_params(
            self._arg_params, self._aux_params, allow_extra=allow_extra)

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        if not allow_missing:
            self.init_params(initializer=None, arg_params=arg_params,
                             aux_params=aux_params, allow_missing=False,
                             force_init=force_init, allow_extra=allow_extra)
            return
        if self.params_initialized and not force_init:
            warnings.warn("set_params ignored: already initialized "
                          "(pass force_init=True to override)", stacklevel=2)
            return
        # Partial update: push straight to devices, host copies become stale.
        self._exec_group.set_params(arg_params, aux_params,
                                    allow_extra=allow_extra)
        self._params_dirty, self.params_initialized = True, True

    def _sync_params_from_devices(self):
        self._exec_group.get_params(self._arg_params, self._aux_params)
        self._params_dirty = False

    # ---- binding ----

    def bind(self, data_shapes, label_shapes=None,
             for_training=True, inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        """Create device executors for the given input shapes."""
        if force_rebind:
            self.binded, self._exec_group = False, None
            self._data_shapes = self._label_shapes = None
            self.__dict__.pop("_reshape_cache", None)
        if self.binded:
            self.logger.warning("Already bound, ignoring bind()")
            return

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._grad_req = grad_req
        self._data_shapes = _as_descs(data_shapes)
        self._label_shapes = _as_descs(label_shapes)
        self._exec_group = self._make_exec_group(for_training,
                                                 inputs_need_grad, grad_req)
        self.binded = True
        self.__dict__.setdefault("_reshape_cache", {})[
            self._shape_key()] = self._exec_group

        self._shares_device_params = False
        if shared_module is not None:
            # Alias (not copy) the donor module's host params, per reference.
            self._arg_params, self._aux_params = (
                shared_module._arg_params, shared_module._aux_params)
            self.params_initialized = True
            donor_group = getattr(shared_module, "_exec_group", None)
            if donor_group is not None:
                # alias the donor's DEVICE arrays too: bucket switches
                # then cost nothing (no sync-down, no set_params up)
                self._shares_device_params = \
                    self._exec_group.share_params_with(donor_group)
                if self._shares_device_params:
                    self._params_dirty = shared_module._params_dirty
        if self.params_initialized and not self._shares_device_params:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    def _make_exec_group(self, for_training, inputs_need_grad,
                         grad_req="write"):
        group_cls = DataParallelExecutorGroup
        if len(self._context) > 1:
            from .fused_group import FusedExecutorGroup, fused_enabled
            same_kind = len({c.device_type for c in self._context}) == 1
            batch = self._data_shapes[0].shape[0]
            if fused_enabled() and same_kind                     and batch % len(self._context) == 0:
                # one SPMD program over a device mesh instead of per-device
                # executors + kvstore reduce (the TPU-native fast path)
                group_cls = FusedExecutorGroup
        return group_cls(
            self._symbol, self._context, self._work_load_list,
            self._data_shapes, self._label_shapes, self._param_names,
            for_training, inputs_need_grad, shared_group=None,
            logger=self.logger, fixed_param_names=self._fixed_param_names,
            grad_req=grad_req, state_names=self._state_names)

    def reshape(self, data_shapes, label_shapes=None):
        """Rebind executors for new input shapes, keeping parameters.

        Exec groups are cached per shape signature (the reference reuses
        the shared memory pool, executor.py reshape; under XLA the costly
        resource is the compiled program, so what we keep is the bound
        group with its jit caches). Alternating shapes — bucketing, the
        last partial batch of every epoch — rebind at zero cost after
        the first visit."""
        self._require_bound()
        self._data_shapes = _as_descs(data_shapes)
        self._label_shapes = _as_descs(label_shapes)
        cache = self.__dict__.setdefault("_reshape_cache", {})
        key = self._shape_key()
        group = cache.pop(key, None)   # pop+reinsert = LRU ordering
        if group is None:
            group = self._make_exec_group(
                self.for_training, self.inputs_need_grad,
                grad_req=getattr(self, "_grad_req", "write"))
            # bound the cache: each entry pins compiled programs AND a
            # device-resident parameter copy — many distinct shapes
            # (e.g. free-form inference batches) must not accumulate
            # deliberate re-read: reshape is a rebind (rare), and tests
            # monkeypatch the limit at runtime
            # graftlint: disable=JG006
            limit = int(os.environ.get("MXNET_MODULE_RESHAPE_CACHE", "8"))
            while len(cache) >= max(limit, 1):
                evicted_key = next(iter(cache))
                cache.pop(evicted_key)
        cache[key] = group
        self._exec_group = group
        if self.params_initialized:
            self._exec_group.set_params(self._arg_params, self._aux_params)

    # ---- optimizer ----

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        """Create kvstore + optimizer; decide update-on-kvstore placement."""
        self._require_ready()
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring...")
            return
        if self._params_dirty:
            self._sync_params_from_devices()

        # the fused SPMD group holds ONE logical param/grad copy: the
        # gradient is already globally reduced inside the XLA program, so
        # a single-device kvstore decision applies
        n_dev = getattr(self._exec_group, "num_device", len(self._context))
        kvstore, update_on_kvstore = _create_kvstore(
            kvstore, n_dev, self._arg_params)

        effective_batch = self._exec_group.batch_size
        if kvstore and "dist" in kvstore.type and "_async" in kvstore.type:
            effective_batch *= kvstore.num_workers

        if isinstance(optimizer, str):
            optimizer = self._build_optimizer(optimizer, optimizer_params,
                                              update_on_kvstore,
                                              1.0 / effective_batch)
        elif not isinstance(optimizer, opt.Optimizer):
            raise TypeError("optimizer must be a name or an Optimizer")

        self._optimizer, self._kvstore = optimizer, kvstore
        self._update_on_kvstore = update_on_kvstore
        self._cached_step, self._cached_step_unusable = None, False

        if kvstore:
            _initialize_kvstore(
                kvstore=kvstore, param_arrays=self._exec_group.param_arrays,
                arg_params=self._arg_params, param_names=self._param_names,
                update_on_kvstore=update_on_kvstore)
        if update_on_kvstore:
            self._updater = None
            kvstore.set_optimizer(optimizer)
        else:
            self._updater = opt.get_updater(optimizer)
        self.optimizer_initialized = True

        if self._preload_opt_states:
            self.load_optimizer_states(self._preload_opt_states)
            self._preload_opt_states = None

    def _build_optimizer(self, name, optimizer_params, update_on_kvstore,
                         rescale_grad):
        """Instantiate a named optimizer with the per-slot name mapping the
        Updater uses for lr/wd multipliers."""
        n_dev = getattr(self._exec_group, "num_device", len(self._context))
        idx2name = {}
        for i, pname in enumerate(self._exec_group.param_names):
            if update_on_kvstore:
                idx2name[i] = pname
            else:
                for k in range(n_dev):
                    idx2name[i * n_dev + k] = pname
        kwargs = dict(optimizer_params)
        kwargs.setdefault("rescale_grad", rescale_grad)
        return opt.create(name, sym=self.symbol, param_idx2name=idx2name,
                          **kwargs)

    # ---- computation ----

    def forward(self, data_batch, is_train=None):
        self._require_ready()
        self._maybe_reshape(data_batch)
        self._exec_group.forward(data_batch, is_train)

    def _maybe_reshape(self, data_batch):
        """Rebind when the incoming batch's shapes differ from the bound ones
        (last partial batch, bucketing); preserves trained params."""
        bound = tuple(d.shape for d in self._data_shapes)
        incoming = tuple(x.shape for x in data_batch.data)
        if bound == incoming:
            return
        if self._params_dirty and self.params_initialized:
            self._sync_params_from_devices()
        if getattr(data_batch, "provide_data", None):
            new_data = data_batch.provide_data
        else:
            new_data = [DataDesc(d.name, shp, d.dtype, d.layout)
                        for d, shp in zip(self._data_shapes, incoming)]
        if getattr(data_batch, "provide_label", None):
            new_label = data_batch.provide_label
        elif getattr(data_batch, "label", None):
            new_label = [DataDesc(d.name, arr.shape, d.dtype, d.layout)
                         for d, arr in zip(self._label_shapes,
                                           data_batch.label)]
        else:
            new_label = None
        self.reshape(new_data, new_label)

    def _fit_step(self, data_batch):
        """fit-loop step. Fast path: fwd+bwd+optimizer as ONE donated
        compiled program (cached_step.CachedTrainStep) when the update
        placement allows — single logical param copy, optimizer on
        worker. Falls back to forward_backward + update otherwise."""
        self._maybe_reshape(data_batch)
        step = self._get_cached_step()
        if step is not None:
            feed = dict(zip(self._data_names, data_batch.data))
            if data_batch.label:
                feed.update(zip(self._label_names, data_batch.label))
            try:
                step.run(feed)
                self._params_dirty = True
                return
            except NotImplementedError:
                # optimizer has no pure update_step: permanently fall back
                self._cached_step_unusable = True
                self._cached_step = None
        super()._fit_step(data_batch)

    def _get_cached_step(self):
        from .cached_step import CachedTrainStep, fused_step_enabled
        if getattr(self, "_cached_step_unusable", False) \
                or not fused_step_enabled():
            return None
        if not (self.optimizer_initialized and self._updater is not None
                and self._kvstore is None and not self.inputs_need_grad):
            return None
        group = self._exec_group
        if len(group.execs) != 1:
            return None
        ex = group.execs[0]
        if ex._group2ctx or ex._monitor is not None:
            return None
        if any(r not in ("write", "null") for r in ex.grad_req.values()):
            return None
        # cache on the exec group so alternating reshape() shapes (their
        # groups are themselves cached) keep their compiled step programs
        cached = getattr(group, "_cached_train_step", None)
        if cached is not None and cached._exec is ex \
                and cached._updater is self._updater:
            self._cached_step = cached
            return cached
        try:
            cached = CachedTrainStep(ex, self._updater, group.param_names)
        except ValueError:
            cached = None
            self._cached_step_unusable = True
        group._cached_train_step = cached
        self._cached_step = cached
        return cached

    def forward_backward(self, data_batch):
        """fwd+bwd as one compiled program per executor (falls back to the
        two-call path when the group doesn't support fusing)."""
        self._require_ready()
        self._maybe_reshape(data_batch)
        fused = getattr(self._exec_group, "forward_backward", None)
        if fused is not None:
            fused(data_batch)
        else:
            self._exec_group.forward(data_batch, True)
            self._exec_group.backward()

    def backward(self, out_grads=None):
        self._require_ready()
        self._exec_group.backward(out_grads=out_grads)

    def update(self):
        """Apply the optimizer to accumulated gradients (ref module.py:615)."""
        if not self.optimizer_initialized:
            raise AssertionError("init_optimizer must run before update")
        self._require_ready()
        self._params_dirty = True
        group = self._exec_group
        if self._update_on_kvstore:
            _update_params_on_kvstore(group.param_arrays, group.grad_arrays,
                                      self._kvstore, group.param_names)
        else:
            _update_params(group.param_arrays, group.grad_arrays,
                           updater=self._updater, kvstore=self._kvstore,
                           num_device=getattr(group, "num_device",
                                              len(self._context)),
                           param_names=group.param_names)

    def get_outputs(self, merge_multi_context=True):
        self._require_ready()
        return self._exec_group.get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        self._require_ready()
        if not self.inputs_need_grad:
            raise AssertionError("bind with inputs_need_grad=True first")
        return self._exec_group.get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._exec_group.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        self._require_bound()
        self._exec_group.install_monitor(mon)

    def prepare(self, data_batch):
        pass
