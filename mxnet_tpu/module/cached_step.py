"""CachedTrainStep: fwd + bwd + optimizer update as ONE donated program.

The reference's training loop after bind does zero graph work per step —
``GraphExecutor::RunOps`` (graph_executor.cc:1403) pushes cached engine ops
and the fused optimizer kernels (``src/operator/optimizer_op.cc``) mutate
weights in place. The TPU equivalent is one jitted XLA program per bound
(shapes, optimizer) pair:

    (params, data, aux, opt_states, rng, hyper)
        -> (outputs, new_params, new_aux, new_opt_states)

with parameter / aux / state buffers **donated**, so XLA updates weights
in place in HBM exactly like the reference's in-place optimizer kernels.
Gradients are consumed inside the program and never materialise at a
program boundary — the step is fwd+bwd+update with nothing in between.

Hyper-parameters (per-param lr/wd after scheduler + multipliers, the
update count ``t``, a fresh PRNG key for stochastic optimizers like SGLD)
enter as *traced* arrays: a changing learning-rate schedule never causes
a retrace.

Used automatically by ``Module.fit`` when the update placement allows it
(single logical parameter copy, optimizer-on-worker — the single-chip and
fused-SPMD cases); any kvstore-mediated placement falls back to the
split path. Opt out with ``MXNET_MODULE_FUSED_STEP=0``.
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np

from .. import random as _random
from .. import telemetry as _tel
from ..ndarray import NDArray
from ..optimizer import _state_raw, _state_writeback

__all__ = ["CachedTrainStep", "fused_step_enabled"]


def fused_step_enabled():
    # deliberate re-read: called once per Module.fit bind (not per step),
    # and tests toggle MXNET_MODULE_FUSED_STEP at runtime
    # graftlint: disable=JG006
    return os.environ.get("MXNET_MODULE_FUSED_STEP", "1").strip().lower() \
        not in ("0", "false", "off", "no")


def tracecheck_programs():
    """AOT specimens for graftcheck: the whole fwd+bwd+update program
    Module.fit ships, bound to the specimen executor with a momentum-SGD
    updater (same construction path as the real bind; the constructor
    never executes anything)."""
    import jax as _jax
    from .. import optimizer as opt_mod
    from ..executor import _tracecheck_executor
    ex = _tracecheck_executor()
    updater = opt_mod.get_updater(opt_mod.SGD(momentum=0.9,
                                              learning_rate=0.05))
    pnames = [n for n in ex.arg_names if n in set(ex._grad_names)]
    cts = CachedTrainStep(ex, updater, ["data"] + pnames)
    spec = lambda a: _jax.ShapeDtypeStruct(a.shape, a.dtype)  # noqa: E731
    params = [spec(ex.arg_dict[n]) for n in cts._pnames]
    rest = [spec(ex.arg_dict[n]) for n in cts._rest_names]
    aux_vals = [spec(ex.aux_dict[n]) for n in ex.aux_names]
    states = [_jax.tree_util.tree_map(
        spec, _state_raw(updater.optimizer.create_state(
            i, ex.arg_dict[n])))
        for i, n in enumerate(cts._pnames)]
    key = _random.next_key()
    n = len(cts._pnames)
    hyper = {"lr": np.zeros(n, np.float32), "wd": np.zeros(n, np.float32),
             "t": np.ones(n, np.int32),
             "key": _jax.ShapeDtypeStruct((n,) + key.shape, key.dtype),
             "rng": spec(key)}
    return [("module_cached_step", cts._step_jit,
             (params, rest, aux_vals, states, hyper), {})]


class CachedTrainStep:
    """One compiled train step bound to (executor, updater, param set)."""

    def __init__(self, executor, updater, param_names):
        self._exec = executor
        self._updater = updater
        self._opt = updater.optimizer
        # updatable params = the executor's grad-bearing args, in the
        # module's param order so optimizer indices match the slow path
        grad_set = set(executor._grad_names)
        self._pnames = [n for n in param_names if n in grad_set]
        if set(self._pnames) != grad_set:
            raise ValueError("fused step needs grads on params only")
        arg_names = executor.arg_names
        self._ppos = [arg_names.index(n) for n in self._pnames]
        self._rest_names = [n for n in arg_names if n not in grad_set]
        rest_pos = [arg_names.index(n) for n in self._rest_names]
        self._pidx = {n: i for i, n in enumerate(param_names)}

        fn_train = executor._train_fn
        n_args = len(arg_names)
        ppos, opt = self._ppos, self._opt

        def step(params, rest, aux_vals, states, hyper):
            def g(ps):
                full = [None] * n_args
                for p, v in zip(ppos, ps):
                    full[p] = v
                for p, v in zip(rest_pos, rest):
                    full[p] = v
                return fn_train(full, aux_vals, hyper["rng"])
            outs, vjp_fn, new_aux = jax.vjp(g, params, has_aux=True)
            (grads,) = vjp_fn(tuple(jnp.ones_like(o) for o in outs))
            new_params, new_states = [], []
            for i, (w, gr) in enumerate(zip(params, grads)):
                h = {"lr": jnp.asarray(hyper["lr"][i], dtype=w.dtype),
                     "wd": jnp.asarray(hyper["wd"][i], dtype=w.dtype),
                     "t": hyper["t"][i], "key": hyper["key"][i]}
                nw, ns = opt.update_step(w, gr.astype(w.dtype),
                                         states[i], h)
                new_params.append(nw.astype(w.dtype))
                new_states.append(ns)
            return outs, new_params, new_aux, new_states

        donate = (0, 2, 3) if executor._ctx.device_type != "cpu" else ()
        self._step_jit = _tel.watch_jit(
            jax.jit(step, donate_argnums=donate), "module_cached_step")

    def _ensure_states(self):
        """Create optimizer state through the Updater so checkpoint
        save/load (updater.get_states/set_states) sees the same layout
        as the slow path."""
        for name in self._pnames:
            idx = self._pidx[name]
            if idx not in self._updater.states:
                self._updater.states[idx] = self._opt.create_state(
                    idx, self._exec.arg_dict[name])
                self._updater.states_synced[idx] = True

    def run(self, feed):
        """Execute one step; *feed* maps data/label names to NDArrays."""
        _tel.bump("module_train_step")
        with _tel.span("module_train_step", cat="step",
                       hist="step_time_us", memory=True,
                       args={"params": len(self._pnames)}):
            return self._run(feed)

    def _run(self, feed):
        ex = self._exec
        for k, v in feed.items():
            if k in ex.arg_dict:
                src = v._data if isinstance(v, NDArray) else jnp.asarray(v)
                ex.arg_dict[k]._set_data(src.astype(ex.arg_dict[k].dtype))
        self._ensure_states()

        opt = self._opt
        prev_num_update = opt.num_update
        lrs, wds, ts = [], [], []
        for name in self._pnames:
            idx = self._pidx[name]
            opt._update_count(idx)
            lrs.append(opt._get_lr(idx))
            wds.append(opt._get_wd(idx))
            ts.append(opt._index_update_count[idx])

        params = [ex._place(n, ex.arg_dict[n]) for n in self._pnames]
        rest = [ex._place(n, ex.arg_dict[n]) for n in self._rest_names]
        aux_vals = [ex._place(n, ex.aux_dict[n]) for n in ex.aux_names]
        # optimizer state must live where its weight lives (sharded
        # executors replicate params over a mesh AFTER create_state ran)
        states = [
            jax.tree_util.tree_map(
                lambda leaf, w=w: leaf if getattr(w, "sharding", None) in (
                    None, getattr(leaf, "sharding", None))
                else jax.device_put(leaf, w.sharding),
                _state_raw(self._updater.states[self._pidx[n]]))
            for n, w in zip(self._pnames, params)]
        key = ex._place_rng(_random.next_key())
        ukeys = jax.random.split(key, len(self._pnames) + 1)
        hyper = {"lr": np.asarray(lrs, np.float32),
                 "wd": np.asarray(wds, np.float32),
                 "t": np.asarray(ts, np.int32),
                 "key": ex._place_rng(ukeys[1:]),
                 "rng": ex._place_rng(ukeys[0])}

        try:
            # program child span inside the module_train_step span: in the
            # trace, the gap between the two is host-side feed/bookkeeping
            with _tel.span("module_step_program", cat="program"):
                outs, new_params, new_aux, new_states = self._step_jit(
                    params, rest, aux_vals, states, hyper)
        except NotImplementedError:
            # optimizer lacks a pure update_step (discovered at trace
            # time): roll back the count bookkeeping so the slow-path
            # retry of this same batch doesn't double-count the step
            for name in self._pnames:
                opt._index_update_count[self._pidx[name]] -= 1
            opt.num_update = prev_num_update
            raise

        for n, v in zip(self._pnames, new_params):
            ex.arg_dict[n]._set_data(v)
        for n, v in zip(ex.aux_names, new_aux):
            ex.aux_dict[n]._set_data(v)
        for n, s in zip(self._pnames, new_states):
            _state_writeback(self._updater.states[self._pidx[n]], s)
        from ..ndarray.ndarray import _wrap
        ex._outputs = [_wrap(o, ex._ctx) for o in outs]
        ex._vjp = None
        return ex._outputs
