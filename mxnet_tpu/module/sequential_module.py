"""SequentialModule: chain modules so one's outputs feed the next.

API parity with the reference ``python/mxnet/module/sequential_module.py``
(:29): ``add(module, take_labels=..., auto_wiring=...)`` builds the chain;
forward threads data through every stage, backward threads gradients in
reverse (each intermediate module is bound with ``inputs_need_grad``).
"""
from __future__ import annotations

import copy
import logging

from ..initializer import Uniform
from ..io import DataDesc
from .base_module import BaseModule

__all__ = ["SequentialModule"]


class SequentialModule(BaseModule):
    """Container running member modules back to back (ref :29)."""

    META_TAKE_LABELS = "take_labels"
    META_AUTO_WIRING = "auto_wiring"

    def __init__(self, logger=logging):
        super().__init__(logger=logger)
        self._modules = []
        self._metas = []
        self._label_shapes = None
        self._data_shapes = None

    def add(self, module, **kwargs):
        """Append a module. ``take_labels=True`` routes the chain's labels
        into this stage; ``auto_wiring=True`` renames the previous stage's
        outputs to this stage's data names."""
        for key in kwargs:
            if key not in (self.META_TAKE_LABELS, self.META_AUTO_WIRING):
                raise ValueError("unknown meta %r" % key)
        self._modules.append(module)
        self._metas.append(dict(kwargs))
        self.binded = False
        self.params_initialized = False
        self.optimizer_initialized = False
        return self

    # ---- properties ----

    @property
    def data_names(self):
        if self._modules:
            return self._modules[0].data_names
        return []

    @property
    def output_names(self):
        if self._modules:
            return self._modules[-1].output_names
        return []

    @property
    def data_shapes(self):
        self._require_bound_()
        return self._modules[0].data_shapes

    @property
    def label_shapes(self):
        self._require_bound_()
        return self._label_shapes

    @property
    def output_shapes(self):
        self._require_bound_()
        return self._modules[-1].output_shapes

    def _require_bound_(self):
        if not self.binded:
            raise AssertionError("SequentialModule is not bound")

    # ---- parameters ----

    def get_params(self):
        self._require_ready()
        arg_params, aux_params = {}, {}
        for module in self._modules:
            arg, aux = module.get_params()
            arg_params.update(arg)
            aux_params.update(aux)
        return arg_params, aux_params

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        if self.params_initialized and not force_init:
            return
        self._require_bound_()
        if initializer is None:
            initializer = Uniform(0.01)
        for module in self._modules:
            module.init_params(initializer=initializer,
                               arg_params=arg_params, aux_params=aux_params,
                               allow_missing=True, force_init=force_init,
                               allow_extra=True)
        # duplicate parameter names across stages would silently shadow
        seen = {}
        for module in self._modules:
            arg, aux = module.get_params()
            for name in list(arg) + list(aux):
                if name in seen:
                    raise ValueError("duplicate parameter %r in modules %s "
                                     "and %s" % (name, seen[name], module))
                seen[name] = module
        self.params_initialized = True

    # ---- binding ----

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        if shared_module is not None:
            raise ValueError("SequentialModule does not accept shared_module")
        if not self._modules:
            raise ValueError("SequentialModule is empty — add() modules first")

        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._label_shapes = label_shapes

        my_shapes = data_shapes
        anybody_takes_labels = any(
            m.get(self.META_TAKE_LABELS) for m in self._metas)
        for pos, (module, meta) in enumerate(zip(self._modules, self._metas)):
            last = pos == len(self._modules) - 1
            labels = label_shapes if meta.get(self.META_TAKE_LABELS) or \
                (last and not anybody_takes_labels and label_shapes) else None
            # every stage but the first needs input grads to keep the
            # backward chain flowing
            need_grad = inputs_need_grad if pos == 0 else True
            module.bind(data_shapes=my_shapes, label_shapes=labels,
                        for_training=for_training,
                        inputs_need_grad=need_grad,
                        force_rebind=force_rebind, grad_req=grad_req)
            # wire this stage's outputs to the next stage's data names
            out_shapes = module.output_shapes
            if meta.get(self.META_AUTO_WIRING) and not last:
                next_names = self._modules[pos + 1].data_names
                out_shapes = [(n, s[1] if isinstance(s, tuple) else s.shape)
                              for n, s in zip(next_names, out_shapes)]
            my_shapes = [d if isinstance(d, DataDesc) else DataDesc(*d)
                         for d in out_shapes]
        self.binded = True

    # ---- optimizer ----

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self._require_ready()
        if self.optimizer_initialized and not force_init:
            self.logger.warning("optimizer already initialized, ignoring.")
            return
        for module in self._modules:
            module.init_optimizer(kvstore=kvstore, optimizer=optimizer,
                                  optimizer_params=optimizer_params,
                                  force_init=force_init)
        self.optimizer_initialized = True

    # ---- computation ----

    def forward(self, data_batch, is_train=None):
        self._require_ready()
        from ..io import DataBatch
        batch = copy.copy(data_batch)
        for pos, (module, meta) in enumerate(zip(self._modules, self._metas)):
            module.forward(batch, is_train=is_train)
            if pos == len(self._modules) - 1:
                break
            outs = module.get_outputs()
            nxt = self._modules[pos + 1]
            batch = DataBatch(outs, data_batch.label,
                              pad=data_batch.pad,
                              provide_data=[DataDesc(n, o.shape)
                                            for n, o in zip(nxt.data_names,
                                                            outs)],
                              provide_label=data_batch.provide_label)

    def backward(self, out_grads=None):
        self._require_ready()
        grads = out_grads
        for pos in range(len(self._modules) - 1, -1, -1):
            module = self._modules[pos]
            module.backward(out_grads=grads)
            if pos == 0:
                break
            grads = module.get_input_grads()

    def update(self):
        self._require_ready()
        for module in self._modules:
            module.update()

    def get_outputs(self, merge_multi_context=True):
        self._require_ready()
        return self._modules[-1].get_outputs(merge_multi_context)

    def get_input_grads(self, merge_multi_context=True):
        self._require_ready()
        if not self.inputs_need_grad:
            raise AssertionError("bind with inputs_need_grad=True first")
        return self._modules[0].get_input_grads(merge_multi_context)

    def update_metric(self, eval_metric, labels):
        self._require_ready()
        consumers = [m for m, meta in zip(self._modules, self._metas)
                     if meta.get(self.META_TAKE_LABELS)]
        for module in consumers or [self._modules[-1]]:
            module.update_metric(eval_metric, labels)

    def install_monitor(self, mon):
        self._require_bound_()
        for module in self._modules:
            module.install_monitor(mon)
