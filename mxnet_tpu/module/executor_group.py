"""DataParallelExecutorGroup (parity: reference module/executor_group.py:99-430).

Reference behavior kept: slice the batch across a context list, one executor
per context sharing the symbol, scatter data, forward/backward all, per-device
grad arrays for the kvstore to reduce.  On a single TPU chip this is one
executor; the mesh-sharded pjit fast path lives in parallel/ (SURVEY §2.5 maps
DataParallelExecutorGroup → batch-sharded pjit).
"""
from __future__ import annotations

import numpy as np

from ..base import MXNetError
from .. import context as ctx_mod
from .. import ndarray as nd
from ..io import DataDesc

__all__ = ["DataParallelExecutorGroup", "_split_input_slice"]


def _split_input_slice(batch_size, work_load_list):
    """Split batch into per-device slices (reference executor_group.py:_split)."""
    total = sum(work_load_list)
    if batch_size < len(work_load_list):
        raise ValueError("batch size cannot be smaller than number of devices")
    slices = []
    start = 0
    for i, w in enumerate(work_load_list):
        n = int(round(batch_size * w / total)) if i < len(work_load_list) - 1 \
            else batch_size - start
        slices.append(slice(start, start + n))
        start += n
    return slices


class DataParallelExecutorGroup:
    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad,
                 shared_group=None, logger=None, fixed_param_names=None,
                 grad_req="write", state_names=None):
        self.symbol = symbol
        self.contexts = contexts
        self.num_device = len(contexts)
        self.workload = workload or [1] * len(contexts)
        self.param_names = param_names
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self.fixed_param_names = fixed_param_names or []
        self.state_names = state_names or []
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()

        self.data_names = [x.name if isinstance(x, DataDesc) else x[0]
                           for x in data_shapes]
        self.label_names = [x.name if isinstance(x, DataDesc) else x[0]
                            for x in (label_shapes or [])]

        if isinstance(grad_req, str):
            self.grad_req = {}
            for name in self.arg_names:
                if name in self.param_names:
                    self.grad_req[name] = ("null" if name in self.fixed_param_names
                                           else grad_req)
                elif name in self.data_names:
                    self.grad_req[name] = grad_req if inputs_need_grad else "null"
                else:
                    self.grad_req[name] = "null"
        else:
            self.grad_req = dict(grad_req)
        if not for_training:
            self.grad_req = {k: "null" for k in self.arg_names}

        self.batch_size = (data_shapes[0].shape if isinstance(data_shapes[0], DataDesc)
                           else data_shapes[0][1])[0]
        self.slices = _split_input_slice(self.batch_size, self.workload)
        self.execs = []
        self._bind_execs(data_shapes, label_shapes)

    def _sliced_shape(self, shapes, i):
        out = {}
        for d in shapes or []:
            name, shape = (d.name, d.shape) if isinstance(d, DataDesc) else d
            sl = self.slices[i]
            out[name] = (sl.stop - sl.start,) + tuple(shape[1:])
        return out

    def _bind_execs(self, data_shapes, label_shapes):
        self.execs = []
        type_dict = {d.name: d.dtype
                     for d in list(data_shapes) + list(label_shapes or [])
                     if isinstance(d, DataDesc) and d.dtype is not None}
        for i, c in enumerate(self.contexts):
            shape_kwargs = self._sliced_shape(data_shapes, i)
            shape_kwargs.update(self._sliced_shape(label_shapes, i))
            ex = self.symbol.simple_bind(c, grad_req=self.grad_req,
                                         type_dict=type_dict,
                                         **shape_kwargs)
            self.execs.append(ex)
        self.data_arrays = [[e.arg_dict[n] for e in self.execs]
                            for n in self.data_names]
        self.label_arrays = [[e.arg_dict[n] for e in self.execs]
                             for n in self.label_names if n in self.arg_names]
        self.param_arrays = [[e.arg_dict[n] for e in self.execs]
                             for n in self.param_names]
        # grads aligned to param_names (None when fixed/no-grad)
        self.grad_arrays = []
        for n in self.param_names:
            if self.grad_req.get(n, "null") != "null":
                self.grad_arrays.append([e.grad_dict[n] for e in self.execs])
            else:
                self.grad_arrays.append(None)
        self.aux_arrays = [[e.aux_dict[n] for e in self.execs]
                           for n in self.aux_names]

    # -- params ------------------------------------------------------------
    def share_params_with(self, donor):
        """Alias the donor group's device-resident param/aux NDArrays.

        The TPU answer to the reference's shared memory pool
        (module/bucketing_module.py:35-106 + graph_executor.cc:868
        storage sharing): executors read ``handle._data`` at call time
        and every update path rebinds the handle in place, so aliasing
        the handles makes bucket switches zero-copy — no device→host
        sync, no host→device set_params. Returns True when every param
        and aux state was shared (caller may then skip set_params)."""
        if type(donor) is not type(self) or \
                len(self.execs) != len(donor.execs):
            return False
        for names, dicts in ((self.param_names, "arg_dict"),
                             (self.aux_names, "aux_dict")):
            for name in names:
                for mine, theirs in zip(self.execs, donor.execs):
                    src = getattr(theirs, dicts).get(name)
                    dst = getattr(mine, dicts).get(name)
                    if src is None or dst is None \
                            or src.shape != dst.shape \
                            or src.dtype != dst.dtype:
                        return False
        for name in self.param_names:
            for mine, theirs in zip(self.execs, donor.execs):
                mine.arg_dict[name] = theirs.arg_dict[name]
        for name in self.aux_names:
            for mine, theirs in zip(self.execs, donor.execs):
                mine.aux_dict[name] = theirs.aux_dict[name]
        # refresh the per-device views the module/kvstore paths iterate
        self.param_arrays = [[e.arg_dict[n] for e in self.execs]
                             for n in self.param_names]
        self.aux_arrays = [[e.aux_dict[n] for e in self.execs]
                           for n in self.aux_names]
        return True

    def set_params(self, arg_params, aux_params, allow_extra=False):
        for ex in self.execs:
            ex.copy_params_from(arg_params, aux_params,
                                allow_extra_params=allow_extra)

    def get_params(self, arg_params, aux_params):
        for name, block in zip(self.param_names, self.param_arrays):
            weight = sum(w.asnumpy() for w in block) / len(block)
            arg_params[name] = nd.array(weight, dtype=block[0].dtype)
        for name, block in zip(self.aux_names, self.aux_arrays):
            weight = sum(w.asnumpy() for w in block) / len(block)
            aux_params[name] = nd.array(weight, dtype=block[0].dtype)

    # -- execution ---------------------------------------------------------
    def _load_data(self, batch):
        for name, arrs in zip(self.data_names, self.data_arrays):
            src = batch.data[self.data_names.index(name)]
            for sl, dst in zip(self.slices, arrs):
                dst._set_data(src[sl.start:sl.stop]._data.astype(dst.dtype)
                              if hasattr(src, "_data")
                              else nd.array(src[sl.start:sl.stop])._data)

    def _load_label(self, batch):
        if not batch.label:
            return
        for i, (name, arrs) in enumerate(zip(self.label_names,
                                             self.label_arrays)):
            src = batch.label[i]
            for sl, dst in zip(self.slices, arrs):
                dst._set_data(src[sl.start:sl.stop]._data.astype(dst.dtype)
                              if hasattr(src, "_data")
                              else nd.array(src[sl.start:sl.stop])._data)

    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self.for_training
        self._load_data(data_batch)
        if self.label_arrays and data_batch.label:
            self._load_label(data_batch)
        for ex in self.execs:
            ex.forward(is_train=is_train)

    def forward_backward(self, data_batch):
        """One fused fwd+bwd XLA program per device (Module.fit hot path;
        ref RunOps pushes cached ops only, graph_executor.cc:1403)."""
        assert self.for_training, \
            "re-bind with for_training=True to run backward"
        self._load_data(data_batch)
        if self.label_arrays and data_batch.label:
            self._load_label(data_batch)
        for ex in self.execs:
            ex.forward_backward()

    def backward(self, out_grads=None):
        assert self.for_training, "re-bind with for_training=True to run backward"
        for i, ex in enumerate(self.execs):
            if out_grads is None:
                ex.backward()
            else:
                sliced = [og[self.slices[i].start:self.slices[i].stop]
                          for og in out_grads]
                ex.backward(sliced)

    def get_outputs(self, merge_multi_context=True):
        outputs = [[ex.outputs[i] for ex in self.execs]
                   for i in range(len(self.execs[0].outputs))]
        if merge_multi_context:
            merged = []
            for per_dev in outputs:
                if len(per_dev) == 1:
                    merged.append(per_dev[0])
                else:
                    merged.append(nd.concatenate(per_dev, axis=0))
            return merged
        return outputs

    def get_input_grads(self, merge_multi_context=True):
        assert self.inputs_need_grad
        grads = [[ex.grad_dict[n] for ex in self.execs]
                 for n in self.data_names]
        if merge_multi_context:
            return [g[0] if len(g) == 1 else nd.concatenate(g, axis=0)
                    for g in grads]
        return grads

    def update_metric(self, eval_metric, labels):
        for i, ex in enumerate(self.execs):
            labels_slice = [l[self.slices[i].start:self.slices[i].stop]
                            for l in labels]
            eval_metric.update(labels_slice, ex.outputs)

    def install_monitor(self, mon):
        for ex in self.execs:
            ex.set_monitor_callback(mon.stat_helper if hasattr(mon, "stat_helper")
                                    else mon)
