"""FusedExecutorGroup: multi-device Module as ONE SPMD program.

The reference's DataParallelExecutorGroup runs one executor per device and
reduces gradients through the kvstore afterwards
(``module/executor_group.py:233-430`` + ``comm.h``). TPU-native fast path:
bind a single executor whose data/label inputs are sharded over a
``Mesh(ctx_list, ("data",))`` and whose parameters are replicated — the
XLA SPMD partitioner splits the forward across devices and inserts the
gradient all-reduce itself, so forward+backward is one fused program and
the kvstore reduce disappears (there is one logical gradient already
summed over the global batch).

Numerics match the slow path exactly for stateless graphs: the fused
gradient equals the sum of per-device slice gradients the kvstore would
have produced. BatchNorm differs *by design*: the fused program computes
global (synchronised) batch statistics where per-device executors use
local slices — sync-BN semantics.

Enabled automatically for multi-device Module binds; opt out with
``MXNET_MODULE_FUSED=0``.
"""
from __future__ import annotations

import logging

import jax
from jax.sharding import PartitionSpec as P

from ..executor import Executor
from ..parallel import mesh as mesh_mod
from .. import ndarray as nd

__all__ = ["FusedExecutorGroup", "fused_enabled"]


def fused_enabled():
    import os
    return os.environ.get("MXNET_MODULE_FUSED", "1").strip().lower() \
        not in ("0", "false", "off", "no")


class _ShardedExecutor(Executor):
    """Executor whose inputs spread over a data-parallel mesh."""

    def __init__(self, symbol, ctx, mesh, batch_arg_names, **kwargs):
        self._mesh = mesh
        self._batch_args = set(batch_arg_names)
        self._data_sharding = mesh_mod.named_sharding(mesh, P("data"))
        self._replicated = mesh_mod.replicated(mesh)
        super().__init__(symbol, ctx, **kwargs)

    def _place(self, name, arr):
        sharding = self._data_sharding if name in self._batch_args \
            else self._replicated
        data = arr._data
        if getattr(data, "sharding", None) != sharding:
            data = jax.device_put(data, sharding)
            arr._set_data(data)
        return data

    def _place_rng(self, key):
        return jax.device_put(key, self._replicated)


class FusedExecutorGroup(object):
    """Drop-in executor-group with the DataParallelExecutorGroup surface,
    backed by one sharded executor (``num_device`` is 1: there is a single
    logical parameter/gradient copy)."""

    num_device = 1

    def __init__(self, symbol, contexts, workload, data_shapes, label_shapes,
                 param_names, for_training, inputs_need_grad,
                 shared_group=None, logger=logging, fixed_param_names=None,
                 grad_req="write", state_names=None):
        self.param_names = list(param_names)
        self.batch_size = data_shapes[0].shape[0]
        if self.batch_size % len(contexts):
            raise ValueError(
                "fused group: batch size %d not divisible by %d devices"
                % (self.batch_size, len(contexts)))
        self._contexts = contexts
        devices = [c.jax_device for c in contexts]
        self._mesh = mesh_mod.make_mesh({"data": len(devices)}, devices)

        fixed = set(fixed_param_names or [])
        batch_args = [d.name for d in data_shapes] + \
            [d.name for d in (label_shapes or [])]
        self._label_names = [d.name for d in (label_shapes or [])]

        arg_dict, grad_dict = {}, {}
        shapes = {d.name: d.shape for d in data_shapes}
        shapes.update({d.name: d.shape for d in (label_shapes or [])})
        dtypes = {d.name: d.dtype
                  for d in list(data_shapes) + list(label_shapes or [])
                  if d.dtype is not None}
        arg_structs, _, aux_structs = symbol._infer(shape_kwargs=shapes,
                                                    dtype_kwargs=dtypes)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        for name, st in zip(arg_names, arg_structs):
            shape = tuple(st.shape)
            arg_dict[name] = nd.zeros(shape, ctx=contexts[0], dtype=st.dtype)
            wants_grad = (for_training and name in self.param_names
                          and name not in fixed)
            if name in batch_args:
                wants_grad = for_training and inputs_need_grad
            if wants_grad and grad_req != "null":
                grad_dict[name] = nd.zeros(shape, ctx=contexts[0],
                                           dtype=st.dtype)
        aux_dict = {name: nd.zeros(tuple(st.shape), ctx=contexts[0],
                                   dtype=st.dtype)
                    for name, st in zip(aux_names, aux_structs)}

        req = {n: ("write" if n in grad_dict else "null")
               for n in arg_names}
        self._exec = _ShardedExecutor(
            symbol, contexts[0], self._mesh, batch_args,
            arg_dict=arg_dict, grad_dict=grad_dict, grad_req=req,
            aux_dict=aux_dict)
        self.execs = [self._exec]
        self._inputs_need_grad = inputs_need_grad
        self._data_names = [d.name for d in data_shapes]

        # one logical copy per param: the interface's per-device lists
        # degenerate to singletons
        self.param_arrays = [[arg_dict[n]] for n in self.param_names
                             if n in arg_dict]
        self.grad_arrays = [[grad_dict[n]] if n in grad_dict else [None]
                            for n in self.param_names]

    # ---- parameter movement ----

    def share_params_with(self, donor):
        """Alias the donor's sharded param/aux NDArrays (see
        DataParallelExecutorGroup.share_params_with — same zero-copy
        bucket-switch contract, single logical copy here)."""
        if type(donor) is not type(self):
            return False
        dex, mex = donor._exec, self._exec
        for names, attr in ((self.param_names, "arg_dict"),
                            (mex.aux_names, "aux_dict")):
            for name in names:
                src = getattr(dex, attr, {}).get(name)
                dst = getattr(mex, attr, {}).get(name)
                if src is None or dst is None or src.shape != dst.shape \
                        or src.dtype != dst.dtype:
                    return False
        for name in self.param_names:
            mex.arg_dict[name] = dex.arg_dict[name]
        for name in mex.aux_names:
            mex.aux_dict[name] = dex.aux_dict[name]
        self.param_arrays = [[mex.arg_dict[n]] for n in self.param_names
                             if n in mex.arg_dict]
        return True

    def set_params(self, arg_params, aux_params, allow_extra=False):
        for name, arr in (arg_params or {}).items():
            if name in self._exec.arg_dict:
                arr.copyto(self._exec.arg_dict[name])
            elif not allow_extra:
                raise ValueError("unknown parameter %s" % name)
        for name, arr in (aux_params or {}).items():
            if name in self._exec.aux_dict:
                arr.copyto(self._exec.aux_dict[name])
            elif not allow_extra:
                raise ValueError("unknown aux state %s" % name)

    def get_params(self, arg_params, aux_params):
        for name, dst in arg_params.items():
            if name in self._exec.arg_dict:
                self._exec.arg_dict[name].copyto(dst)
        for name, dst in aux_params.items():
            if name in self._exec.aux_dict:
                self._exec.aux_dict[name].copyto(dst)

    # ---- computation ----

    def forward(self, data_batch, is_train=None):
        if is_train is None:
            is_train = self._exec.grad_req and any(
                r != "null" for r in self._exec.grad_req.values())
        feed = dict(zip(self._data_names, data_batch.data))
        if data_batch.label:
            feed.update(zip(self._label_names, data_batch.label))
        self._exec.forward(is_train=bool(is_train), **feed)

    def forward_backward(self, data_batch):
        """Fused fwd+bwd in one SPMD program over the mesh."""
        feed = dict(zip(self._data_names, data_batch.data))
        if data_batch.label:
            feed.update(zip(self._label_names, data_batch.label))
        self._exec.forward_backward(**feed)

    def backward(self, out_grads=None):
        self._exec.backward(out_grads=out_grads)

    def get_outputs(self, merge_multi_context=True):
        outs = self._exec.outputs
        return outs if merge_multi_context else [[o] for o in outs]

    def get_input_grads(self, merge_multi_context=True):
        grads = [self._exec.grad_dict.get(n) for n in self._data_names]
        return grads if merge_multi_context else [[g] for g in grads]

    def update_metric(self, eval_metric, labels):
        eval_metric.update(labels, self._exec.outputs)

    def install_monitor(self, mon):
        mon.install(self._exec)
