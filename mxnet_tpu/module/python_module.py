"""PythonModule / PythonLossModule: user-defined modules in pure Python.

API parity with the reference ``python/mxnet/module/python_module.py``
(PythonModule base at :30, PythonLossModule at :185): modules whose
computation is arbitrary Python, used to splice custom losses or glue
stages into a SequentialModule chain. No executors are bound; parameters
are empty by convention (a Python module carrying state manages it
itself).
"""
from __future__ import annotations

import logging

import numpy as np

from .. import ndarray as nd
from .base_module import BaseModule

__all__ = ["PythonModule", "PythonLossModule"]


class PythonModule(BaseModule):
    """Subclass and implement ``forward``/``backward`` in Python
    (ref python_module.py:30). Parameter-free by default."""

    def __init__(self, data_names, label_names, output_names, logger=logging):
        super().__init__(logger=logger)
        self._data_names = list(data_names)
        self._label_names = list(label_names or [])
        self._output_names = list(output_names)
        self._data_shapes = None
        self._label_shapes = None
        self._output_shapes = None

    # ---- properties ----

    @property
    def data_names(self):
        return self._data_names

    @property
    def output_names(self):
        return self._output_names

    @property
    def data_shapes(self):
        return self._data_shapes

    @property
    def label_shapes(self):
        return self._label_shapes

    @property
    def output_shapes(self):
        return self._output_shapes

    # ---- parameters: none by default ----

    def get_params(self):
        return {}, {}

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        self.params_initialized = True

    def update(self):
        pass

    def update_metric(self, eval_metric, labels):
        if self._label_shapes is not None:
            eval_metric.update(labels, self.get_outputs())

    # ---- binding ----

    def bind(self, data_shapes, label_shapes=None, for_training=True,
             inputs_need_grad=False, force_rebind=False, shared_module=None,
             grad_req="write"):
        if self.binded and not force_rebind:
            self.logger.warning("Already bound, ignoring bind()")
            return
        self.for_training = for_training
        self.inputs_need_grad = inputs_need_grad
        self._data_shapes = data_shapes
        self._label_shapes = label_shapes
        self._output_shapes = self._compute_output_shapes()
        self.binded = True

    def _compute_output_shapes(self):
        """Subclasses declare their output shapes from the input shapes."""
        raise NotImplementedError()

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        self.optimizer_initialized = True

    def install_monitor(self, mon):
        pass


class PythonLossModule(PythonModule):
    """A terminal loss stage (ref python_module.py:185): forwards its input
    unchanged; ``backward`` produces the input gradient, either from a
    user ``grad_func`` or a registered default."""

    def __init__(self, name="pyloss", data_names=("data",),
                 label_names=("softmax_label",), logger=logging,
                 grad_func=None):
        super().__init__(data_names, label_names, (name + "_output",),
                         logger=logger)
        self._name = name
        self._scores = None
        self._labels = None
        self._scores_grad = None
        if grad_func is not None and not callable(grad_func):
            raise TypeError("grad_func must be callable")
        self._grad_func = grad_func

    def _compute_output_shapes(self):
        return [(self._name + "_output", self._data_shapes[0].shape)]

    def forward(self, data_batch, is_train=None):
        self._scores = data_batch.data[0]
        if data_batch.label:
            self._labels = data_batch.label[0]

    def get_outputs(self, merge_multi_context=True):
        return [self._scores]

    def backward(self, out_grads=None):
        if self._grad_func is not None:
            grad = self._grad_func(self._scores, self._labels)
            if not isinstance(grad, nd.NDArray):
                grad = nd.array(np.asarray(grad))
            self._scores_grad = grad
        else:
            raise NotImplementedError(
                "PythonLossModule requires grad_func (or override backward)")

    def get_input_grads(self, merge_multi_context=True):
        return [self._scores_grad]
