"""BaseModule: the abstract train/score/predict interface.

API parity with the reference ``python/mxnet/module/base_module.py``
(``fit`` :376-530, ``score`` :212, ``predict`` :272, ``forward_backward``
:189), independently organised: the epoch loop is factored into
``_train_one_epoch`` and callback dispatch into a shared helper.
"""
from __future__ import annotations

import logging
import time

from .. import metric as metric_mod
from .. import ndarray as nd
from ..checkpoint import hooks as _ckpt_hooks
from ..initializer import Uniform
from ..model import BatchEndParam

__all__ = ["BaseModule"]


def _fire(callbacks, payload):
    """Invoke a callback, or each callback in a list, with *payload*."""
    if callbacks is None:
        return
    if not isinstance(callbacks, (list, tuple)):
        callbacks = (callbacks,)
    for cb in callbacks:
        cb(payload)


def _fire_epoch(callbacks, epoch, sym, arg, aux):
    if callbacks is None:
        return
    if not isinstance(callbacks, (list, tuple)):
        callbacks = (callbacks,)
    for cb in callbacks:
        cb(epoch, sym, arg, aux)


def _coerce_metric(m):
    return m if isinstance(m, metric_mod.EvalMetric) else metric_mod.create(m)


def _subclass_must_implement(what):
    return NotImplementedError("subclass responsibility: " + what)


def _check_input_names(symbol, names, typename, throw):
    """Warn (or raise) when a declared data/label name is not a symbol arg."""
    known = symbol.list_arguments()
    weightish = ("_weight", "_bias", "_gamma", "_beta")
    for name in names:
        if name in known:
            continue
        suggestions = [a for a in known
                       if not any(a.endswith(suf) for suf in weightish)]
        msg = ("\033[91mYou created Module with Module(..., %s_names=%s) but "
               "input with name '%s' is not found in symbol.list_arguments(). "
               "Did you mean one of:\n\t%s\033[0m"
               % (typename, str(names), name, "\n\t".join(suggestions)))
        if throw:
            raise ValueError(msg)
        logging.warning(msg)


def _trim_pad(outputs, pad):
    """Drop the last *pad* rows (batch padding) from each output array."""
    if not pad:
        return list(outputs)
    return [out[: out.shape[0] - pad] for out in outputs]


class BaseModule:
    """Shared state flags + the generic training/eval loops.

    Concrete subclasses (Module, BucketingModule, ...) implement the
    computation primitives (bind/forward/backward/update/...); everything
    here is expressed in terms of those primitives only.
    """

    def __init__(self, logger=logging):
        self.logger = logger
        self.binded = self.params_initialized = self.optimizer_initialized = False
        self.for_training = self.inputs_need_grad = False
        self._symbol, self._total_exec_bytes = None, 0

    # ---- high-level driver API ----

    def forward_backward(self, data_batch):
        """One fused fwd+bwd pass (ref base_module.py:189)."""
        self.forward(data_batch, True)
        self.backward()

    def _fit_step(self, data_batch):
        """One fit-loop step: fwd+bwd+update. Subclasses may fuse all
        three into a single compiled program (Module does, when update
        placement allows)."""
        self.forward_backward(data_batch)
        self.update()

    def fit(self, train_data, eval_data=None, eval_metric="acc",
            epoch_end_callback=None, batch_end_callback=None, kvstore="local",
            optimizer="sgd", optimizer_params=None,
            eval_end_callback=None, eval_batch_end_callback=None,
            initializer=None, arg_params=None, aux_params=None,
            allow_missing=False, force_rebind=False, force_init=False,
            begin_epoch=0, num_epoch=None, validation_metric=None,
            monitor=None):
        """Train for ``num_epoch - begin_epoch`` epochs (ref :376-530).

        Sequence per the reference contract: bind → (monitor) → init_params →
        init_optimizer → per-epoch {train pass, epoch callbacks, validation}.
        """
        if num_epoch is None:
            raise ValueError("fit() requires num_epoch")
        self.bind(data_shapes=train_data.provide_data,
                  label_shapes=train_data.provide_label,
                  for_training=True, force_rebind=force_rebind)
        if monitor is not None:
            self.install_monitor(monitor)
        self.init_params(initializer=initializer or Uniform(0.01),
                         arg_params=arg_params, aux_params=aux_params,
                         allow_missing=allow_missing, force_init=force_init)
        self.init_optimizer(
            kvstore=kvstore, optimizer=optimizer,
            optimizer_params=optimizer_params or (("learning_rate", 0.01),))

        train_metric = _coerce_metric(eval_metric)
        val_metric = validation_metric if validation_metric is not None \
            else train_metric

        for epoch in range(begin_epoch, num_epoch):
            started = time.time()
            self._train_one_epoch(train_data, train_metric, epoch,
                                  batch_end_callback, monitor)
            for name, val in train_metric.get_name_value():
                self.logger.info("Epoch[%d] Train-%s=%f", epoch, name, val)
            self.logger.info("Epoch[%d] Time cost=%.3f",
                             epoch, time.time() - started)

            # Sync trained params back into the module's canonical copies so
            # epoch callbacks (checkpointing) observe the latest values.
            arg_now, aux_now = self.get_params()
            self.set_params(arg_now, aux_now)
            _fire_epoch(epoch_end_callback, epoch, self.symbol, arg_now, aux_now)

            if eval_data:
                scored = self.score(eval_data, val_metric, epoch=epoch,
                                    batch_end_callback=eval_batch_end_callback,
                                    score_end_callback=eval_end_callback)
                for name, val in scored:
                    self.logger.info("Epoch[%d] Validation-%s=%f",
                                     epoch, name, val)
            train_data.reset()

    def _train_one_epoch(self, train_data, train_metric, epoch,
                         batch_end_callback, monitor):
        """Inner loop of one training epoch over *train_data*."""
        train_metric.reset()
        for nbatch, batch in enumerate(train_data):
            self.prepare(batch)
            if monitor is not None:
                monitor.tic()
            if monitor is None:
                self._fit_step(batch)
            else:
                self.forward_backward(batch)
                self.update()
            self.update_metric(train_metric, batch.label)
            if monitor is not None:
                monitor.toc_print()
            # step boundary (see gluon/trainer.py): checkpoint snapshot
            # point + pending-SIGTERM honor, with the epoch cursor
            _ckpt_hooks.note_step_boundary(epoch=epoch, batch=nbatch)
            _fire(batch_end_callback,
                  BatchEndParam(epoch=epoch, nbatch=nbatch,
                                eval_metric=train_metric, locals=locals()))

    def score(self, eval_data, eval_metric, num_batch=None,
              batch_end_callback=None, score_end_callback=None, reset=True,
              epoch=0):
        """Evaluate the metric over *eval_data* (ref base_module.py:212)."""
        self._require_ready()
        if reset:
            eval_data.reset()
        eval_metric = _coerce_metric(eval_metric)
        eval_metric.reset()

        seen = 0
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch >= num_batch:
                break
            self.forward(batch, is_train=False)
            self.update_metric(eval_metric, batch.label)
            _fire(batch_end_callback,
                  BatchEndParam(epoch=epoch, nbatch=nbatch,
                                eval_metric=eval_metric, locals=locals()))
            seen += 1
        _fire(score_end_callback,
              BatchEndParam(epoch=epoch, nbatch=seen,
                            eval_metric=eval_metric, locals=locals()))
        return eval_metric.get_name_value()

    def iter_predict(self, eval_data, num_batch=None, reset=True):
        """Yield ``(padded-trimmed outputs, i, batch)`` per batch."""
        self._require_ready()
        if reset:
            eval_data.reset()
        for nbatch, batch in enumerate(eval_data):
            if num_batch is not None and nbatch >= num_batch:
                break
            self.forward(batch, is_train=False)
            yield (_trim_pad(self.get_outputs(), batch.pad or 0),
                   nbatch, batch)

    def predict(self, eval_data, num_batch=None, merge_batches=True,
                reset=True, always_output_list=False):
        """Collect forward outputs over *eval_data* (ref base_module.py:272).

        With ``merge_batches`` the per-batch output lists are concatenated
        along axis 0 into one array per output head.
        """
        per_batch = [[o.copy() for o in outs] for outs, _, _
                     in self.iter_predict(eval_data, num_batch, reset)]
        if not per_batch or not merge_batches:
            return per_batch
        heads = len(per_batch[0])
        if any(len(outs) != heads for outs in per_batch):
            raise ValueError(
                "cannot merge: per-batch output counts differ "
                "(bucketing produces variable head counts)")
        merged = [nd.concatenate([outs[i] for outs in per_batch])
                  for i in range(heads)]
        if heads == 1 and not always_output_list:
            return merged[0]
        return merged

    # ---- parameter management ----

    @property
    def symbol(self):
        return self._symbol

    def get_params(self):
        raise _subclass_must_implement("get_params")

    def init_params(self, initializer=None, arg_params=None, aux_params=None,
                    allow_missing=False, force_init=False, allow_extra=False):
        raise _subclass_must_implement("init_params")

    def set_params(self, arg_params, aux_params, allow_missing=False,
                   force_init=True, allow_extra=False):
        self.init_params(initializer=None, arg_params=arg_params,
                         aux_params=aux_params, allow_missing=allow_missing,
                         force_init=force_init, allow_extra=allow_extra)

    def save_params(self, fname):
        """Write params to *fname* in the ``arg:``/``aux:`` dict format."""
        arg_params, aux_params = self.get_params()
        blob = {}
        for prefix, group in (("arg:", arg_params), ("aux:", aux_params)):
            for name, array in group.items():
                blob[prefix + name] = array
        nd.save(fname, blob)

    def load_params(self, fname):
        """Read params written by :meth:`save_params`."""
        arg_params, aux_params = {}, {}
        for key, array in nd.load(fname).items():
            kind, _, name = key.partition(":")
            if kind == "arg":
                arg_params[name] = array
            elif kind == "aux":
                aux_params[name] = array
            else:
                raise ValueError("unrecognised key %r in %s" % (key, fname))
        self.set_params(arg_params, aux_params)

    def get_states(self, merge_multi_context=True):
        self._require_ready()
        return []

    def set_states(self, states=None, value=None):
        self._require_ready()

    def install_monitor(self, mon):
        raise _subclass_must_implement("install_monitor")

    def prepare(self, data_batch):
        """Hook called before each training batch (sparse row-id prefetch
        in the reference); default no-op."""

    def _require_ready(self):
        if not (self.binded and self.params_initialized):
            raise AssertionError("module must be binded and initialized")

    # ---- abstract properties ----

    @property
    def data_names(self):
        raise _subclass_must_implement("data_names")

    @property
    def output_names(self):
        raise _subclass_must_implement("output_names")

    @property
    def data_shapes(self):
        raise _subclass_must_implement("data_shapes")

    @property
    def label_shapes(self):
        raise _subclass_must_implement("label_shapes")

    @property
    def output_shapes(self):
        raise _subclass_must_implement("output_shapes")

    # ---- abstract computation primitives ----

    def forward(self, data_batch, is_train=None):
        raise _subclass_must_implement("forward")

    def backward(self, out_grads=None):
        raise _subclass_must_implement("backward")

    def get_outputs(self, merge_multi_context=True):
        raise _subclass_must_implement("get_outputs")

    def get_input_grads(self, merge_multi_context=True):
        raise _subclass_must_implement("get_input_grads")

    def update(self):
        raise _subclass_must_implement("update")

    def update_metric(self, eval_metric, labels):
        raise _subclass_must_implement("update_metric")

    def bind(self, data_shapes, label_shapes=None,
             for_training=True, inputs_need_grad=False, force_rebind=False,
             shared_module=None, grad_req="write"):
        raise _subclass_must_implement("bind")

    def init_optimizer(self, kvstore="local", optimizer="sgd",
                       optimizer_params=(("learning_rate", 0.01),),
                       force_init=False):
        raise _subclass_must_implement("init_optimizer")
