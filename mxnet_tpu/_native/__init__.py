"""ctypes bindings to the native runtime (native/ → lib*.so).

Reference analogue: the ctypes bridge in ``python/mxnet/base.py`` loading
``libmxnet.so``.  Here the native surface is split per subsystem
(RecordIO codec, threaded image loader, dependency engine; SURVEY §2.1).
Binding is optional: when a shared object hasn't been built
(``make -C native``), callers fall back to pure-python implementations of
the identical contract.
"""
from __future__ import annotations

import ctypes
import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
_LOADED = {}              # so_name -> CDLL | None (memoized, incl. misses)


def load_shared(so_name, required_symbol=None):
    """Load ``so_name`` from the package dir, lazily building it with the
    in-image toolchain on first miss (serialized via a per-target lock
    file so concurrent workers don't race the same ``make``).  Returns a
    CDLL or None.  Memoized per name — a failed build is not retried.

    ``required_symbol`` guards against a stale prebuilt library: when
    the loaded object lacks the symbol, it is rebuilt once from source
    and reloaded (gitignored .so files can predate an ABI addition).
    """
    if so_name in _LOADED:
        return _LOADED[so_name]
    lib = _load_uncached(so_name)
    if lib is not None and required_symbol is not None and \
            not hasattr(lib, required_symbol):
        try:
            os.remove(os.path.join(_DIR, so_name))
        except OSError:
            pass
        lib = _load_uncached(so_name)
        if lib is not None and not hasattr(lib, required_symbol):
            lib = None          # still stale: degrade to the fallback
    _LOADED[so_name] = lib
    return lib


def _load_uncached(so_name):
    so_path = os.path.join(_DIR, so_name)
    if not os.path.exists(so_path) and \
            os.environ.get("MXNET_TPU_BUILD_NATIVE", "1") == "1":
        _try_build(so_path)
    if not os.path.exists(so_path):
        return None
    try:
        return ctypes.CDLL(so_path)
    except OSError:
        # corrupt or ABI-incompatible artifact: rebuild once, then degrade
        # to the pure-Python fallback (callers expect CDLL-or-None)
        try:
            os.remove(so_path)
        except OSError:
            return None
        if os.environ.get("MXNET_TPU_BUILD_NATIVE", "1") == "1":
            _try_build(so_path)
        if os.path.exists(so_path):
            try:
                return ctypes.CDLL(so_path)
            except OSError:
                return None
        return None


def _try_build(so_path):
    native_dir = os.path.join(os.path.dirname(_DIR), "..", "native")
    if not os.path.isdir(native_dir):
        return False
    import logging
    logging.getLogger("mxnet_tpu").info(
        "building %s (one-time; set MXNET_TPU_BUILD_NATIVE=0 to skip)",
        os.path.basename(so_path))
    lock_path = so_path + ".build.lock"
    try:
        import fcntl
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if os.path.exists(so_path):      # another process built it
                return True
            subprocess.run(["make", "-C", native_dir,
                            os.path.relpath(so_path, native_dir)],
                           check=True, capture_output=True, timeout=120)
        return os.path.exists(so_path)
    except Exception:
        return False


_lib = None
_tried = False


def lib():
    """The RecordIO codec CDLL, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    l = load_shared("librecordio.so")
    if l is None:
        return None
    l.MXRIOWriterCreate.restype = ctypes.c_void_p
    l.MXRIOWriterCreate.argtypes = [ctypes.c_char_p]
    l.MXRIOWrite.restype = ctypes.c_int
    l.MXRIOWrite.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.c_uint64]
    l.MXRIOWriterTell.restype = ctypes.c_int64
    l.MXRIOWriterTell.argtypes = [ctypes.c_void_p]
    l.MXRIOWriterFree.restype = None
    l.MXRIOWriterFree.argtypes = [ctypes.c_void_p]
    l.MXRIOReaderCreate.restype = ctypes.c_void_p
    l.MXRIOReaderCreate.argtypes = [ctypes.c_char_p]
    l.MXRIORead.restype = ctypes.c_int
    l.MXRIORead.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_char_p),
                            ctypes.POINTER(ctypes.c_uint64)]
    l.MXRIOReaderTell.restype = ctypes.c_int64
    l.MXRIOReaderTell.argtypes = [ctypes.c_void_p]
    l.MXRIOReaderSeek.restype = ctypes.c_int
    l.MXRIOReaderSeek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    l.MXRIOReaderFree.restype = None
    l.MXRIOReaderFree.argtypes = [ctypes.c_void_p]
    _lib = l
    return _lib


def available():
    return lib() is not None
