"""ctypes bindings to the native runtime (native/ → librecordio.so).

Reference analogue: the ctypes bridge in ``python/mxnet/base.py`` loading
``libmxnet.so``.  Here the native surface is the IO substrate (RecordIO
codec; SURVEY §2.1 "Data IO (native)").  Binding is optional: when the
shared object hasn't been built (``make -C native``), callers fall back to
the pure-python implementation of the identical wire format.
"""
from __future__ import annotations

import ctypes
import os
import subprocess

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "librecordio.so")
_lib = None
_tried = False


def _try_build():
    """Best-effort lazy build with the in-image toolchain (g++).

    Serialized via a lock file so concurrent DataLoader workers don't race
    the same `make`; logs one line so the (up to ~min) compile isn't a
    silent stall.
    """
    native_dir = os.path.join(os.path.dirname(_DIR), "..", "native")
    if not os.path.isdir(native_dir):
        return False
    import logging
    logging.getLogger("mxnet_tpu").info(
        "building native recordio codec (one-time; set "
        "MXNET_TPU_BUILD_NATIVE=0 to skip)")
    lock_path = os.path.join(_DIR, ".build.lock")
    try:
        import fcntl
        with open(lock_path, "w") as lock:
            fcntl.flock(lock, fcntl.LOCK_EX)
            if os.path.exists(_SO):      # another process built it
                return True
            subprocess.run(["make", "-C", native_dir,
                            os.path.relpath(_SO, native_dir)],
                           check=True, capture_output=True, timeout=120)
        return os.path.exists(_SO)
    except Exception:
        return False


def lib():
    """The loaded CDLL, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    if not os.path.exists(_SO) and \
            os.environ.get("MXNET_TPU_BUILD_NATIVE", "1") == "1":
        _try_build()
    if not os.path.exists(_SO):
        return None
    l = ctypes.CDLL(_SO)
    l.MXRIOWriterCreate.restype = ctypes.c_void_p
    l.MXRIOWriterCreate.argtypes = [ctypes.c_char_p]
    l.MXRIOWrite.restype = ctypes.c_int
    l.MXRIOWrite.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                             ctypes.c_uint64]
    l.MXRIOWriterTell.restype = ctypes.c_int64
    l.MXRIOWriterTell.argtypes = [ctypes.c_void_p]
    l.MXRIOWriterFree.restype = None
    l.MXRIOWriterFree.argtypes = [ctypes.c_void_p]
    l.MXRIOReaderCreate.restype = ctypes.c_void_p
    l.MXRIOReaderCreate.argtypes = [ctypes.c_char_p]
    l.MXRIORead.restype = ctypes.c_int
    l.MXRIORead.argtypes = [ctypes.c_void_p,
                            ctypes.POINTER(ctypes.c_char_p),
                            ctypes.POINTER(ctypes.c_uint64)]
    l.MXRIOReaderTell.restype = ctypes.c_int64
    l.MXRIOReaderTell.argtypes = [ctypes.c_void_p]
    l.MXRIOReaderSeek.restype = ctypes.c_int
    l.MXRIOReaderSeek.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    l.MXRIOReaderFree.restype = None
    l.MXRIOReaderFree.argtypes = [ctypes.c_void_p]
    _lib = l
    return _lib


def available():
    return lib() is not None
