"""ctypes binding to the native threaded dependency engine
(native/engine.cc -> libengine.so).

Reference analogue: the C API surface of the dependency engine
(``include/mxnet/engine.h:95-280``) that every subsystem schedules
through.  Here the native engine schedules *host-side* tasks (IO,
checkpoint, transport) — device work is XLA/PJRT's job — but the
dependency protocol (const/mutable vars, serialized writes, parallel
reads, WaitForVar/WaitForAll) is the same observable contract
(SURVEY §3.3).

Binding is optional: when the shared object is missing and cannot be
built, ``lib()`` returns None and the Python facade degrades to
synchronous inline execution.
"""
from __future__ import annotations

import ctypes

from . import load_shared

_lib = None
_tried = False

TASK_FN = ctypes.CFUNCTYPE(None, ctypes.c_void_p)


def lib():
    """The loaded CDLL, or None when unavailable."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    l = load_shared("libengine.so", required_symbol="MXEngineFreeAsync")
    if l is None:
        return None
    l.MXEngineCreate.restype = ctypes.c_void_p
    l.MXEngineCreate.argtypes = [ctypes.c_int, ctypes.c_int]
    l.MXEngineFree.restype = None
    l.MXEngineFree.argtypes = [ctypes.c_void_p]
    l.MXEngineFreeAsync.restype = None
    l.MXEngineFreeAsync.argtypes = [ctypes.c_void_p]
    l.MXEngineNewVariable.restype = ctypes.c_int64
    l.MXEngineNewVariable.argtypes = [ctypes.c_void_p]
    l.MXEngineDeleteVariable.restype = None
    l.MXEngineDeleteVariable.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    l.MXEnginePushAsync.restype = None
    l.MXEnginePushAsync.argtypes = [
        ctypes.c_void_p, TASK_FN, ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int,
        ctypes.POINTER(ctypes.c_int64), ctypes.c_int, ctypes.c_int]
    l.MXEngineWaitForVar.restype = None
    l.MXEngineWaitForVar.argtypes = [ctypes.c_void_p, ctypes.c_int64]
    l.MXEngineWaitForAll.restype = None
    l.MXEngineWaitForAll.argtypes = [ctypes.c_void_p]
    l.MXEnginePendingTasks.restype = ctypes.c_int
    l.MXEnginePendingTasks.argtypes = [ctypes.c_void_p]
    l.MXEngineSetSync.restype = None
    l.MXEngineSetSync.argtypes = [ctypes.c_void_p, ctypes.c_int]
    _lib = l
    return _lib


def available():
    return lib() is not None
