"""Testing framework (reference ``python/mxnet/test_utils.py``, 1540 LoC).

The de-facto test harness of the reference (SURVEY §4): numeric-gradient
checking as the universal op-correctness oracle, symbolic forward/backward
vs numpy references, cross-context consistency, sparse random generators,
and dtype-scaled tolerances.  Ported TPU-native: contexts resolve to jax
devices; ``check_consistency`` compares eager vs jit (the analogue of the
reference's CPU↔GPU comparison) and cpu↔accelerator when one is attached.
"""
from __future__ import annotations

import functools
import time

import numpy as np

from .base import MXNetError
from .context import Context, cpu, current_context
from . import ndarray as nd
from . import random as _random
from .ndarray import NDArray
from . import symbol as sym
from .symbol import Symbol
from . import autograd

__all__ = ["default_context", "set_default_context", "default_dtype",
           "assert_almost_equal", "almost_equal", "same", "rand_shape_nd",
           "rand_shape_2d", "rand_shape_3d", "rand_ndarray", "rand_sparse_ndarray",
           "random_arrays", "check_numeric_gradient", "check_symbolic_forward",
           "check_symbolic_backward", "check_consistency", "check_speed",
           "numeric_grad", "simple_forward", "retry"]

_default_ctx = None


def default_context():
    """Current default test context (reference common.py:50)."""
    return _default_ctx or current_context()


def set_default_context(ctx):
    global _default_ctx
    _default_ctx = ctx


def default_dtype():
    return np.float32


def _dtype_tol(dtype):
    dtype = np.dtype(dtype)
    if dtype == np.float16:
        return 1e-1, 1e-2
    if dtype == np.float32:
        return 1e-3, 1e-4
    return 1e-5, 1e-7


def same(a, b):
    return np.array_equal(np.asarray(a), np.asarray(b))


def almost_equal(a, b, rtol=None, atol=None):
    a, b = np.asarray(a), np.asarray(b)
    rtol = rtol if rtol is not None else _dtype_tol(a.dtype)[0]
    atol = atol if atol is not None else _dtype_tol(a.dtype)[1]
    return np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b")):
    """Assert closeness with dtype-scaled tolerances
    (reference test_utils.py:467)."""
    if isinstance(a, NDArray):
        a = a.asnumpy()
    if isinstance(b, NDArray):
        b = b.asnumpy()
    a, b = np.asarray(a), np.asarray(b)
    rtol = rtol if rtol is not None else _dtype_tol(a.dtype)[0]
    atol = atol if atol is not None else _dtype_tol(a.dtype)[1]
    if np.allclose(a, b, rtol=rtol, atol=atol, equal_nan=True):
        return
    a, b = np.broadcast_arrays(a, b)  # so the error index is valid
    index = np.unravel_index(
        np.argmax(np.abs(a - b)), a.shape) if a.shape else ()
    rel = np.max(np.abs(a - b) / (np.abs(b) + atol))
    raise AssertionError(
        "Error %f exceeds tolerance rtol=%f, atol=%f. Location of maximum "
        "error: %s, %s=%r, %s=%r"
        % (rel, rtol, atol, str(index), names[0],
           a[index] if a.shape else a, names[1], b[index] if b.shape else b))


def _randint(low, high, size=None):
    """Seed-governed integer draw: ``integers`` on the post-seed
    Generator, ``randint`` on the pre-seed legacy ``np.random`` module
    (the one draw whose name differs between the two surfaces)."""
    rng = _random.host_rng()
    draw = getattr(rng, "integers", None) or rng.randint
    return draw(low, high, size=size)


def rand_shape_nd(ndim, dim=10):
    return tuple(int(d) for d in _randint(1, dim + 1, size=ndim))


def rand_shape_2d(dim0=10, dim1=10):
    return tuple(int(_randint(1, d + 1)) for d in (dim0, dim1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return tuple(int(_randint(1, d + 1)) for d in (dim0, dim1, dim2))


def random_arrays(*shapes):
    """Random numpy float32 arrays (reference test_utils.py)."""
    rng = _random.host_rng()
    arrays = [rng.standard_normal(s).astype(np.float32) if s else
              np.float32(rng.standard_normal()) for s in shapes]
    return arrays[0] if len(arrays) == 1 else arrays


def rand_ndarray(shape, stype="default", density=None, dtype=None,
                 ctx=None):
    """Random dense/sparse NDArray (reference rand_ndarray/rand_sparse)."""
    if stype == "default":
        return nd.array(_random.host_rng().uniform(-1, 1, shape), ctx=ctx,
                        dtype=dtype or np.float32)
    arr, _ = rand_sparse_ndarray(shape, stype, density=density, dtype=dtype)
    return arr


def rand_sparse_ndarray(shape, stype, density=None, dtype=None,
                        data_init=None, rsp_indices=None):
    """Random sparse NDArray + dense numpy twin
    (reference test_utils.py:254)."""
    from .ndarray import sparse as sp
    density = 0.5 if density is None else density
    dtype = dtype or np.float32
    if stype == "row_sparse":
        num_rows = shape[0]
        if rsp_indices is not None:
            indices = np.asarray(rsp_indices)
        else:
            idx_mask = _random.host_rng().random(num_rows) < density
            indices = np.nonzero(idx_mask)[0]
        dense = np.zeros(shape, dtype=dtype)
        if len(indices):
            vals = _random.host_rng().uniform(
                -1, 1, (len(indices),) + shape[1:])
            if data_init is not None:
                vals[:] = data_init
            dense[indices] = vals
        arr = sp.row_sparse_array(
            (dense[indices], indices), shape=shape, dtype=dtype) \
            if len(indices) else sp.zeros("row_sparse", shape, dtype=dtype)
        return arr, dense
    if stype == "csr":
        rng = _random.host_rng()
        dense = rng.uniform(0, 1, shape).astype(dtype)
        dense[rng.random(shape) >= density] = 0
        arr = sp.csr_matrix(dense, shape=shape, dtype=dtype)
        return arr, dense
    raise ValueError("unknown stype %s" % stype)


def numeric_grad(f, xs, eps=1e-4):
    """Central-difference gradients of scalar f wrt list of numpy arrays.

    Uses ``.flat`` indexing (valid for any memory layout — ``reshape(-1)``
    would silently copy non-contiguous arrays and lose the perturbation).
    """
    grads = []
    for i, x in enumerate(xs):
        g = np.zeros_like(x, dtype=np.float64)
        for j in range(x.size):
            orig = x.flat[j]
            x.flat[j] = orig + eps
            fp = f(xs)
            x.flat[j] = orig - eps
            fm = f(xs)
            x.flat[j] = orig
            g.flat[j] = (fp - fm) / (2 * eps)
        grads.append(g)
    return grads


def check_numeric_gradient(sym_or_fn, location, aux_states=None,
                           numeric_eps=1e-3, rtol=1e-2, atol=None,
                           grad_nodes=None, ctx=None):
    """Finite-difference vs autograd — the universal op-correctness oracle
    (reference test_utils.py:789).

    ``sym_or_fn``: a Symbol (single output; reduced by sum to a scalar) or a
    callable taking NDArrays and returning an NDArray.
    ``location``: list or dict of input numpy arrays.
    """
    if isinstance(location, (list, tuple)):
        loc_arrays = [np.ascontiguousarray(a, dtype=np.float64)
                      for a in location]
        names = None
    else:
        names = list(location.keys())
        loc_arrays = [np.ascontiguousarray(location[k], dtype=np.float64)
                      for k in names]

    is_symbol = isinstance(sym_or_fn, Symbol)
    if is_symbol and names is None:
        names = sym_or_fn.list_arguments()

    if grad_nodes is None:
        grad_idx = list(range(len(loc_arrays)))
    elif names is not None:
        grad_idx = [names.index(g) for g in grad_nodes]
    else:
        raise ValueError(
            "grad_nodes requires named inputs: pass location as a dict "
            "(or a Symbol, whose argument names are used)")

    if is_symbol:
        # symbolic path: grads come from the executor's compiled backward
        # (the eager tape does not see inside Executor.forward)
        args = {k: nd.array(a.astype(np.float32))
                for k, a in zip(names, loc_arrays)}
        grad_dict = {names[i]: nd.zeros(loc_arrays[i].shape,
                                        dtype=np.float32)
                     for i in grad_idx}
        aux = {k: nd.array(v) for k, v in (aux_states or {}).items()}
        ex = sym_or_fn.bind(ctx or default_context(), args,
                            args_grad=grad_dict, grad_req="write",
                            aux_states=aux)
        outs = ex.forward(is_train=True)
        ex.backward([nd.ones_like(o) for o in outs])
        sym_grads = [grad_dict[names[i]].asnumpy() for i in grad_idx]

        def scalar_f(xs):
            a = {k: nd.array(x.astype(np.float32))
                 for k, x in zip(names, xs)}
            e = sym_or_fn.bind(ctx or default_context(), a,
                               grad_req="null", aux_states=aux)
            return float(sum(o.sum().asnumpy()
                             for o in e.forward(is_train=True)))
    else:
        fn = sym_or_fn
        # autograd gradients via the eager tape
        inputs = [nd.array(a.astype(np.float32)) for a in loc_arrays]
        grads = [nd.zeros(a.shape, dtype=np.float32) for a in loc_arrays]
        for i in grad_idx:
            autograd.mark_variables([inputs[i]], [grads[i]])
        with autograd.record():
            out = fn(*inputs)
            loss = out.sum() if np.prod(out.shape) > 1 else out
        loss.backward()
        sym_grads = [grads[i].asnumpy() for i in grad_idx]

        def scalar_f(xs):
            ins = [nd.array(x.astype(np.float32)) for x in xs]
            o = fn(*ins)
            return float(o.sum().asnumpy() if np.prod(o.shape) > 1
                         else o.asnumpy())

    # perturb only the requested inputs (numeric_grad mutates in place, so
    # handing it the subset is equivalent and skips wasted forward passes)
    subset = [loc_arrays[i] for i in grad_idx]
    num_grads = numeric_grad(lambda _: scalar_f(loc_arrays), subset,
                             eps=numeric_eps)

    for i, (sg, ng) in enumerate(zip(sym_grads, num_grads)):
        assert_almost_equal(sg, ng, rtol=rtol,
                            atol=atol if atol is not None else rtol * 1e-1,
                            names=("autograd[%d]" % i, "numeric[%d]" % i))


def check_symbolic_forward(symbol, location, expected, rtol=1e-4, atol=None,
                           aux_states=None, ctx=None):
    """Forward outputs vs numpy expectations (reference :921)."""
    if isinstance(location, (list, tuple)):
        location = dict(zip(symbol.list_arguments(), location))
    args = {k: nd.array(v) for k, v in location.items()}
    aux = {k: nd.array(v) for k, v in (aux_states or {}).items()}
    ex = symbol.bind(ctx or default_context(), args, grad_req="null",
                     aux_states=aux)
    outputs = ex.forward(is_train=False)
    for out, exp in zip(outputs, expected):
        assert_almost_equal(out.asnumpy(), exp, rtol=rtol, atol=atol)
    return outputs


def check_symbolic_backward(symbol, location, out_grads, expected,
                            rtol=1e-4, atol=None, grad_req="write",
                            aux_states=None, ctx=None):
    """Backward grads vs numpy expectations (reference :995)."""
    arg_names = symbol.list_arguments()
    if isinstance(location, (list, tuple)):
        location = dict(zip(arg_names, location))
    if isinstance(expected, (list, tuple)):
        expected = dict(zip(arg_names, expected))
    args = {k: nd.array(v) for k, v in location.items()}
    grad_dict = {k: nd.zeros(np.asarray(v).shape)
                 for k, v in location.items()}
    aux = {k: nd.array(v) for k, v in (aux_states or {}).items()}
    ex = symbol.bind(ctx or default_context(), args, args_grad=grad_dict,
                     grad_req=grad_req, aux_states=aux)
    ex.forward(is_train=True)
    ex.backward([nd.array(g) for g in out_grads] if
                isinstance(out_grads, (list, tuple)) else
                [nd.array(out_grads)])
    for name, exp in expected.items():
        assert_almost_equal(grad_dict[name].asnumpy(), exp, rtol=rtol,
                            atol=atol, names=(name + "_grad", "expected"))
    return grad_dict


def check_consistency(sym, ctx_list=None, location=None, scale=1.0,
                      rtol=1e-3, atol=1e-4):
    """Run the same symbol eagerly-bound on multiple contexts and
    cross-compare outputs (reference :1203; the CPU↔GPU matrix becomes
    cpu↔accelerator and jit↔eager on TPU builds)."""
    from .context import num_tpus, tpu
    if ctx_list is None:
        ctx_list = [cpu()]
        if num_tpus():
            ctx_list.append(tpu())
    assert location is not None, "provide location={name: ndarray}"
    args0 = {}
    for k, v in location.items():
        v = np.asarray(v)
        # keep integer inputs integer (index/token ops); narrow floats to f32
        args0[k] = v.astype(np.float32) if v.dtype.kind == "f" else v
    outs = []
    for ctx in ctx_list:
        args = {k: nd.array(v, ctx=ctx) for k, v in args0.items()}
        ex = sym.bind(ctx, args, grad_req="null")
        outs.append([o.asnumpy() for o in ex.forward(is_train=False)])
    ref = outs[0]
    for other, ctx in zip(outs[1:], ctx_list[1:]):
        for a, b in zip(ref, other):
            assert_almost_equal(a, b, rtol=rtol, atol=atol,
                                names=(str(ctx_list[0]), str(ctx)))
    return outs


def check_speed(sym_or_fn, location=None, ctx=None, n=20, typ="whole"):
    """Time forward passes (reference :1129). Only whole-pass timing is
    meaningful under XLA (there is no separate per-op schedule to time)."""
    assert typ == "whole", "only typ='whole' is supported on the XLA build"
    ctx = ctx or default_context()
    if isinstance(sym_or_fn, Symbol):
        args = {k: nd.array(v, ctx=ctx) for k, v in (location or {}).items()}
        ex = sym_or_fn.bind(ctx, args, grad_req="null")
        ex.forward()
        [o.wait_to_read() for o in ex.outputs]
        t0 = time.time()
        for _ in range(n):
            outs = ex.forward()
        [o.wait_to_read() for o in outs]
        return (time.time() - t0) / n
    fn = sym_or_fn
    fn()
    t0 = time.time()
    for _ in range(n):
        out = fn()
    if isinstance(out, NDArray):
        out.wait_to_read()
    return (time.time() - t0) / n


def simple_forward(sym, ctx=None, is_train=False, **inputs):
    """Feed numpy kwargs, return numpy outputs (reference :569)."""
    ctx = ctx or default_context()
    args = {k: nd.array(v, ctx=ctx) for k, v in inputs.items()}
    ex = sym.bind(ctx, args, grad_req="null")
    outputs = [o.asnumpy() for o in ex.forward(is_train=is_train)]
    return outputs[0] if len(outputs) == 1 else outputs


def retry(n):
    """Retry-flaky decorator (reference :550)."""
    assert n > 0

    def decorate(f):
        @functools.wraps(f)
        def wrapper(*args, **kwargs):
            for i in range(n):
                try:
                    return f(*args, **kwargs)
                except AssertionError as e:
                    if i == n - 1:
                        raise e
                    # perturb the framework seed so the retry draws fresh
                    # data (host Generator AND traced key stream move)
                    _random.seed(int(_randint(0, 100000)))
        return wrapper
    return decorate


def _synthetic_digits(n, rng, protos):
    """Procedural MNIST stand-in: one shared noisy prototype per class.

    The reference's get_mnist downloads the real dataset
    (ref test_utils.py dataset helpers); this build has no egress, so tests
    and examples fall back to a same-shape synthetic set that an MLP can
    learn to >97%. The prototypes are shared between train and test splits.
    """
    labels = rng.randint(0, 10, n)
    images = protos[labels] + rng.normal(0, 0.3, (n, 28, 28)).astype(
        np.float32)
    return np.clip(images, 0.0, 1.0)[:, None, :, :], labels.astype(
        np.float32)


def get_mnist(path="data"):
    """MNIST arrays: real idx files under *path* if present, else synthetic.

    Returns dict(train_data, train_label, test_data, test_label), images
    NCHW float32 in [0, 1].
    """
    import os
    names = ["train-images-idx3-ubyte", "train-labels-idx1-ubyte",
             "t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"]
    files = [os.path.join(path, n) for n in names]
    if all(os.path.exists(f) for f in files):
        from .io import _read_idx_file
        tr_x = _read_idx_file(files[0]).astype(np.float32) / 255.0
        tr_y = _read_idx_file(files[1]).astype(np.float32)
        te_x = _read_idx_file(files[2]).astype(np.float32) / 255.0
        te_y = _read_idx_file(files[3]).astype(np.float32)
        return {"train_data": tr_x[:, None, :, :], "train_label": tr_y,
                "test_data": te_x[:, None, :, :], "test_label": te_y}
    rng = np.random.RandomState(42)
    protos = rng.rand(10, 28, 28).astype(np.float32)
    tr_x, tr_y = _synthetic_digits(4096, rng, protos)
    te_x, te_y = _synthetic_digits(1024, rng, protos)
    return {"train_data": tr_x, "train_label": tr_y,
            "test_data": te_x, "test_label": te_y}


def get_mnist_iterator(batch_size, flat=False, path="data"):
    """(train_iter, val_iter) over get_mnist arrays (ref get_mnist_iterator)."""
    from .io import NDArrayIter
    blob = get_mnist(path)
    tr_x, te_x = blob["train_data"], blob["test_data"]
    if flat:
        tr_x = tr_x.reshape(tr_x.shape[0], -1)
        te_x = te_x.reshape(te_x.shape[0], -1)
    train = NDArrayIter(tr_x, blob["train_label"], batch_size, shuffle=True)
    val = NDArrayIter(te_x, blob["test_label"], batch_size)
    return train, val
