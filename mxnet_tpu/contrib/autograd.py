"""Old-style contrib autograd API (reference python/mxnet/contrib/autograd.py).

Thin aliases over the first-class ``mxnet_tpu.autograd`` scopes so code
written against the 2017 contrib surface keeps running.
"""
from ..autograd import (backward, grad, is_recording as _is_recording,
                        mark_variables, pause, record,
                        set_recording as set_is_training)
from ..autograd import record as train_section          # noqa: F401
from ..autograd import pause as test_section            # noqa: F401

__all__ = ["set_is_training", "mark_variables", "backward", "grad",
           "train_section", "test_section", "compute_gradient"]


def compute_gradient(outputs):
    """Compute gradients of outputs w.r.t. marked variables
    (ref contrib/autograd.py:compute_gradient)."""
    backward(outputs)
