"""TensorBoard logging callback (reference python/mxnet/contrib/tensorboard.py).

``LogMetricsCallback`` mirrors the reference API. When a SummaryWriter
implementation is importable (``torch.utils.tensorboard`` or the
standalone ``tensorboardX``) scalars go to real event files; otherwise
they append to ``<logging_dir>/scalars.jsonl`` (one
``{"step", "tag", "value"}`` object per line) so the callback works in
hermetic environments.
"""
from __future__ import annotations

import json
import os
import time

__all__ = ["LogMetricsCallback"]


def _make_writer(logging_dir):
    try:
        from torch.utils.tensorboard import SummaryWriter
        return SummaryWriter(logging_dir)
    except Exception:
        pass
    try:
        from tensorboardX import SummaryWriter
        return SummaryWriter(logging_dir)
    except Exception:
        return None


class _JsonlWriter:
    def __init__(self, logging_dir):
        os.makedirs(logging_dir, exist_ok=True)
        self._path = os.path.join(logging_dir, "scalars.jsonl")

    def add_scalar(self, tag, value, global_step=None):
        with open(self._path, "a") as fh:
            fh.write(json.dumps({"time": time.time(), "step": global_step,
                                 "tag": tag, "value": float(value)}) + "\n")

    def flush(self):
        pass


class LogMetricsCallback(object):
    """Batch-end callback streaming the eval metric to TensorBoard
    (ref contrib/tensorboard.py:LogMetricsCallback)."""

    def __init__(self, logging_dir, prefix=None):
        self.prefix = prefix
        self.step = 0
        self._writer = _make_writer(logging_dir) or _JsonlWriter(logging_dir)

    def __call__(self, param):
        self.step += 1
        if param.eval_metric is None:
            return
        for name, value in param.eval_metric.get_name_value():
            if self.prefix is not None:
                name = "%s-%s" % (self.prefix, name)
            self._writer.add_scalar(name, value, self.step)
