"""Device contexts for a TPU-native runtime.

Parity surface: reference ``python/mxnet/context.py`` (``Context``, ``cpu()``,
``gpu()``, ``current_context()``).  TPU-first redesign: contexts resolve to JAX
devices; ``tpu(i)`` is first-class; ``gpu(i)`` is accepted for source
compatibility with reference examples and resolves to the i-th accelerator
(TPU chip here).  A context can also wrap a whole ``jax.sharding.Mesh`` for
SPMD execution (``Context.mesh``) — the TPU replacement for MXNet's
"list of contexts" data-parallel idiom.
"""
from __future__ import annotations

import threading

import jax

__all__ = ["Context", "cpu", "gpu", "tpu", "cpu_pinned", "current_context",
           "num_gpus", "num_tpus", "device_mesh"]

_DEVTYPE2ID = {"cpu": 1, "gpu": 2, "cpu_pinned": 3, "tpu": 6}
_ID2DEVTYPE = {v: k for k, v in _DEVTYPE2ID.items()}


def _accelerator_devices():
    """All non-CPU JAX devices, else CPU devices (test/CI fallback)."""
    devs = [d for d in jax.devices() if d.platform != "cpu"]
    return devs if devs else jax.devices()


class Context:
    """A device context. Constructing it never allocates; it is a name.

    Reference semantics kept: ``Context('cpu', 0)``, equality, hashing,
    ``with ctx:`` to set the default, ``device_typeid`` codes for
    serialization.
    """

    _default_ctx = threading.local()
    devtype2str = _ID2DEVTYPE
    devstr2type = _DEVTYPE2ID

    def __init__(self, device_type, device_id=0):
        if isinstance(device_type, Context):
            self.device_type, self.device_id = device_type.device_type, device_type.device_id
        else:
            if device_type not in _DEVTYPE2ID:
                raise ValueError("unknown device type %r" % (device_type,))
            self.device_type = device_type
            self.device_id = device_id
        self._old_ctx = None

    @property
    def device_typeid(self):
        return _DEVTYPE2ID[self.device_type]

    # -- JAX resolution ----------------------------------------------------
    @property
    def jax_device(self):
        """Resolve to a concrete jax.Device.

        ``cpu`` → host CPU backend; ``tpu``/``gpu`` → i-th accelerator
        (falls back to CPU devices when no accelerator is attached, so the
        whole suite runs on a forced-CPU mesh).
        """
        if self.device_type in ("cpu", "cpu_pinned"):
            try:
                cpus = jax.devices("cpu")
            except RuntimeError:
                cpus = jax.devices()
            return cpus[min(self.device_id, len(cpus) - 1)]
        devs = _accelerator_devices()
        if self.device_id >= len(devs):
            raise MXNetErrorForDevice(self, len(devs))
        return devs[self.device_id]

    def __eq__(self, other):
        return (isinstance(other, Context)
                and self.device_type == other.device_type
                and self.device_id == other.device_id)

    def __hash__(self):
        return hash((self.device_type, self.device_id))

    def __str__(self):
        return "%s(%d)" % (self.device_type, self.device_id)

    __repr__ = __str__

    def __enter__(self):
        self._old_ctx = current_context()
        Context._default_ctx.value = self
        return self

    def __exit__(self, *exc):
        Context._default_ctx.value = self._old_ctx

    def empty_cache(self):
        """Parity no-op: XLA owns HBM pooling (reference: GPUPooledStorageManager)."""


def MXNetErrorForDevice(ctx, n):
    from .base import MXNetError
    return MXNetError("Invalid device id %d for %s: only %d device(s) present"
                      % (ctx.device_id, ctx.device_type, n))


def cpu(device_id=0):
    return Context("cpu", device_id)


def cpu_pinned(device_id=0):
    return Context("cpu_pinned", device_id)


def gpu(device_id=0):
    """Source-compat alias: reference examples say ``mx.gpu(i)``; on this
    runtime it names the i-th accelerator chip (TPU)."""
    return Context("gpu", device_id)


def tpu(device_id=0):
    return Context("tpu", device_id)


def current_context():
    if not hasattr(Context._default_ctx, "value"):
        Context._default_ctx.value = Context("cpu", 0)
    return Context._default_ctx.value


def num_gpus():
    """Number of attached accelerator chips (reference: mx.context.num_gpus).

    Returns 0 — never raises — when the accelerator backend fails to
    initialize (e.g. the TPU tunnel is down), so callers can fall back to
    CPU the way reference code treats a CUDA-less build.
    """
    try:
        devs = [d for d in jax.devices() if d.platform != "cpu"]
    except RuntimeError:
        return 0
    return len(devs)


def num_tpus():
    return num_gpus()


def device_mesh(ctx_list=None, axis_name="dp"):
    """Build a 1-D ``jax.sharding.Mesh`` from a context list.

    This is the TPU-native replacement for MXNet's multi-context
    data-parallel idiom (``ctx=[mx.gpu(0), mx.gpu(1), ...]``): instead of one
    executor per device, we build a mesh and shard the batch axis over it.
    """
    from jax.sharding import Mesh
    import numpy as np
    if ctx_list is None:
        devs = _accelerator_devices()
    else:
        devs = [Context(c).jax_device if not isinstance(c, Context) else c.jax_device
                for c in ctx_list]
    return Mesh(np.array(devs), (axis_name,))
