"""Optimizers (parity: reference python/mxnet/optimizer.py:36-1167).

Updates dispatch to the fused update *ops* (``mxnet_tpu/ops/optim_ops.py``,
reference ``src/operator/optimizer_op.cc``) so that under jit the whole
update fuses into the training-step XLA program; pure-python fallbacks cover
the optimizers the reference implements in Python (AdaGrad, AdaDelta, ...).
"""
from __future__ import annotations

import logging
import math
import pickle

import numpy as np

from .base import Registry, MXNetError
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["Optimizer", "SGD", "NAG", "SGLD", "DCASGD", "Adam", "AdaGrad",
           "RMSProp", "AdaDelta", "Ftrl", "Adamax", "Nadam", "Signum",
           "Test", "create", "get_updater", "Updater", "register"]

_REG = Registry("optimizer")


def register(klass):
    _REG.register(klass, klass.__name__)
    return klass


class Optimizer:
    """Base optimizer (reference optimizer.py:36)."""

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            self.lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.lr_mult = {}
        self.wd_mult = {}
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.clip_gradient = clip_gradient
        if param_idx2name is None:
            param_idx2name = {}
        self.idx2name = param_idx2name.copy()
        self.sym_info = None
        if sym is not None:
            self.sym_info = (sym.attr_dict(), sym.list_arguments())
        self.param_dict = param_dict or {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    # -- serialization for kvstore set_optimizer ---------------------------
    @staticmethod
    def create_optimizer(name, **kwargs):
        return _REG.get(name)(**kwargs)

    def create_state(self, index, weight):
        return None

    def update(self, index, weight, grad, state):
        raise NotImplementedError()

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined.")
        self.lr = lr

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = {}
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__lr_mult__" in attr[name]:
                    self.lr_mult[name] = float(attr[name]["__lr_mult__"])
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for n in self.idx2name.values():
            if not (n.endswith("_weight") or n.endswith("_gamma")):
                self.wd_mult[n] = 0.0
        if self.sym_info:
            attr, arg_names = self.sym_info
            for name in arg_names:
                if name in attr and "__wd_mult__" in attr[name]:
                    self.wd_mult[name] = float(attr[name]["__wd_mult__"])
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        if index not in self._index_update_count:
            self._index_update_count[index] = self.begin_num_update
        self._index_update_count[index] += 1
        self.num_update = max(self._index_update_count[index], self.num_update)

    def _get_lr(self, index):
        if self.lr_scheduler is not None:
            lr = self.lr_scheduler(self.num_update)
        else:
            lr = self.lr
        if index in self.param_dict:
            lr *= self.param_dict[index].lr_mult
        elif index in self.lr_mult:
            lr *= self.lr_mult[index]
        elif index in self.idx2name:
            lr *= self.lr_mult.get(self.idx2name[index], 1.0)
        return lr

    def _get_wd(self, index):
        wd = self.wd
        if index in self.param_dict:
            wd *= self.param_dict[index].wd_mult
        elif index in self.wd_mult:
            wd *= self.wd_mult[index]
        elif index in self.idx2name:
            wd *= self.wd_mult.get(self.idx2name[index], 1.0)
        return wd

    def _common_kw(self):
        kw = {"rescale_grad": self.rescale_grad}
        if self.clip_gradient:
            kw["clip_gradient"] = self.clip_gradient
        return kw


@register
class SGD(Optimizer):
    """SGD with momentum and optional multi-precision (reference :434)."""

    def __init__(self, momentum=0.0, lazy_update=True,
                 multi_precision=False, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update
        self.multi_precision = multi_precision

    def create_state(self, index, weight):
        if self.multi_precision and weight.dtype == np.float16:
            w32 = weight.astype(np.float32)
            mom = (nd.zeros(weight.shape, ctx=weight.context,
                            dtype=np.float32) if self.momentum else None)
            return (mom, w32)
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, ctx=weight.context,
                            dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kw()
        if isinstance(state, tuple):  # multi-precision
            mom, w32 = state
            if mom is not None:
                nd._internal.mp_sgd_mom_update(
                    weight, grad, mom, w32, out=weight, lr=lr, wd=wd,
                    momentum=self.momentum, **kw)
            else:
                nd._internal.mp_sgd_update(weight, grad, w32, out=weight,
                                           lr=lr, wd=wd, **kw)
        elif state is not None:
            nd._internal.sgd_mom_update(weight, grad, state, out=weight,
                                        lr=lr, wd=wd,
                                        momentum=self.momentum, **kw)
        else:
            nd._internal.sgd_update(weight, grad, out=weight, lr=lr, wd=wd,
                                    **kw)


register(SGD, )  # default name already registered; keep ccSGD alias:
_REG.register(SGD, "ccsgd")


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (reference :585)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return None
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        if state is not None:
            state *= self.momentum
            state += grad
            grad += self.momentum * state
            weight -= lr * (grad + wd * weight)
        else:
            weight -= lr * (grad + wd * weight)


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (reference :631)."""

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        weight -= lr / 2 * (grad + wd * weight)
        weight += nd.random.normal(0, math.sqrt(lr), shape=weight.shape,
                                   ctx=weight.context, dtype=weight.dtype)


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (reference :560)."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.weight_previous = {}
        self.lamda = lamda

    def create_state(self, index, weight):
        if self.momentum == 0.0:
            return (None, weight.copy())
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                weight.copy())

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        mom, previous_weight = state
        if mom is not None:
            mom *= self.momentum
            mom += -lr * (grad + wd * weight + self.lamda * grad * grad *
                          (weight - previous_weight))
            weight.copyto(previous_weight)
            weight += mom
        else:
            weight += -lr * (grad + wd * weight + self.lamda * grad * grad *
                             (weight - previous_weight))
            weight.copyto(previous_weight)
            # previous updated after


@register
class Adam(Optimizer):
    """Adam (reference :754); dispatches to the fused adam_update op."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype),
                nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        coef1 = 1.0 - self.beta1 ** t
        coef2 = 1.0 - self.beta2 ** t
        # ** 0.5, not math.sqrt: ShardedTrainer.apply_updates patches
        # _index_update_count with traced step counts, so t may be a tracer.
        lr *= coef2 ** 0.5 / coef1
        mean, var = state
        nd._internal.adam_update(weight, grad, mean, var, out=weight, lr=lr,
                                 wd=wd, beta1=self.beta1, beta2=self.beta2,
                                 epsilon=self.epsilon, **self._common_kw())


@register
class AdaGrad(Optimizer):
    """AdaGrad (reference :902)."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context, dtype=weight.dtype)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        history = state
        history += grad * grad
        weight -= lr * (grad / nd.sqrt(history + self.float_stable_eps)
                        + wd * weight)


@register
class RMSProp(Optimizer):
    """RMSProp, centered or not (reference :938)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1 = gamma1
        self.gamma2 = gamma2
        self.centered = centered
        self.epsilon = epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        z = lambda: nd.zeros(weight.shape, ctx=weight.context,
                             dtype=weight.dtype)
        if self.centered:
            return (z(), z(), z())
        return (z(),)

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kw()
        if self.clip_weights:
            kw["clip_weights"] = self.clip_weights
        if not self.centered:
            (n,) = state
            nd._internal.rmsprop_update(weight, grad, n, out=weight, lr=lr,
                                        wd=wd, gamma1=self.gamma1,
                                        epsilon=self.epsilon, **kw)
        else:
            n, g, delta = state
            nd._internal.rmspropalex_update(weight, grad, n, g, delta,
                                            out=weight, lr=lr, wd=wd,
                                            gamma1=self.gamma1,
                                            gamma2=self.gamma2,
                                            epsilon=self.epsilon, **kw)


@register
class AdaDelta(Optimizer):
    """AdaDelta (reference :1004)."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho = rho
        self.epsilon = epsilon

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        wd = self._get_wd(index)
        grad = grad * self.rescale_grad
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        acc_g, acc_delta = state
        acc_g *= self.rho
        acc_g += (1.0 - self.rho) * grad * grad
        current_delta = (nd.sqrt(acc_delta + self.epsilon)
                         / nd.sqrt(acc_g + self.epsilon)) * grad
        acc_delta *= self.rho
        acc_delta += (1.0 - self.rho) * current_delta * current_delta
        weight[:] = weight - current_delta - wd * weight


@register
class Ftrl(Optimizer):
    """FTRL (reference :1040); fused ftrl_update op."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1 = lamda1
        self.beta = beta

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),  # z
                nd.zeros(weight.shape, ctx=weight.context))  # n

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        z, n = state
        nd._internal.ftrl_update(weight, grad, z, n, out=weight, lr=lr,
                                 wd=wd, lamda1=self.lamda1, beta=self.beta,
                                 **self._common_kw())


@register
class Adamax(Optimizer):
    """AdaMax (reference :1084)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        lr /= (1.0 - self.beta1 ** t)
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        m_t, u_t = state
        m_t *= self.beta1
        m_t += (1.0 - self.beta1) * grad
        u_t[:] = nd.maximum(self.beta2 * u_t, nd.abs(grad))
        weight -= lr * m_t / u_t


@register
class Nadam(Optimizer):
    """Nesterov Adam (reference :1119)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        self.schedule_decay = schedule_decay
        self.m_schedule = 1.0

    def create_state(self, index, weight):
        return (nd.zeros(weight.shape, ctx=weight.context),
                nd.zeros(weight.shape, ctx=weight.context))

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        t = self._index_update_count[index]
        grad = grad * self.rescale_grad + wd * weight
        if self.clip_gradient is not None:
            grad = nd.clip(grad, -self.clip_gradient, self.clip_gradient)
        momentum_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        momentum_t_1 = self.beta1 * (1.0 - 0.5 * 0.96 **
                                     ((t + 1) * self.schedule_decay))
        self.m_schedule = self.m_schedule * momentum_t
        m_schedule_next = self.m_schedule * momentum_t_1
        m_t, v_t = state
        m_t *= self.beta1
        m_t += (1.0 - self.beta1) * grad
        v_t *= self.beta2
        v_t += (1.0 - self.beta2) * grad * grad
        grad_prime = grad / (1.0 - self.m_schedule)
        m_t_prime = m_t / (1.0 - m_schedule_next)
        v_t_prime = v_t / (1.0 - self.beta2 ** t)
        m_t_bar = ((1.0 - momentum_t) * grad_prime
                   + momentum_t_1 * m_t_prime)
        weight -= lr * m_t_bar / (nd.sqrt(v_t_prime) + self.epsilon)


@register
class Signum(Optimizer):
    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum = momentum
        self.wd_lh = wd_lh

    def create_state(self, index, weight):
        if self.momentum != 0.0:
            return nd.zeros(weight.shape, ctx=weight.context,
                            dtype=weight.dtype)
        return None

    def update(self, index, weight, grad, state):
        self._update_count(index)
        lr, wd = self._get_lr(index), self._get_wd(index)
        kw = self._common_kw()
        if state is not None:
            nd._internal.signum_update(weight, grad, state, out=weight, lr=lr,
                                       wd=wd, momentum=self.momentum,
                                       wd_lh=self.wd_lh, **kw)
        else:
            nd._internal.signsgd_update(weight, grad, out=weight, lr=lr,
                                        wd=wd, **kw)


@register
class Test(Optimizer):
    """Test optimizer: w -= rescale_grad * grad (reference :1110)."""

    def create_state(self, index, weight):
        return nd.zeros(weight.shape, ctx=weight.context)

    def update(self, index, weight, grad, state):
        weight -= grad * self.rescale_grad
        state[:] = weight


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _REG.get(name)(**kwargs)


class Updater:
    """Stateful per-index updater (reference optimizer.py:1124 get_updater)."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
            self.states_synced[index] = True
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states):
        data = pickle.loads(states)
        if isinstance(data, tuple) and len(data) == 2:
            self.states, opt = data
            if opt is not None:
                self.optimizer = opt
        else:
            self.states = data
        self.states_synced = dict.fromkeys(self.states.keys(), False)

    def get_states(self, dump_optimizer=False):
        return pickle.dumps((self.states, self.optimizer)
                            if dump_optimizer else self.states)


def get_updater(optimizer):
    return Updater(optimizer)
