"""Optimizers built on a pure functional update core.

API parity with the reference ``python/mxnet/optimizer.py:36-1167``
(Optimizer registry, lr/wd multipliers, per-index update counts, Updater
state serialisation, the SGD…Nadam zoo). Independent, TPU-first design:
every optimizer's math lives in one **pure** method

    ``update_step(weight, grad, state, hyper) -> (new_weight, new_state)``

on raw jax arrays (``hyper`` carries lr/wd/t — possibly traced scalars).
The classic mutating ``update(index, weight, grad, state)`` entry point and
the sharded SPMD trainer both call the same pure core, so eager, Module,
and one-program pjit paths are bitwise-identical; under jit the update
fuses into the training-step XLA program exactly like the reference's
fused update ops (``src/operator/optimizer_op.cc``).
"""
from __future__ import annotations

import math
import pickle

import numpy as np
import jax.numpy as jnp

import weakref

from .base import Registry
from . import ndarray as nd
from . import telemetry as _tel
from .ndarray import NDArray
from .ops import optim_ops as _kern

# per-instance jitted update_step programs; kept OUT of the instance so
# optimizers stay picklable (dist set_optimizer, dump_optimizer states)
_JIT_UPDATE_CACHE = weakref.WeakKeyDictionary()
_TRACECHECK_KEEPALIVE = []    # graftcheck specimen optimizers (see below)

__all__ = ["Optimizer", "SGD", "NAG", "SGLD", "DCASGD", "Adam", "AdaGrad",
           "RMSProp", "AdaDelta", "Ftrl", "Adamax", "Nadam", "Signum",
           "Test", "create", "get_updater", "Updater", "register"]

_REG = Registry("optimizer")


def register(klass):
    _REG.register(klass, klass.__name__)
    return klass


def create(name, **kwargs):
    if isinstance(name, Optimizer):
        return name
    return _REG.get(name)(**kwargs)


# ---- state pytree plumbing: NDArray-structured <-> raw jax arrays ----

def _state_raw(state):
    if state is None:
        return None
    if isinstance(state, NDArray):
        return state._data
    return tuple(_state_raw(s) for s in state)


def _state_writeback(state, new_raw):
    """Mutate the NDArray state structure in place with updated arrays."""
    if state is None:
        return
    if isinstance(state, NDArray):
        state._set_data(new_raw)
        return
    for slot, val in zip(state, new_raw):
        _state_writeback(slot, val)


def _zeros_like_nd(weight, dtype=None):
    """Zeros shaped (and *sharded*) like the weight: states must live on
    the same device/mesh placement or eager updates mix devices."""
    from .ndarray.ndarray import _wrap
    data = jnp.zeros_like(weight._data, dtype=dtype or weight.dtype)
    return _wrap(data, weight.context)


def static_hypers(opt):
    """The optimizer scalars BAKED into a compiled update trace
    (momentum, betas, clip_gradient, ...) — the cache-key complement of
    the traced hypers (lr/wd/rescale/update counts)."""
    dynamic = ("lr", "wd", "rescale_grad", "num_update", "begin_num_update")
    items = []
    for k, v in sorted(vars(opt).items()):
        if k in dynamic or k.startswith("_"):
            continue
        if isinstance(v, (int, float, bool, str)) or v is None:
            items.append((k, v))
    return tuple(items)


class Optimizer:
    """Registry base + hyper-parameter bookkeeping (ref optimizer.py:36).

    Subclasses implement ``create_state`` and the pure ``update_step``;
    the mutating ``update`` wrapper is shared.
    """

    def __init__(self, rescale_grad=1.0, param_idx2name=None, wd=0.0,
                 clip_gradient=None, learning_rate=0.01, lr_scheduler=None,
                 sym=None, begin_num_update=0, param_dict=None):
        self.rescale_grad = rescale_grad
        self.lr = learning_rate
        self.lr_scheduler = lr_scheduler
        if lr_scheduler is not None:
            lr_scheduler.base_lr = learning_rate
        self.wd = wd
        self.clip_gradient = clip_gradient
        self.begin_num_update = begin_num_update
        self.num_update = begin_num_update
        self._index_update_count = {}
        self.idx2name = dict(param_idx2name or {})
        self.sym_info = (sym.attr_dict(), sym.list_arguments()) if sym \
            else None
        self.param_dict = param_dict or {}
        self.set_lr_mult({})
        self.set_wd_mult({})

    # ---- factory used by kvstore set_optimizer ----

    @staticmethod
    def create_optimizer(name, **kwargs):
        return _REG.get(name)(**kwargs)

    # ---- hyper-parameter resolution ----

    def set_learning_rate(self, lr):
        if self.lr_scheduler is not None:
            raise UserWarning("LRScheduler of the optimizer has already been "
                              "defined.")
        self.lr = lr

    def _mult_from_attrs(self, key):
        """Collect __lr_mult__/__wd_mult__ attrs from the bound symbol."""
        found = {}
        if self.sym_info:
            attrs, arg_names = self.sym_info
            for name in arg_names:
                if name in attrs and key in attrs[name]:
                    found[name] = float(attrs[name][key])
        return found

    def set_lr_mult(self, args_lr_mult):
        self.lr_mult = self._mult_from_attrs("__lr_mult__")
        self.lr_mult.update(args_lr_mult)

    def set_wd_mult(self, args_wd_mult):
        self.wd_mult = {}
        for name in self.idx2name.values():
            if not name.endswith(("_weight", "_gamma")):
                self.wd_mult[name] = 0.0
        self.wd_mult.update(self._mult_from_attrs("__wd_mult__"))
        self.wd_mult.update(args_wd_mult)

    def _update_count(self, index):
        count = self._index_update_count.setdefault(index,
                                                    self.begin_num_update)
        self._index_update_count[index] = count + 1
        self.num_update = max(count + 1, self.num_update)

    def _snapshot_update_counts(self, indices):
        """Pre-step snapshot of the per-slot update counts for *indices*
        plus ``num_update`` — the undo token the guardian needs when a
        step's update is suppressed in-program (a skipped step must not
        advance ``hyper['t']`` or Adam bias correction drifts from the
        clean trajectory)."""
        return ({i: self._index_update_count.get(i) for i in indices},
                self.num_update)

    def _revert_update_counts(self, snapshot):
        """Restore a :meth:`_snapshot_update_counts` token after a
        skipped step (slots first seen on the skipped step are removed
        entirely, exactly undoing ``_update_count``'s setdefault)."""
        counts, num_update = snapshot
        for index, prev in counts.items():
            if prev is None:
                self._index_update_count.pop(index, None)
            else:
                self._index_update_count[index] = prev
        self.num_update = num_update

    def _resolve_mult(self, index, table):
        if index in self.param_dict:
            p = self.param_dict[index]
            return p.lr_mult if table is self.lr_mult else p.wd_mult
        if index in table:
            return table[index]
        name = self.idx2name.get(index)
        return table.get(name, 1.0) if name is not None else 1.0

    def _get_lr(self, index):
        base = self.lr_scheduler(self.num_update) if self.lr_scheduler \
            else self.lr
        return base * self._resolve_mult(index, self.lr_mult)

    def _get_wd(self, index):
        return self.wd * self._resolve_mult(index, self.wd_mult)

    def _clip(self):
        """clip_gradient in the kernel convention (-1 = off)."""
        return self.clip_gradient if self.clip_gradient else -1.0

    # ---- the two update entry points ----

    #: set by stochastic optimizers (SGLD): the fused step then feeds a
    #: fresh per-slot PRNG key through ``hyper["key"]``
    needs_rng = False

    def create_state(self, index, weight):
        return None

    def update_step(self, weight, grad, state, hyper):
        """Pure update on raw jax arrays. hyper: {lr, wd, t[, key]}."""
        raise NotImplementedError("%s has no pure update_step"
                                  % type(self).__name__)

    def supports_fused(self):
        """True iff the whole-model fused step may replace the per-slot
        ``update`` loop bitwise: the optimizer must expose the pure core
        and must not have customised the mutating entry point (a custom
        ``update`` may carry bookkeeping the fused path can't replay)."""
        cls = type(self)
        return (cls.update is Optimizer.update
                and cls.update_step is not Optimizer.update_step)

    @staticmethod
    def _hyper_dtype(w, state):
        """lr/wd dtype for one slot: the dtype the eager loop's weak-typed
        python-float hypers effectively compute in — the weight dtype,
        EXCEPT when a half-precision weight carries an f32 master copy in
        its state (multi-precision), where the update math runs in f32."""
        if np.dtype(w.dtype) == np.float16:
            import jax
            leaves = jax.tree_util.tree_flatten(state)[0]
            if any(np.dtype(l.dtype) == np.float32 for l in leaves):
                return np.float32
        return w.dtype

    def fused_update_step(self, weights, grads, states, hyper):
        """Pure whole-model update: every slot's ``update_step`` in ONE
        trace, so jit compiles the entire weight update into a single
        XLA program (the reference's fused optimizer_op.cc kernels,
        lifted from per-tensor to per-model).

        weights/grads/states: equal-length lists of raw jax pytrees.
        hyper: {"lr": f32[n], "wd": f32[n], "t": i32[n],
                "rescale": f32 scalar[, "key": PRNGKey[n]]} — all traced,
        so lr schedules and batch-size changes never retrace.
        """
        prev_rescale = self.rescale_grad
        self.rescale_grad = hyper["rescale"]
        try:
            keys = hyper.get("key")
            new_ws, new_ss = [], []
            for i, (w, g, s) in enumerate(zip(weights, grads, states)):
                hdt = self._hyper_dtype(w, s)
                h = {"lr": jnp.asarray(hyper["lr"][i], hdt),
                     "wd": jnp.asarray(hyper["wd"][i], hdt),
                     "t": hyper["t"][i]}
                if keys is not None:
                    h["key"] = keys[i]
                nw, ns = self.update_step(w, g.astype(w.dtype), s, h)
                new_ws.append(nw.astype(w.dtype))
                new_ss.append(ns)
            return new_ws, new_ss
        finally:
            self.rescale_grad = prev_rescale

    def update(self, index, weight, grad, state):
        """Classic mutating update: resolves hyper-params for *index*,
        runs the pure core as ONE jitted per-slot program, writes results
        back into the NDArrays.

        Jitting (rather than eager op-by-op dispatch) matters twice: it
        fuses the slot's update into a single XLA program like the
        reference's optimizer_op.cc kernels, and it makes the per-slot
        loop execute the exact same compiled subgraph as the fused
        whole-model Trainer step — the bitwise-oracle contract.
        """
        self._update_count(index)
        hyper = {"lr": self._get_lr(index), "wd": self._get_wd(index),
                 "t": self._index_update_count[index],
                 # traced, NOT baked: Trainer.step rewrites it per batch
                 "rescale": self.rescale_grad}
        if self.needs_rng:
            # a key must enter as an argument: drawing it inside the
            # traced fn would freeze one key into the compiled program
            from . import random as _random
            hyper["key"] = _random.next_key()
        new_w, new_state = _jitted_update_step(self)(
            weight._data, grad._data, _state_raw(state), hyper)
        weight._set_data(new_w)
        _state_writeback(state, new_state)


def _jitted_update_step(opt):
    """The per-slot jitted update program for *opt*.

    Shared by ``Optimizer.update`` (the eager per-slot hot path) and the
    graftcheck AOT driver (``tracecheck_programs``), so the program the
    trace tier analyzes IS the program the framework ships.

    Cache key: static scalar hypers are BAKED into the trace, so a
    mid-training mutation (opt.clip_gradient = ...) must rebuild.
    Recomputing the fingerprint here costs a ~20-attr scan per slot
    — micro vs the jit dispatch it gates, and the price of honoring
    mutations without a __setattr__ hook on every optimizer.
    """
    import jax
    statics = static_hypers(opt)
    cached = _JIT_UPDATE_CACHE.get(opt)
    if cached is None or cached[0] != statics:
        # weakref.proxy: the cached value must not strongly reference
        # the key or this WeakKeyDictionary can never evict
        _self = weakref.proxy(opt)

        def _step(w, g, s, h):
            prev = _self.rescale_grad
            _self.rescale_grad = h["rescale"]   # trace-time only
            try:
                return _self.update_step(w, g, s, h)
            finally:
                _self.rescale_grad = prev
        cached = (statics,
                  _tel.watch_jit(jax.jit(_step), "optimizer_update_step"))
        _JIT_UPDATE_CACHE[opt] = cached
    return cached[1]


def tracecheck_programs():
    """AOT specimens for graftcheck: the per-slot jitted update program,
    for a momentum-SGD and an Adam instance (one no-state and one
    multi-slot-state layout)."""
    specimens = []
    # the jitted step references its optimizer via weakref.proxy: pin the
    # specimens so the driver's later trace doesn't observe a dead owner
    _TRACECHECK_KEEPALIVE[:] = [SGD(momentum=0.9, learning_rate=0.05),
                                Adam(learning_rate=1e-3)]
    for opt in _TRACECHECK_KEEPALIVE:
        w = nd.zeros((16, 8))
        state = opt.create_state(0, w)
        hyper = {"lr": 0.05, "wd": 0.0, "t": 1,
                 "rescale": np.float32(1.0)}
        specimens.append(("optimizer_update_step", _jitted_update_step(opt),
                          (w._data, w._data, _state_raw(state), hyper), {}))
    return specimens


@register
class SGD(Optimizer):
    """SGD with momentum + optional multi-precision fp16 (ref :434)."""

    def __init__(self, momentum=0.0, lazy_update=True,
                 multi_precision=False, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lazy_update = lazy_update
        self.multi_precision = multi_precision

    def create_state(self, index, weight):
        if self.multi_precision and weight.dtype == np.float16:
            mom = _zeros_like_nd(weight, np.float32) if self.momentum \
                else None
            return (mom, weight.astype(np.float32))
        if self.momentum != 0.0:
            return _zeros_like_nd(weight)
        return None

    def update_step(self, w, g, state, hyper):
        kw = dict(lr=hyper["lr"], wd=hyper["wd"],
                  rescale_grad=self.rescale_grad, clip_gradient=self._clip())
        if isinstance(state, tuple):          # multi-precision
            mom, w32 = state
            if mom is not None:
                new_w, new_mom, new_w32 = _kern._mp_sgd_mom_update(
                    w, g, mom, w32, momentum=self.momentum, **kw)
                return new_w, (new_mom, new_w32)
            new_w, new_w32 = _kern._mp_sgd_update(w, g, w32, **kw)
            return new_w, (None, new_w32)
        if state is not None:
            new_w, new_mom = _kern._sgd_mom_update(
                w, g, state, momentum=self.momentum, **kw)
            return new_w, new_mom
        return _kern._sgd_update(w, g, **kw), None


_REG.register(SGD, "ccsgd")


@register
class NAG(Optimizer):
    """Nesterov accelerated SGD (ref :585)."""

    def __init__(self, momentum=0.0, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum

    def create_state(self, index, weight):
        return _zeros_like_nd(weight) if self.momentum != 0.0 else None

    def update_step(self, w, g, state, hyper):
        lr, wd = hyper["lr"], hyper["wd"]
        g = _kern._prep_grad(g, self.rescale_grad, self._clip())
        if state is None:
            return w - lr * (g + wd * w), None
        new_mom = self.momentum * state + g
        lookahead = g + self.momentum * new_mom
        return w - lr * (lookahead + wd * w), new_mom


@register
class SGLD(Optimizer):
    """Stochastic gradient Langevin dynamics (ref :631): gradient step at
    lr/2 plus N(0, lr) noise."""

    needs_rng = True

    def update_step(self, w, g, state, hyper):
        import jax
        lr, wd = hyper["lr"], hyper["wd"]
        g = _kern._prep_grad(g, self.rescale_grad, self._clip())
        key = hyper.get("key")
        if key is None:
            from . import random as _random
            key = _random.next_key()
        noise = math.sqrt(lr) if not hasattr(lr, "dtype") else jnp.sqrt(lr)
        stepped = w - lr / 2 * (g + wd * w)
        return stepped + noise * jax.random.normal(key, w.shape,
                                                   dtype=w.dtype), None


@register
class DCASGD(Optimizer):
    """Delay-compensated async SGD (ref :560); state carries the momentum
    and the weight snapshot from the previous update."""

    def __init__(self, momentum=0.0, lamda=0.04, **kwargs):
        super().__init__(**kwargs)
        self.momentum = momentum
        self.lamda = lamda

    def create_state(self, index, weight):
        mom = _zeros_like_nd(weight) if self.momentum != 0.0 else None
        return (mom, weight.copy())

    def update_step(self, w, g, state, hyper):
        lr, wd = hyper["lr"], hyper["wd"]
        g = _kern._prep_grad(g, self.rescale_grad, self._clip())
        mom, prev_w = state
        compensated = g + wd * w + self.lamda * g * g * (w - prev_w)
        if mom is not None:
            new_mom = self.momentum * mom - lr * compensated
            return w + new_mom, (new_mom, w)
        return w - lr * compensated, (None, w)


@register
class Adam(Optimizer):
    """Adam with bias correction folded into lr (ref :754)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, lazy_update=True, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def update_step(self, w, g, state, hyper):
        # jnp (not python) scalar math: t may be a traced scalar under
        # jit, and the eager per-slot loop must round identically to the
        # fused whole-model trace (bitwise-oracle contract) — so both
        # compute the bias correction in f32 on-device.
        t = jnp.asarray(hyper["t"], jnp.float32)
        # final astype keeps fp16 weights in fp16 math (a bare f32 scalar
        # would promote the whole update)
        corrected = (hyper["lr"] * jnp.sqrt(1.0 - self.beta2 ** t)
                     / (1.0 - self.beta1 ** t)).astype(w.dtype)
        mean, var = state
        new_w, new_mean, new_var = _kern._adam_update(
            w, g, mean, var, lr=corrected, wd=hyper["wd"],
            beta1=self.beta1, beta2=self.beta2, epsilon=self.epsilon,
            rescale_grad=self.rescale_grad, clip_gradient=self._clip())
        return new_w, (new_mean, new_var)


@register
class AdaGrad(Optimizer):
    """AdaGrad (ref :902); state is the squared-gradient history."""

    def __init__(self, eps=1e-7, **kwargs):
        super().__init__(**kwargs)
        self.float_stable_eps = eps

    def create_state(self, index, weight):
        return _zeros_like_nd(weight)

    def update_step(self, w, g, state, hyper):
        lr, wd = hyper["lr"], hyper["wd"]
        g = _kern._prep_grad(g, self.rescale_grad, self._clip())
        hist = state + g * g
        stepped = w - lr * (g / jnp.sqrt(hist + self.float_stable_eps)
                            + wd * w)
        return stepped, hist


@register
class RMSProp(Optimizer):
    """RMSProp, plain or centered (ref :938)."""

    def __init__(self, learning_rate=0.001, gamma1=0.9, gamma2=0.9,
                 epsilon=1e-8, centered=False, clip_weights=None, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.gamma1, self.gamma2 = gamma1, gamma2
        self.centered, self.epsilon = centered, epsilon
        self.clip_weights = clip_weights

    def create_state(self, index, weight):
        n = 3 if self.centered else 1
        return tuple(_zeros_like_nd(weight) for _ in range(n))

    def update_step(self, w, g, state, hyper):
        kw = dict(lr=hyper["lr"], wd=hyper["wd"], gamma1=self.gamma1,
                  epsilon=self.epsilon, rescale_grad=self.rescale_grad,
                  clip_gradient=self._clip(),
                  clip_weights=self.clip_weights or -1.0)
        if self.centered:
            n, avg, delta = state
            new_w, nn, ng, nd_ = _kern._rmspropalex_update(
                w, g, n, avg, delta, gamma2=self.gamma2, **kw)
            return new_w, (nn, ng, nd_)
        (n,) = state
        new_w, nn = _kern._rmsprop_update(w, g, n, **kw)
        return new_w, (nn,)


@register
class AdaDelta(Optimizer):
    """AdaDelta (ref :1004); state = (E[g^2], E[dx^2])."""

    def __init__(self, rho=0.90, epsilon=1e-5, **kwargs):
        super().__init__(**kwargs)
        self.rho, self.epsilon = rho, epsilon

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def update_step(self, w, g, state, hyper):
        wd = hyper["wd"]
        g = _kern._prep_grad(g, self.rescale_grad, self._clip())
        acc_g, acc_dx = state
        acc_g = self.rho * acc_g + (1.0 - self.rho) * g * g
        dx = jnp.sqrt((acc_dx + self.epsilon) / (acc_g + self.epsilon)) * g
        acc_dx = self.rho * acc_dx + (1.0 - self.rho) * dx * dx
        return w - dx - wd * w, (acc_g, acc_dx)


@register
class Ftrl(Optimizer):
    """FTRL-proximal (ref :1040); state = (z, n)."""

    def __init__(self, lamda1=0.01, learning_rate=0.1, beta=1, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.lamda1, self.beta = lamda1, beta

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def update_step(self, w, g, state, hyper):
        z, n = state
        new_w, new_z, new_n = _kern._ftrl_update(
            w, g, z, n, lr=hyper["lr"], wd=hyper["wd"], lamda1=self.lamda1,
            beta=self.beta, rescale_grad=self.rescale_grad,
            clip_gradient=self._clip())
        return new_w, (new_z, new_n)


@register
class Adamax(Optimizer):
    """AdaMax: infinity-norm variant of Adam (ref :1084)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2 = beta1, beta2

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight))

    def update_step(self, w, g, state, hyper):
        # f32 jnp scalar prep: eager loop and fused trace must match;
        # final astype keeps fp16 weights in fp16 math
        t = jnp.asarray(hyper["t"], jnp.float32)
        lr = (hyper["lr"] / (1.0 - self.beta1 ** t)).astype(w.dtype)
        g = _kern._prep_grad(g, self.rescale_grad, self._clip()) \
            + hyper["wd"] * w
        m, u = state
        m = self.beta1 * m + (1.0 - self.beta1) * g
        u = jnp.maximum(self.beta2 * u, jnp.abs(g))
        return w - lr * m / u, (m, u)


@register
class Nadam(Optimizer):
    """Nesterov Adam (ref :1119); the momentum-schedule product rides in
    the state so the pure core stays stateless."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, schedule_decay=0.004, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.schedule_decay = schedule_decay

    def create_state(self, index, weight):
        return (_zeros_like_nd(weight), _zeros_like_nd(weight),
                nd.ones((1,), ctx=weight.context))     # running mu product

    def update_step(self, w, g, state, hyper):
        lr, wd = hyper["lr"], hyper["wd"]
        # f32 jnp scalar prep: eager loop and fused trace must match
        t = jnp.asarray(hyper["t"], jnp.float32)
        g = _kern._prep_grad(g, self.rescale_grad, self._clip()) + wd * w
        mu_t = self.beta1 * (1.0 - 0.5 * 0.96 ** (t * self.schedule_decay))
        mu_next = self.beta1 * (1.0 - 0.5 * 0.96 **
                                ((t + 1) * self.schedule_decay))
        m, v, sched = state
        sched = sched * mu_t
        sched_next = sched * mu_next
        m = self.beta1 * m + (1.0 - self.beta1) * g
        v = self.beta2 * v + (1.0 - self.beta2) * g * g
        g_hat = g / (1.0 - sched)
        m_hat = m / (1.0 - sched_next)
        v_hat = v / (1.0 - self.beta2 ** t)
        m_bar = (1.0 - mu_t) * g_hat + mu_next * m_hat
        return w - lr * m_bar / (jnp.sqrt(v_hat) + self.epsilon), \
            (m, v, sched)


@register
class Signum(Optimizer):
    """Sign-of-gradient SGD with momentum (signum_update kernels)."""

    def __init__(self, learning_rate=0.01, momentum=0.9, wd_lh=0.0, **kwargs):
        super().__init__(learning_rate=learning_rate, **kwargs)
        self.momentum, self.wd_lh = momentum, wd_lh

    def create_state(self, index, weight):
        return _zeros_like_nd(weight) if self.momentum != 0.0 else None

    def update_step(self, w, g, state, hyper):
        kw = dict(lr=hyper["lr"], wd=hyper["wd"],
                  rescale_grad=self.rescale_grad, clip_gradient=self._clip())
        if state is not None:
            new_w, new_mom = _kern._signum_update(
                w, g, state, momentum=self.momentum, wd_lh=self.wd_lh, **kw)
            return new_w, new_mom
        return _kern._signsgd_update(w, g, **kw), None


@register
class Test(Optimizer):
    """w -= rescale_grad * g; state mirrors the weight (ref :1110)."""

    def create_state(self, index, weight):
        return _zeros_like_nd(weight)

    def update_step(self, w, g, state, hyper):
        new_w = w - self.rescale_grad * g
        return new_w, new_w

    def update(self, index, weight, grad, state):
        # no hyper resolution needed; keep the reference's exact behavior
        new_w, new_s = self.update_step(weight._data, grad._data,
                                        _state_raw(state), {})
        weight._set_data(new_w)
        _state_writeback(state, new_s)


class Updater:
    """Per-slot stateful wrapper (ref optimizer.py:1124 get_updater):
    lazily creates optimizer state per index and serialises it for
    checkpointing."""

    def __init__(self, optimizer):
        self.optimizer = optimizer
        self.states = {}
        self.states_synced = {}

    def __call__(self, index, grad, weight):
        if index not in self.states:
            self.states[index] = self.optimizer.create_state(index, weight)
            self.states_synced[index] = True
        from . import profiler as _prof
        _prof.bump("xla_program_calls")   # one eager update program per slot
        _prof.bump("optimizer_update")
        self.optimizer.update(index, weight, grad, self.states[index])

    def set_states(self, states):
        self.set_states_payload(pickle.loads(states))

    def set_states_payload(self, payload):
        """Install an already-decoded get_states payload (callers that
        sniffed the blob's format avoid a second full deserialization —
        unpickling re-materializes every state NDArray on device)."""
        if isinstance(payload, tuple) and len(payload) == 2:
            self.states, maybe_opt = payload
            if maybe_opt is not None:
                self.optimizer = maybe_opt
        else:
            self.states = payload
        self.states_synced = dict.fromkeys(self.states, False)

    def get_states(self, dump_optimizer=False):
        payload = (self.states, self.optimizer) if dump_optimizer \
            else self.states
        return pickle.dumps(payload)


def get_updater(optimizer):
    return Updater(optimizer)
