"""RecordIO: the reference's packed binary record format.

Parity surface: reference ``python/mxnet/recordio.py`` —
``MXRecordIO`` (:36), ``MXIndexedRecordIO`` (:170), ``IRHeader``
pack/unpack (+jpeg payloads) (:291-380), over the dmlc-core chunked
format (``src/io/image_recordio.h``).

Format (dmlc-core recordio): each record is
``[kMagic:u32][lrec:u32][data][pad to 4B]`` where ``lrec`` encodes
cflag (upper 3 bits, 0 = complete record) and length (lower 29 bits).
This is a pure-python reimplementation of the wire format — files it
writes are readable by the reference and vice versa.
"""
from __future__ import annotations

import collections
import ctypes
import os
import struct

import numpy as np

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader",
           "pack", "unpack", "pack_img", "unpack_img"]

_KMAGIC = 0xced7230a


class MXRecordIO(object):
    """Sequential reader/writer of RecordIO files (reference :36).

    Backed by the native C++ codec (``native/recordio.cc`` via ctypes,
    4 MB buffered IO) when ``mxnet_tpu/_native/librecordio.so`` is built;
    falls back to pure python on the identical wire format otherwise.
    """

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.is_open = False
        self.fd = None
        self._h = None      # native handle
        self._lib = None
        self.open()

    def open(self):
        from . import _native
        from .stream import open_stream, split_scheme
        if self.flag == "w":
            self.writable = True
        elif self.flag == "r":
            self.writable = False
        else:
            raise ValueError("Invalid flag %s" % self.flag)
        # scheme URIs (s3://, mem://, ...) go through the pluggable
        # stream layer; the native codec mmaps local paths only
        scheme, rest = split_scheme(self.uri)
        remote = scheme not in (None, "file")
        local_path = rest if scheme == "file" else self.uri
        lib = None if remote else _native.lib()
        if lib is not None:
            create = (lib.MXRIOWriterCreate if self.writable
                      else lib.MXRIOReaderCreate)
            self._h = create(local_path.encode())
            if not self._h:
                raise IOError("cannot open %s" % self.uri)
            self._lib = lib
        else:
            self.fd = open_stream(self.uri,
                                  "wb" if self.writable else "rb")
        self.is_open = True

    def __del__(self):
        self.close()

    def __getstate__(self):
        is_open = self.is_open
        self.close()
        d = dict(self.__dict__)
        d["is_open"] = is_open
        d.pop("fd", None)
        d.pop("_h", None)
        d.pop("_lib", None)
        return d

    def __setstate__(self, d):
        self.__dict__ = d
        self.fd = None
        self._h = None
        self._lib = None
        is_open = d.get("is_open", False)
        self.is_open = False
        if is_open:
            self.open()

    def close(self):
        if not self.is_open:
            return
        if self._h is not None:
            free = (self._lib.MXRIOWriterFree if self.writable
                    else self._lib.MXRIOReaderFree)
            free(self._h)
            self._h = None
        else:
            self.fd.close()
        self.is_open = False

    def reset(self):
        self.close()
        self.open()

    def write(self, buf):
        assert self.writable
        if self._h is not None:
            buf = bytes(buf)  # accept bytearray/memoryview like fd.write
            if self._lib.MXRIOWrite(self._h, buf, len(buf)) != 0:
                raise IOError("RecordIO write failed")
            return
        lrec = len(buf)  # cflag 0 (complete)
        self.fd.write(struct.pack("<II", _KMAGIC, lrec))
        self.fd.write(buf)
        pad = (4 - (len(buf) % 4)) % 4
        if pad:
            self.fd.write(b"\x00" * pad)

    def read(self):
        assert not self.writable
        if self._h is not None:
            out = ctypes.c_char_p()
            n = ctypes.c_uint64()
            status = self._lib.MXRIORead(self._h, ctypes.byref(out),
                                         ctypes.byref(n))
            if status == 0:
                return None
            if status < 0:
                raise IOError("corrupt RecordIO stream in %s" % self.uri)
            return ctypes.string_at(out, n.value)
        head = self.fd.read(8)
        if len(head) < 8:
            return None
        magic, lrec = struct.unpack("<II", head)
        assert magic == _KMAGIC, "Invalid RecordIO magic"
        length = lrec & ((1 << 29) - 1)
        cflag = lrec >> 29
        assert cflag == 0, "multi-chunk records not supported"
        buf = self.fd.read(length)
        pad = (4 - (length % 4)) % 4
        if pad:
            self.fd.read(pad)
        return buf

    def seek(self, pos):
        assert not self.writable
        if self._h is not None:
            if self._lib.MXRIOReaderSeek(self._h, pos) != 0:
                raise IOError("seek(%d) failed on %s" % (pos, self.uri))
        else:
            self.fd.seek(pos)

    def tell(self):
        if self._h is not None:
            return (self._lib.MXRIOWriterTell(self._h) if self.writable
                    else self._lib.MXRIOReaderTell(self._h))
        return self.fd.tell()


class MXIndexedRecordIO(MXRecordIO):
    """Random-access RecordIO with a .idx sidecar (reference :170)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super(MXIndexedRecordIO, self).__init__(uri, flag)

    def open(self):
        from .stream import open_stream
        MXRecordIO.open(self)
        self.idx = {}
        self.keys = []
        if not self.writable:
            try:
                fin = open_stream(self.idx_path, "r")
            except (FileNotFoundError, OSError):
                fin = None    # sidecar optional, any scheme
            if fin is not None:
                with fin:
                    for line in fin.readlines():
                        line = line.strip().split("\t")
                        key = self.key_type(line[0])
                        self.idx[key] = int(line[1])
                        self.keys.append(key)

    def close(self):
        if not self.is_open:
            return
        if self.writable:
            from .stream import open_stream
            with open_stream(self.idx_path, "w") as fout:
                for k in self.keys:
                    fout.write("%s\t%d\n" % (str(k), self.idx[k]))
        MXRecordIO.close(self)

    def seek(self, idx):
        assert not self.writable
        MXRecordIO.seek(self, self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.keys.append(key)
        self.idx[key] = pos


IRHeader = collections.namedtuple("HEADER",
                                  ["flag", "label", "id", "id2"])
_IR_FORMAT = "IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


def pack(header, s):
    """Pack a string with an IRHeader (reference recordio.py:291)."""
    header = IRHeader(*header)
    if isinstance(header.label, (int, float)):
        header = header._replace(label=float(header.label))
        ret = struct.pack(_IR_FORMAT, 0, header.label, header.id,
                          header.id2)
    else:
        label = np.asarray(header.label, dtype=np.float32)
        header = header._replace(flag=label.size, label=0)
        ret = struct.pack(_IR_FORMAT, header.flag, header.label,
                          header.id, header.id2)
        ret += label.tobytes()
    return ret + s


def unpack(s):
    """Unpack an IRHeader-packed string (reference recordio.py:322)."""
    header = IRHeader(*struct.unpack(_IR_FORMAT, s[:_IR_SIZE]))
    s = s[_IR_SIZE:]
    if header.flag > 0:
        label = np.frombuffer(s[:header.flag * 4], dtype=np.float32)
        header = header._replace(label=label)
        s = s[header.flag * 4:]
    return header, s


def unpack_img(s, iscolor=-1):
    """Unpack a packed image record (reference recordio.py:344).
    JPEG decode requires PIL or cv2; raw numpy payloads always work."""
    header, s = unpack(s)
    img = _imdecode(s, iscolor)
    return header, img


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    """Pack an image (reference recordio.py:366)."""
    encoded = _imencode(img, quality, img_fmt)
    return pack(header, encoded)


def _imdecode(buf, iscolor=-1):
    try:
        import cv2
        return cv2.imdecode(np.frombuffer(buf, np.uint8), iscolor)
    except ImportError:
        pass
    try:
        import io as _io
        from PIL import Image
        return np.asarray(Image.open(_io.BytesIO(buf)))
    except ImportError:
        raise ImportError("unpack_img requires cv2 or PIL")


def _imencode(img, quality, img_fmt):
    try:
        import cv2
        jpg_formats = [".JPG", ".JPEG"]
        png_formats = [".PNG"]
        encode_params = None
        if img_fmt.upper() in jpg_formats:
            encode_params = [cv2.IMWRITE_JPEG_QUALITY, quality]
        elif img_fmt.upper() in png_formats:
            encode_params = [cv2.IMWRITE_PNG_COMPRESSION, quality]
        ret, buf = cv2.imencode(img_fmt, img, encode_params)
        assert ret, "failed to encode image"
        return buf.tobytes()
    except ImportError:
        pass
    try:
        import io as _io
        from PIL import Image
        bio = _io.BytesIO()
        fmt = "JPEG" if img_fmt.lower() in (".jpg", ".jpeg") else "PNG"
        Image.fromarray(np.asarray(img)).save(bio, format=fmt,
                                              quality=quality)
        return bio.getvalue()
    except ImportError:
        raise ImportError("pack_img requires cv2 or PIL")
