"""User-facing Pallas kernel registration — the TPU answer to RTC.

Reference parity: ``python/mxnet/rtc.py`` + ``src/common/rtc.cc:32-80``
let a user hand the runtime raw CUDA source (``CudaModule(source)
.get_kernel(...).launch(...)``) and call it on NDArrays. On TPU the
user-authored kernel is a **Pallas** function instead of CUDA source, and
"launching" means installing it in the operator registry so it is usable
from every frontend — ``mx.nd.<name>``, ``mx.sym.<name>``, hybridized
Gluon blocks, Module training — exactly like a built-in op:

    import mxnet_tpu as mx
    from jax.experimental import pallas as pl

    def _scale_kernel(x_ref, o_ref, *, alpha):
        o_ref[...] = x_ref[...] * alpha

    @mx.pallas.register("my_scale", grad=lambda og, ins, outs, attrs:
                        (og[0] * float(attrs.get("alpha", 1.0)),))
    def my_scale(x, alpha=2.0, interpret=False):
        import functools
        return pl.pallas_call(
            functools.partial(_scale_kernel, alpha=float(alpha)),
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=interpret)(x)

    y = mx.nd.my_scale(mx.nd.ones((4, 4)), alpha=3.0)   # eager
    s = mx.sym.my_scale(mx.sym.Variable("d"), alpha=3.0)  # symbolic

Kernels that accept an ``interpret`` keyword get it filled automatically:
``False`` on TPU (compiled Mosaic), ``True`` elsewhere (the Pallas
interpreter — the CPU-test story, mirroring how the in-tree flash
attention kernels degrade, ``ops/pallas_kernels.py:16``).

Gradients: pure-JAX ops differentiate through ``jax.vjp`` automatically;
``pl.pallas_call`` does not, so kernels used in training either pass
``grad=`` (a semantic backward like the reference's custom FGradient) or
register a companion backward kernel.
"""
from __future__ import annotations

import inspect

import jax

from .base import MXNetError
from .ops.registry import OP_REGISTRY, Op

__all__ = ["register", "unregister", "registered_kernels"]

_USER_KERNELS = []
_SHADOWED = {}  # name -> Op it force-replaced, restored on unregister()


def _auto_interpret():
    """Interpret-mode default: compiled on TPU, interpreter elsewhere."""
    return jax.default_backend() != "tpu"


def _expose(name, op):
    """Install the nd/sym wrappers for a freshly registered op (the
    import-time generation in ndarray/__init__ and symbol/__init__ has
    already run by the time a user registers a kernel)."""
    import sys
    from . import ndarray as nd_mod
    from . import symbol as sym_mod
    from .ndarray import _make_op_func
    from .symbol import _make_sym_func

    nd_fn = _make_op_func(name, op)
    sym_fn = _make_sym_func(name, op)
    setattr(sys.modules[nd_mod.__name__ + "._internal"], name, nd_fn)
    setattr(sys.modules[sym_mod.__name__ + "._internal"], name, sym_fn)
    if not name.startswith("_"):
        setattr(nd_mod, name, nd_fn)
        setattr(sym_mod, name, sym_fn)
    return nd_fn


def register(name, fn=None, *, grad=None, num_outputs=1, takes_mode=False,
             needs_rng=False, interpret=None, force=False):
    """Register *fn* as operator *name*, usable from nd/sym/gluon.

    Parameters
    ----------
    fn : pure function ``(*jax_arrays, **attrs) -> array | tuple`` —
        typically wrapping ``pl.pallas_call``. If it accepts an
        ``interpret`` keyword, the registry fills it per-backend unless
        the call site pins it.
    grad : optional semantic backward
        ``bwd(out_grads, inputs, outputs, attrs) -> input_grads`` (tuple,
        one per input). Without it, gradients flow through ``jax.vjp`` —
        fine for pure-JAX bodies, unavailable for raw pallas_call.
    interpret : force interpret mode on (True) / off (False); default
        auto-selects by backend at call time.
    force : allow replacing an existing registration.

    Returns the eager ``mx.nd.<name>`` callable (decorator-friendly).
    """
    if fn is None:  # decorator form
        def deco(f):
            return register(name, f, grad=grad, num_outputs=num_outputs,
                            takes_mode=takes_mode, needs_rng=needs_rng,
                            interpret=interpret, force=force)
        return deco
    if name in OP_REGISTRY:
        if not force:
            raise MXNetError(
                "operator %r already registered (pass force=True to replace)"
                % name)
        if name not in _SHADOWED and name not in _USER_KERNELS:
            # force=True over a built-in: stash it so unregister() restores
            # the core operator instead of deleting it (r4 advice).
            _SHADOWED[name] = OP_REGISTRY[name]

    params = inspect.signature(fn).parameters
    accepts_interpret = "interpret" in params

    if accepts_interpret:
        def body(*arrays, **attrs):
            if attrs.get("interpret") is None:
                attrs["interpret"] = (_auto_interpret() if interpret is None
                                      else interpret)
            return fn(*arrays, **attrs)
        body.__name__ = getattr(fn, "__name__", name)
    else:
        body = fn

    op = Op(name, body, num_outputs=num_outputs, takes_mode=takes_mode,
            needs_rng=needs_rng, custom_vjp=grad,
            attr_defaults={"interpret": None} if accepts_interpret else None)
    OP_REGISTRY[name] = op
    if name not in _USER_KERNELS:
        _USER_KERNELS.append(name)
    return _expose(name, op)


def unregister(name):
    """Remove a user-registered kernel and its nd/sym wrappers
    (built-ins are protected)."""
    import sys
    from . import ndarray as nd_mod
    from . import symbol as sym_mod
    if name not in _USER_KERNELS:
        raise MXNetError("%r is not a user-registered kernel" % name)
    _USER_KERNELS.remove(name)
    OP_REGISTRY.pop(name, None)
    for mod in (nd_mod, sym_mod,
                sys.modules.get(nd_mod.__name__ + "._internal"),
                sys.modules.get(sym_mod.__name__ + "._internal")):
        if mod is not None and hasattr(mod, name):
            delattr(mod, name)
    shadowed = _SHADOWED.pop(name, None)
    if shadowed is not None:
        # the kernel force-replaced a built-in: put the original back,
        # wrappers included, so the framework keeps its core operator
        OP_REGISTRY[name] = shadowed
        _expose(name, shadowed)


def registered_kernels():
    """Names of live user-registered kernels."""
    return list(_USER_KERNELS)
