"""Image IO + augmentation (reference python/mxnet/image/)."""
from .image import *  # noqa: F401,F403
from . import image  # noqa: F401
from .detection import *  # noqa: F401,F403
from . import detection  # noqa: F401
from . import native_iter  # noqa: F401
from .native_iter import ImageRecordIter  # noqa: F401
