"""Detection-aware image augmenters + ImageDetIter.

API parity with the reference ``python/mxnet/image/detection.py`` (the
Det* augmenter family over (image, label) pairs and ImageDetIter feeding
the SSD workload; native twin ``src/io/image_det_aug_default.cc``).
Labels are (N, 5+) rows ``[class, x0, y0, x1, y1, ...]`` with corner
coordinates normalised to [0, 1]; class < 0 marks padding rows.

Same host-side design as image.py: every augmenter implements
``_apply(img, label) -> (img, label)`` on numpy, composed per sample
before the batch lands on device once.
"""
from __future__ import annotations

import random as _rng

import numpy as np

from .. import io as _io
from .. import ndarray as nd
from .image import (Augmenter, CastAug, ColorJitterAug, ColorNormalizeAug,
                    ImageIter, _to_np, _wrap, imresize)

__all__ = ["DetAugmenter", "DetBorrowAug", "DetRandomSelectAug",
           "DetHorizontalFlipAug", "DetRandomCropAug", "DetRandomPadAug",
           "CreateDetAugmenter", "ImageDetIter"]


class DetAugmenter(object):
    """Base joint (image, label) augmenter (ref detection.py:DetAugmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return [type(self).__name__.lower(), self._kwargs]

    def __call__(self, src, label):
        raise NotImplementedError


class DetBorrowAug(DetAugmenter):
    """Lift an image-only augmenter into the detection pipeline; the
    label passes through unchanged (ref detection.py:DetBorrowAug)."""

    def __init__(self, augmenter):
        if not isinstance(augmenter, Augmenter):
            raise TypeError("DetBorrowAug requires an image Augmenter")
        super().__init__(augmenter=augmenter.dumps())
        self.augmenter = augmenter

    def __call__(self, src, label):
        return self.augmenter(src)[0], label


class DetRandomSelectAug(DetAugmenter):
    """Apply one randomly chosen member augmenter (or skip entirely with
    probability 1 - skip_prob... matching the reference's selection
    semantics: each call picks one of aug_list)."""

    def __init__(self, aug_list, skip_prob=0.0):
        super().__init__(skip_prob=skip_prob)
        self.aug_list = list(aug_list)
        self.skip_prob = skip_prob

    def __call__(self, src, label):
        if not self.aug_list or _rng.random() < self.skip_prob:
            return src, label
        return _rng.choice(self.aug_list)(src, label)


class DetHorizontalFlipAug(DetAugmenter):
    """Mirror image and x-coordinates together (ref DetHorizontalFlipAug)."""

    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def __call__(self, src, label):
        if _rng.random() >= self.p:
            return src, label
        img = _wrap(_to_np(src)[:, ::-1])
        flipped = label.copy()
        valid = flipped[:, 0] >= 0
        x0 = flipped[valid, 1].copy()
        flipped[valid, 1] = 1.0 - flipped[valid, 3]
        flipped[valid, 3] = 1.0 - x0
        return img, flipped


class DetRandomCropAug(DetAugmenter):
    """Random crop keeping enough object overlap (ref DetRandomCropAug).

    Tries ``max_attempts`` crops with area in [min_object_covered-scaled
    bounds]; keeps boxes whose center survives, re-normalised to the crop;
    falls back to the untouched input."""

    def __init__(self, min_object_covered=0.3, min_eject_coverage=0.3,
                 aspect_ratio_range=(0.75, 1.33), area_range=(0.3, 1.0),
                 max_attempts=20):
        super().__init__(min_object_covered=min_object_covered,
                         area_range=area_range)
        self.min_object_covered = min_object_covered
        self.min_eject_coverage = min_eject_coverage
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts

    def _try_crop(self, label):
        frac = _rng.uniform(*self.area_range)
        aspect = _rng.uniform(*self.aspect_ratio_range)
        cw = min(np.sqrt(frac * aspect), 1.0)
        ch = min(np.sqrt(frac / aspect), 1.0)
        cx0 = _rng.uniform(0, 1.0 - cw)
        cy0 = _rng.uniform(0, 1.0 - ch)
        crop = (cx0, cy0, cx0 + cw, cy0 + ch)

        valid = label[:, 0] >= 0
        if not valid.any():
            return crop, label
        boxes = label[valid, 1:5]
        ix0 = np.maximum(boxes[:, 0], crop[0])
        iy0 = np.maximum(boxes[:, 1], crop[1])
        ix1 = np.minimum(boxes[:, 2], crop[2])
        iy1 = np.minimum(boxes[:, 3], crop[3])
        inter = np.clip(ix1 - ix0, 0, None) * np.clip(iy1 - iy0, 0, None)
        area = (boxes[:, 2] - boxes[:, 0]) * (boxes[:, 3] - boxes[:, 1])
        coverage = np.where(area > 0, inter / np.maximum(area, 1e-12), 0)
        if coverage.max() < self.min_object_covered:
            return None, None

        keep = coverage >= self.min_eject_coverage
        out = np.full_like(label, -1.0)
        n_keep = int(keep.sum())
        if n_keep == 0:
            return None, None
        kept = boxes[keep]
        # re-normalise into crop coordinates
        new = np.empty_like(kept)
        new[:, 0] = (np.maximum(kept[:, 0], crop[0]) - crop[0]) / cw
        new[:, 1] = (np.maximum(kept[:, 1], crop[1]) - crop[1]) / ch
        new[:, 2] = (np.minimum(kept[:, 2], crop[2]) - crop[0]) / cw
        new[:, 3] = (np.minimum(kept[:, 3], crop[3]) - crop[1]) / ch
        out[:n_keep, 0] = label[valid, 0][keep]
        out[:n_keep, 1:5] = np.clip(new, 0.0, 1.0)
        return crop, out

    def __call__(self, src, label):
        arr = _to_np(src)
        h, w = arr.shape[:2]
        for _ in range(self.max_attempts):
            crop, new_label = self._try_crop(label)
            if crop is None:
                continue
            x0, y0 = int(crop[0] * w), int(crop[1] * h)
            x1, y1 = max(int(crop[2] * w), x0 + 1), max(int(crop[3] * h),
                                                        y0 + 1)
            return _wrap(arr[y0:y1, x0:x1]), new_label
        return src, label


class DetRandomPadAug(DetAugmenter):
    """Pad the image into a larger canvas, shrinking boxes accordingly
    (ref DetRandomPadAug)."""

    def __init__(self, aspect_ratio_range=(0.75, 1.33),
                 area_range=(1.0, 3.0), max_attempts=20,
                 pad_val=(127, 127, 127)):
        super().__init__(area_range=area_range)
        self.aspect_ratio_range = aspect_ratio_range
        self.area_range = area_range
        self.max_attempts = max_attempts
        self.pad_val = np.asarray(pad_val, np.float32)

    def __call__(self, src, label):
        arr = _to_np(src)
        h, w = arr.shape[:2]
        frac = _rng.uniform(*self.area_range)
        if frac <= 1.0:
            return src, label
        scale = np.sqrt(frac)
        new_h, new_w = int(h * scale), int(w * scale)
        oy = _rng.randint(0, new_h - h)
        ox = _rng.randint(0, new_w - w)
        canvas = np.empty((new_h, new_w, arr.shape[2]), arr.dtype)
        canvas[:] = self.pad_val[:arr.shape[2]].astype(arr.dtype)
        canvas[oy:oy + h, ox:ox + w] = arr
        out = label.copy()
        valid = out[:, 0] >= 0
        out[valid, 1] = (out[valid, 1] * w + ox) / new_w
        out[valid, 3] = (out[valid, 3] * w + ox) / new_w
        out[valid, 2] = (out[valid, 2] * h + oy) / new_h
        out[valid, 4] = (out[valid, 4] * h + oy) / new_h
        return _wrap(canvas), out


class _DetResize(DetAugmenter):
    """Force-resize to the network input; normalised boxes are invariant."""

    def __init__(self, width, height, interp=2):
        super().__init__(width=width, height=height)
        self.width, self.height, self.interp = width, height, interp

    def __call__(self, src, label):
        return imresize(src, self.width, self.height, self.interp), label


def CreateDetAugmenter(data_shape, resize=0, rand_crop=0, rand_pad=0,
                       rand_mirror=False, mean=None, std=None, brightness=0,
                       contrast=0, saturation=0, pca_noise=0, inter_method=2,
                       min_object_covered=0.3, aspect_ratio_range=(0.75, 1.33),
                       area_range=(0.3, 3.0), min_eject_coverage=0.3,
                       max_attempts=50, pad_val=(127, 127, 127)):
    """Standard detection augmentation list (ref CreateDetAugmenter)."""
    pipeline = []
    if rand_crop > 0:
        crop = DetRandomCropAug(min_object_covered=min_object_covered,
                                min_eject_coverage=min_eject_coverage,
                                aspect_ratio_range=aspect_ratio_range,
                                area_range=(area_range[0],
                                            min(area_range[1], 1.0)),
                                max_attempts=max_attempts)
        pipeline.append(DetRandomSelectAug([crop], 1.0 - rand_crop))
    if rand_pad > 0:
        pad = DetRandomPadAug(aspect_ratio_range=aspect_ratio_range,
                              area_range=(max(area_range[0], 1.0),
                                          area_range[1]),
                              max_attempts=max_attempts, pad_val=pad_val)
        pipeline.append(DetRandomSelectAug([pad], 1.0 - rand_pad))
    if rand_mirror:
        pipeline.append(DetHorizontalFlipAug(0.5))
    pipeline.append(_DetResize(data_shape[2], data_shape[1], inter_method))
    pipeline.append(DetBorrowAug(CastAug()))
    if brightness or contrast or saturation:
        pipeline.append(DetBorrowAug(
            ColorJitterAug(brightness, contrast, saturation)))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        pipeline.append(DetBorrowAug(ColorNormalizeAug(mean, std)))
    return pipeline


class ImageDetIter(ImageIter):
    """Detection iterator: (image, (obj, 5) label) batches
    (ref detection.py:ImageDetIter). Labels pad to the batch's max object
    count with -1 rows."""

    def __init__(self, batch_size, data_shape, label_width=-1,
                 aug_list=None, label_name="label", **kwargs):
        super().__init__(batch_size, data_shape, label_width=1,
                         aug_list=aug_list if aug_list is not None else [],
                         label_name=label_name, **kwargs)
        if aug_list is None:
            self.auglist = CreateDetAugmenter(data_shape)
        self._label_width = label_width
        self.provide_label = None       # set per batch (object count varies)
        self._label_name = label_name

    def _normalise_label(self, raw):
        """Raw header label → (obj, 5) [cls, x0, y0, x1, y1]."""
        arr = np.asarray(raw, np.float32).ravel()
        if arr.size % 5:
            arr = arr[arr.size % 5:]
        return arr.reshape(-1, 5)

    def next(self):
        from .image import imdecode
        c, h, w = self.data_shape
        data_buf = np.zeros((self.batch_size, h, w, c), np.float32)
        labels = []
        filled = 0
        try:
            while filled < self.batch_size:
                raw_label, blob = self.next_sample()
                img = imdecode(blob)
                label = self._normalise_label(raw_label)
                for aug in self.auglist:
                    img, label = aug(img, label)
                data_buf[filled] = _to_np(img).reshape(h, w, c)
                labels.append(label)
                filled += 1
        except StopIteration:
            if filled == 0:
                raise
        width = self._label_width if self._label_width > 0 else \
            max(max((l.shape[0] for l in labels), default=1), 1)
        label_buf = np.full((self.batch_size, width, 5), -1.0, np.float32)
        for i, l in enumerate(labels):
            label_buf[i, :min(width, l.shape[0])] = l[:width]
        batch = nd.array(data_buf.transpose(0, 3, 1, 2))
        return _io.DataBatch(
            [batch], [nd.array(label_buf)], pad=self.batch_size - filled,
            provide_data=[_io.DataDesc("data", batch.shape)],
            provide_label=[_io.DataDesc(self._label_name, label_buf.shape)])
