"""Image IO + augmentation pipeline.

Parity surface: reference ``python/mxnet/image/image.py`` — ``imdecode``,
``scale_down``, ``resize_short``, ``fixed_crop``, ``random_crop``,
``center_crop``, ``color_normalize``, augmenter classes, and ``ImageIter``
(python-side image pipeline over .rec / .lst files).

TPU note: decode/augment run on host (cv2) exactly like the reference's
OpenCV path (``src/io/image_aug_default.cc``); the device only sees the
final batched float tensor — one upload per batch.
"""
from __future__ import annotations

import os
import random as pyrandom

import numpy as np

try:
    import cv2
except ImportError:
    cv2 = None

from .. import ndarray as nd
from .. import io as _io
from .. import recordio

__all__ = ["imdecode", "scale_down", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "imresize", "CreateAugmenter", "Augmenter",
           "ResizeAug", "ForceResizeAug", "RandomCropAug", "RandomSizedCropAug",
           "CenterCropAug", "RandomOrderAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug",
           "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
           "HorizontalFlipAug", "CastAug", "ImageIter"]


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode an image byte buffer to an NDArray (HWC, BGR→RGB)
    (reference image.py:imdecode over src/io/image_io.cc)."""
    if cv2 is None:
        raise ImportError("imdecode requires cv2")
    img = cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), flag)
    if img is None:
        raise ValueError("Decoding image failed")
    if to_rgb and img.ndim == 3:
        img = cv2.cvtColor(img, cv2.COLOR_BGR2RGB)
    if img.ndim == 2:
        img = img[:, :, None]
    return nd.array(img, dtype=np.uint8)


def imresize(src, w, h, interp=1):
    arr = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    out = cv2.resize(arr, (w, h), interpolation=interp)
    if out.ndim == 2:
        out = out[:, :, None]
    return nd.array(out, dtype=out.dtype)


def scale_down(src_size, size):
    """Scale size down to fit in src_size (reference image.py:scale_down)."""
    w, h = size
    sw, sh = src_size
    if sh < h:
        w, h = float(w * sh) / h, sh
    if sw < w:
        w, h = sw, float(h * sw) / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize shorter edge to size (reference image.py:resize_short)."""
    arr = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    h, w = arr.shape[:2]
    if h > w:
        new_h, new_w = size * h // w, size
    else:
        new_h, new_w = size, size * w // h
    return imresize(arr, new_w, new_h, interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    arr = src.asnumpy() if isinstance(src, nd.NDArray) else np.asarray(src)
    out = arr[y0:y0 + h, x0:x0 + w]
    if size is not None and (w, h) != size:
        return imresize(out, size[0], size[1], interp)
    return nd.array(out, dtype=out.dtype)


def random_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = pyrandom.randint(0, w - new_w)
    y0 = pyrandom.randint(0, h - new_h)
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def center_crop(src, size, interp=2):
    h, w = src.shape[:2]
    new_w, new_h = scale_down((w, h), size)
    x0 = (w - new_w) // 2
    y0 = (h - new_h) // 2
    out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
    return out, (x0, y0, new_w, new_h)


def random_size_crop(src, size, min_area, ratio, interp=2):
    """Random crop w/ size in [min_area*area, area] and aspect in ratio."""
    h, w = src.shape[:2]
    area = h * w
    for _ in range(10):
        target_area = pyrandom.uniform(min_area, 1.0) * area
        new_ratio = pyrandom.uniform(*ratio)
        new_w = int(round(np.sqrt(target_area * new_ratio)))
        new_h = int(round(np.sqrt(target_area / new_ratio)))
        if pyrandom.random() < 0.5:
            new_h, new_w = new_w, new_h
        if new_w <= w and new_h <= h:
            x0 = pyrandom.randint(0, w - new_w)
            y0 = pyrandom.randint(0, h - new_h)
            out = fixed_crop(src, x0, y0, new_w, new_h, size, interp)
            return out, (x0, y0, new_w, new_h)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    if mean is not None:
        src = src - mean
    if std is not None:
        src = src / std
    return src


class Augmenter(object):
    """Image augmenter base (reference image.py:Augmenter)."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return [self.__class__.__name__.lower(), self._kwargs]

    def __call__(self, src):
        raise NotImplementedError


class ResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super(ResizeAug, self).__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return [resize_short(src, self.size, self.interp)]


class ForceResizeAug(Augmenter):
    def __init__(self, size, interp=2):
        super(ForceResizeAug, self).__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return [imresize(src, self.size[0], self.size[1], self.interp)]


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super(RandomCropAug, self).__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return [random_crop(src, self.size, self.interp)[0]]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, min_area, ratio, interp=2):
        super(RandomSizedCropAug, self).__init__(
            size=size, min_area=min_area, ratio=ratio, interp=interp)
        self.size = size
        self.min_area = min_area
        self.ratio = ratio
        self.interp = interp

    def __call__(self, src):
        return [random_size_crop(src, self.size, self.min_area,
                                 self.ratio, self.interp)[0]]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super(CenterCropAug, self).__init__(size=size, interp=interp)
        self.size = size
        self.interp = interp

    def __call__(self, src):
        return [center_crop(src, self.size, self.interp)[0]]


class RandomOrderAug(Augmenter):
    def __init__(self, ts):
        super(RandomOrderAug, self).__init__()
        self.ts = ts

    def __call__(self, src):
        pyrandom.shuffle(self.ts)
        srcs = [src]
        for t in self.ts:
            srcs = [j for i in srcs for j in t(i)]
        return srcs


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super(BrightnessJitterAug, self).__init__(brightness=brightness)
        self.brightness = brightness

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.brightness, self.brightness)
        return [src.astype(np.float32) * alpha]


class ContrastJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)

    def __init__(self, contrast):
        super(ContrastJitterAug, self).__init__(contrast=contrast)
        self.contrast = contrast

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.contrast, self.contrast)
        arr = src.asnumpy().astype(np.float32)
        gray = (arr * self._coef).sum() * (3.0 / arr.size)
        return [nd.array(arr * alpha + gray * (1.0 - alpha),
                         dtype=np.float32)]


class SaturationJitterAug(Augmenter):
    _coef = np.array([[[0.299, 0.587, 0.114]]], dtype=np.float32)

    def __init__(self, saturation):
        super(SaturationJitterAug, self).__init__(saturation=saturation)
        self.saturation = saturation

    def __call__(self, src):
        alpha = 1.0 + pyrandom.uniform(-self.saturation, self.saturation)
        arr = src.asnumpy().astype(np.float32)
        gray = (arr * self._coef).sum(axis=2, keepdims=True)
        return [nd.array(arr * alpha + gray * (1.0 - alpha),
                         dtype=np.float32)]


class HueJitterAug(Augmenter):
    def __init__(self, hue):
        super(HueJitterAug, self).__init__(hue=hue)
        self.hue = hue

    def __call__(self, src):
        alpha = pyrandom.uniform(-self.hue, self.hue)
        arr = src.asnumpy().astype(np.float32)
        u = np.cos(alpha * np.pi)
        w = np.sin(alpha * np.pi)
        bt = np.array([[1.0, 0.0, 0.0],
                       [0.0, u, -w],
                       [0.0, w, u]], dtype=np.float32)
        tyiq = np.array([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]], dtype=np.float32)
        ityiq = np.array([[1.0, 0.956, 0.621],
                          [1.0, -0.272, -0.647],
                          [1.0, -1.107, 1.705]], dtype=np.float32)
        t = np.dot(np.dot(ityiq, bt), tyiq).T
        return [nd.array(np.dot(arr, t), dtype=np.float32)]


class ColorJitterAug(RandomOrderAug):
    def __init__(self, brightness, contrast, saturation):
        ts = []
        if brightness > 0:
            ts.append(BrightnessJitterAug(brightness))
        if contrast > 0:
            ts.append(ContrastJitterAug(contrast))
        if saturation > 0:
            ts.append(SaturationJitterAug(saturation))
        super(ColorJitterAug, self).__init__(ts)


class LightingAug(Augmenter):
    """PCA-based lighting jitter (AlexNet style)."""

    def __init__(self, alphastd, eigval, eigvec):
        super(LightingAug, self).__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval)
        self.eigvec = np.asarray(eigvec)

    def __call__(self, src):
        alpha = np.random.normal(0, self.alphastd, size=(3,))
        rgb = np.dot(self.eigvec * alpha, self.eigval)
        return [src.astype(np.float32) + nd.array(rgb, dtype=np.float32)]


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super(ColorNormalizeAug, self).__init__()
        self.mean = nd.array(mean) if mean is not None else None
        self.std = nd.array(std) if std is not None else None

    def __call__(self, src):
        return [color_normalize(src.astype(np.float32), self.mean,
                                self.std)]


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super(HorizontalFlipAug, self).__init__(p=p)
        self.p = p

    def __call__(self, src):
        if pyrandom.random() < self.p:
            arr = src.asnumpy()[:, ::-1]
            return [nd.array(np.ascontiguousarray(arr), dtype=arr.dtype)]
        return [src]


class CastAug(Augmenter):
    def __call__(self, src):
        return [src.astype(np.float32)]


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """Create the standard augmenter list (reference image.py:CreateAugmenter)."""
    auglist = []
    if resize > 0:
        auglist.append(ResizeAug(resize, inter_method))
    crop_size = (data_shape[2], data_shape[1])
    if rand_resize:
        assert rand_crop
        auglist.append(RandomSizedCropAug(crop_size, 0.3, (3.0 / 4.0,
                                                           4.0 / 3.0),
                                          inter_method))
    elif rand_crop:
        auglist.append(RandomCropAug(crop_size, inter_method))
    else:
        auglist.append(CenterCropAug(crop_size, inter_method))
    if rand_mirror:
        auglist.append(HorizontalFlipAug(0.5))
    auglist.append(CastAug())
    if brightness or contrast or saturation:
        auglist.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        eigval = np.array([55.46, 4.794, 1.148])
        eigvec = np.array([[-0.5675, 0.7192, 0.4009],
                           [-0.5808, -0.0045, -0.8140],
                           [-0.5836, -0.6948, 0.4203]])
        auglist.append(LightingAug(pca_noise, eigval, eigvec))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        auglist.append(ColorNormalizeAug(mean, std))
    return auglist


class ImageIter(_io.DataIter):
    """Image data iterator over .rec files or .lst + raw images
    (reference image.py:ImageIter) with pluggable augmenters."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0,
                 num_parts=1, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label", **kwargs):
        super(ImageIter, self).__init__()
        assert path_imgrec or path_imglist or (isinstance(imglist, list))
        self.imgrec = None
        self.imglist = None
        if path_imgrec:
            if path_imgidx:
                self.imgrec = recordio.MXIndexedRecordIO(
                    path_imgidx, path_imgrec, "r")
                self.imgidx = list(self.imgrec.keys)
            else:
                self.imgrec = recordio.MXRecordIO(path_imgrec, "r")
                self.imgidx = None
        if path_imglist:
            with open(path_imglist) as fin:
                imglist = {}
                imgkeys = []
                for line in iter(fin.readline, ""):
                    line = line.strip().split("\t")
                    label = np.array([float(i) for i in line[1:-1]])
                    key = int(line[0])
                    imglist[key] = (label, line[-1])
                    imgkeys.append(key)
                self.imglist = imglist
                self.seq = imgkeys
        elif isinstance(imglist, list):
            result = {}
            imgkeys = []
            index = 1
            for img in imglist:
                key = str(index)
                index += 1
                label = np.array(img[0]) if isinstance(
                    img[0], (list, np.ndarray)) else np.array([img[0]])
                result[key] = (label, img[1])
                imgkeys.append(str(key))
            self.imglist = result
            self.seq = imgkeys
        else:
            self.seq = self.imgidx

        self.path_root = path_root
        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        if num_parts > 1 and self.seq is not None:
            assert part_index < num_parts
            n = len(self.seq) // num_parts
            self.seq = self.seq[part_index * n:(part_index + 1) * n]
        if aug_list is None:
            self.auglist = CreateAugmenter(data_shape, **kwargs)
        else:
            self.auglist = aug_list
        self.cur = 0
        self.provide_data = [
            _io.DataDesc(data_name, (batch_size,) + self.data_shape)]
        self.provide_label = [
            _io.DataDesc(label_name, (batch_size, label_width)
                         if label_width > 1 else (batch_size,))]
        self.reset()

    def reset(self):
        if self.shuffle and self.seq is not None:
            pyrandom.shuffle(self.seq)
        if self.imgrec is not None:
            self.imgrec.reset()
        self.cur = 0

    def next_sample(self):
        if self.seq is not None:
            if self.cur >= len(self.seq):
                raise StopIteration
            idx = self.seq[self.cur]
            self.cur += 1
            if self.imgrec is not None:
                s = self.imgrec.read_idx(idx)
                header, img = recordio.unpack(s)
                if self.imglist is None:
                    return header.label, img
                return self.imglist[idx][0], img
            label, fname = self.imglist[idx]
            with open(os.path.join(self.path_root, fname), "rb") as fin:
                img = fin.read()
            return label, img
        s = self.imgrec.read()
        if s is None:
            raise StopIteration
        header, img = recordio.unpack(s)
        return header.label, img

    def next(self):
        batch_size = self.batch_size
        c, h, w = self.data_shape
        batch_data = np.zeros((batch_size, h, w, c), dtype=np.float32)
        batch_label = np.zeros((batch_size,) + (
            (self.label_width,) if self.label_width > 1 else ()),
            dtype=np.float32)
        i = 0
        pad = 0
        try:
            while i < batch_size:
                label, s = self.next_sample()
                data = imdecode(s)
                for aug in self.auglist:
                    data = aug(data)[0]
                batch_data[i] = data.asnumpy().reshape(h, w, c)
                batch_label[i] = label
                i += 1
        except StopIteration:
            if i == 0:
                raise
            pad = batch_size - i
        # NCHW for the device
        arr = nd.array(batch_data.transpose(0, 3, 1, 2))
        return _io.DataBatch([arr], [nd.array(batch_label)], pad=pad)
