"""Host-side image decode + augmentation pipeline.

API parity with the reference ``python/mxnet/image/image.py`` (imdecode,
resize/crop helpers, the augmenter zoo, CreateAugmenter, ImageIter over
.rec/.lst sources). Independent design: every augmenter is a thin shell over
a ``_apply(float32 HWC numpy) -> numpy`` hook, and ImageIter reads samples
through a pluggable source object (record file vs. image list) instead of
branching inline.

TPU note: decode/augment stay on host (cv2), mirroring the reference's
OpenCV path (``src/io/image_aug_default.cc``); the device receives one
batched NCHW tensor per step.
"""
from __future__ import annotations

import os
import random as _rng

import numpy as np

try:
    import cv2
except ImportError:      # pragma: no cover - cv2 is present in CI
    cv2 = None

from .. import io as _io
from .. import ndarray as nd
from .. import recordio

__all__ = ["imdecode", "scale_down", "resize_short", "fixed_crop",
           "random_crop", "center_crop", "color_normalize",
           "random_size_crop", "imresize", "CreateAugmenter", "Augmenter",
           "ResizeAug", "ForceResizeAug", "RandomCropAug", "RandomSizedCropAug",
           "CenterCropAug", "RandomOrderAug", "BrightnessJitterAug",
           "ContrastJitterAug", "SaturationJitterAug", "HueJitterAug",
           "ColorJitterAug", "LightingAug", "ColorNormalizeAug",
           "HorizontalFlipAug", "CastAug", "ImageIter"]

# ITU-R BT.601 luma weights, used by contrast/saturation jitter.
_LUMA = np.array([0.299, 0.587, 0.114], dtype=np.float32)


def _to_np(img):
    """Accept NDArray or ndarray; return a numpy view (HWC)."""
    return img.asnumpy() if isinstance(img, nd.NDArray) else np.asarray(img)


def _wrap(arr, dtype=None):
    """numpy → NDArray, keeping dtype unless overridden."""
    a = np.ascontiguousarray(arr)
    return nd.array(a, dtype=dtype or a.dtype)


def imdecode(buf, flag=1, to_rgb=True, out=None):
    """Decode compressed image bytes into an HWC uint8 NDArray.

    Matches reference ``image.py:imdecode`` (backed by src/io/image_io.cc):
    channel order flips BGR→RGB unless ``to_rgb=False``; grayscale gets a
    trailing singleton channel.
    """
    if cv2 is None:
        raise ImportError("imdecode requires cv2")
    raw = cv2.imdecode(np.frombuffer(buf, dtype=np.uint8), flag)
    if raw is None:
        raise ValueError("Decoding image failed")
    if raw.ndim == 2:
        raw = raw[:, :, None]
    elif to_rgb:
        raw = cv2.cvtColor(raw, cv2.COLOR_BGR2RGB)
    return _wrap(raw, dtype=np.uint8)


def imresize(src, w, h, interp=1):
    """Resize to exactly (w, h) via cv2."""
    resized = cv2.resize(_to_np(src), (w, h), interpolation=interp)
    if resized.ndim == 2:
        resized = resized[:, :, None]
    return _wrap(resized)


def scale_down(src_size, size):
    """Shrink the requested crop (w, h) to fit inside src (w, h), keeping
    aspect (ref image.py:scale_down)."""
    sw, sh = src_size
    w, h = size
    if h > sh:
        w, h = w * sh / h, sh
    if w > sw:
        w, h = sw, h * sw / w
    return int(w), int(h)


def resize_short(src, size, interp=2):
    """Resize so the shorter edge equals *size* (ref image.py:resize_short)."""
    arr = _to_np(src)
    h, w = arr.shape[:2]
    if w < h:
        target = (size, size * h // w)      # (w, h)
    else:
        target = (size * w // h, size)
    return imresize(arr, target[0], target[1], interp)


def fixed_crop(src, x0, y0, w, h, size=None, interp=2):
    """Crop the [y0:y0+h, x0:x0+w] window, optionally resizing to *size*."""
    window = _to_np(src)[y0:y0 + h, x0:x0 + w]
    if size is not None and size != (w, h):
        return imresize(window, size[0], size[1], interp)
    return _wrap(window)


def random_crop(src, size, interp=2):
    """Uniformly-placed crop of (scaled-down) *size*; returns (img, box)."""
    h, w = src.shape[:2]
    cw, ch = scale_down((w, h), size)
    x0 = _rng.randint(0, w - cw)
    y0 = _rng.randint(0, h - ch)
    return fixed_crop(src, x0, y0, cw, ch, size, interp), (x0, y0, cw, ch)


def center_crop(src, size, interp=2):
    """Centered crop of (scaled-down) *size*; returns (img, box)."""
    h, w = src.shape[:2]
    cw, ch = scale_down((w, h), size)
    x0, y0 = (w - cw) // 2, (h - ch) // 2
    return fixed_crop(src, x0, y0, cw, ch, size, interp), (x0, y0, cw, ch)


def random_size_crop(src, size, min_area, ratio, interp=2):
    """Inception-style crop: random area fraction and aspect ratio, with a
    center-crop fallback after 10 failed attempts."""
    h, w = src.shape[:2]
    for _ in range(10):
        frac = _rng.uniform(min_area, 1.0)
        aspect = _rng.uniform(*ratio)
        cw = int(round(np.sqrt(h * w * frac * aspect)))
        ch = int(round(np.sqrt(h * w * frac / aspect)))
        if _rng.random() < 0.5:
            cw, ch = ch, cw
        if cw <= w and ch <= h:
            x0 = _rng.randint(0, w - cw)
            y0 = _rng.randint(0, h - ch)
            return fixed_crop(src, x0, y0, cw, ch, size, interp), \
                (x0, y0, cw, ch)
    return center_crop(src, size, interp)


def color_normalize(src, mean, std=None):
    """(src - mean) / std with broadcasting; either part optional."""
    out = src
    if mean is not None:
        out = out - mean
    if std is not None:
        out = out / std
    return out


# --------------------------------------------------------------------------
# Augmenter zoo. Each subclass implements _apply(img)->img on NDArray/numpy;
# __call__ wraps the result in a one-element list per the reference protocol
# (RandomOrderAug may fan out).
# --------------------------------------------------------------------------

class Augmenter(object):
    """Base augmenter; records ctor kwargs for ``dumps()`` introspection."""

    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def dumps(self):
        return [type(self).__name__.lower(), self._kwargs]

    def _apply(self, src):
        raise NotImplementedError

    def __call__(self, src):
        return [self._apply(src)]


class ResizeAug(Augmenter):
    """Shorter-edge resize."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def _apply(self, src):
        return resize_short(src, self.size, self.interp)


class ForceResizeAug(Augmenter):
    """Exact (w, h) resize, ignoring aspect."""

    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def _apply(self, src):
        return imresize(src, self.size[0], self.size[1], self.interp)


class RandomCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def _apply(self, src):
        return random_crop(src, self.size, self.interp)[0]


class RandomSizedCropAug(Augmenter):
    def __init__(self, size, min_area, ratio, interp=2):
        super().__init__(size=size, min_area=min_area, ratio=ratio,
                         interp=interp)
        self.size, self.min_area = size, min_area
        self.ratio, self.interp = ratio, interp

    def _apply(self, src):
        return random_size_crop(src, self.size, self.min_area, self.ratio,
                                self.interp)[0]


class CenterCropAug(Augmenter):
    def __init__(self, size, interp=2):
        super().__init__(size=size, interp=interp)
        self.size, self.interp = size, interp

    def _apply(self, src):
        return center_crop(src, self.size, self.interp)[0]


class RandomOrderAug(Augmenter):
    """Apply child augmenters in a freshly shuffled order each call."""

    def __init__(self, ts):
        super().__init__()
        self.ts = ts

    def __call__(self, src):
        order = list(self.ts)
        _rng.shuffle(order)
        imgs = [src]
        for aug in order:
            imgs = [out for img in imgs for out in aug(img)]
        return imgs


class BrightnessJitterAug(Augmenter):
    def __init__(self, brightness):
        super().__init__(brightness=brightness)
        self.brightness = brightness

    def _apply(self, src):
        gain = 1.0 + _rng.uniform(-self.brightness, self.brightness)
        return _wrap(_to_np(src).astype(np.float32) * gain)


class ContrastJitterAug(Augmenter):
    """Blend toward the image's mean luma."""

    def __init__(self, contrast):
        super().__init__(contrast=contrast)
        self.contrast = contrast

    def _apply(self, src):
        gain = 1.0 + _rng.uniform(-self.contrast, self.contrast)
        arr = _to_np(src).astype(np.float32)
        mean_luma = float((arr * _LUMA).sum()) * 3.0 / arr.size
        return _wrap(arr * gain + mean_luma * (1.0 - gain))


class SaturationJitterAug(Augmenter):
    """Blend toward the per-pixel luma (grayscale)."""

    def __init__(self, saturation):
        super().__init__(saturation=saturation)
        self.saturation = saturation

    def _apply(self, src):
        gain = 1.0 + _rng.uniform(-self.saturation, self.saturation)
        arr = _to_np(src).astype(np.float32)
        gray = (arr * _LUMA).sum(axis=2, keepdims=True)
        return _wrap(arr * gain + gray * (1.0 - gain))


class HueJitterAug(Augmenter):
    """Rotate hue in YIQ space by a random angle."""

    # RGB→YIQ and back (NTSC).
    _RGB2YIQ = np.array([[0.299, 0.587, 0.114],
                         [0.596, -0.274, -0.321],
                         [0.211, -0.523, 0.311]], dtype=np.float32)
    _YIQ2RGB = np.array([[1.0, 0.956, 0.621],
                         [1.0, -0.272, -0.647],
                         [1.0, -1.107, 1.705]], dtype=np.float32)

    def __init__(self, hue):
        super().__init__(hue=hue)
        self.hue = hue

    def _apply(self, src):
        theta = _rng.uniform(-self.hue, self.hue) * np.pi
        c, s = np.cos(theta), np.sin(theta)
        rot = np.array([[1.0, 0.0, 0.0],
                        [0.0, c, -s],
                        [0.0, s, c]], dtype=np.float32)
        full = (self._YIQ2RGB @ rot @ self._RGB2YIQ).T
        return _wrap(_to_np(src).astype(np.float32) @ full)


class ColorJitterAug(RandomOrderAug):
    """Brightness/contrast/saturation jitter in random order."""

    def __init__(self, brightness, contrast, saturation):
        members = [cls(v) for cls, v in
                   ((BrightnessJitterAug, brightness),
                    (ContrastJitterAug, contrast),
                    (SaturationJitterAug, saturation)) if v > 0]
        super().__init__(members)


class LightingAug(Augmenter):
    """AlexNet PCA lighting noise along RGB eigenvectors."""

    def __init__(self, alphastd, eigval, eigvec):
        super().__init__(alphastd=alphastd)
        self.alphastd = alphastd
        self.eigval = np.asarray(eigval, dtype=np.float32)
        self.eigvec = np.asarray(eigvec, dtype=np.float32)

    def _apply(self, src):
        alpha = np.random.normal(0.0, self.alphastd, size=(3,))
        shift = (self.eigvec * alpha) @ self.eigval
        return _wrap(_to_np(src).astype(np.float32) + shift.astype(np.float32))


class ColorNormalizeAug(Augmenter):
    def __init__(self, mean, std):
        super().__init__()
        self.mean = None if mean is None else np.asarray(mean, np.float32)
        self.std = None if std is None else np.asarray(std, np.float32)

    def _apply(self, src):
        arr = _to_np(src).astype(np.float32)
        return _wrap(color_normalize(arr, self.mean, self.std))


class HorizontalFlipAug(Augmenter):
    def __init__(self, p):
        super().__init__(p=p)
        self.p = p

    def _apply(self, src):
        if _rng.random() >= self.p:
            return src
        return _wrap(_to_np(src)[:, ::-1])


class CastAug(Augmenter):
    def _apply(self, src):
        return _wrap(_to_np(src).astype(np.float32))


def CreateAugmenter(data_shape, resize=0, rand_crop=False, rand_resize=False,
                    rand_mirror=False, mean=None, std=None, brightness=0,
                    contrast=0, saturation=0, pca_noise=0, inter_method=2):
    """Assemble the standard train/val augmentation list
    (ref image.py:CreateAugmenter). data_shape is CHW."""
    crop = (data_shape[2], data_shape[1])       # (w, h)
    pipeline = []
    if resize > 0:
        pipeline.append(ResizeAug(resize, inter_method))
    if rand_resize:
        if not rand_crop:
            raise ValueError("rand_resize requires rand_crop=True")
        pipeline.append(
            RandomSizedCropAug(crop, 0.3, (3.0 / 4.0, 4.0 / 3.0),
                               inter_method))
    elif rand_crop:
        pipeline.append(RandomCropAug(crop, inter_method))
    else:
        pipeline.append(CenterCropAug(crop, inter_method))
    if rand_mirror:
        pipeline.append(HorizontalFlipAug(0.5))
    pipeline.append(CastAug())
    if brightness or contrast or saturation:
        pipeline.append(ColorJitterAug(brightness, contrast, saturation))
    if pca_noise > 0:
        # ImageNet RGB covariance eigendecomposition (AlexNet paper values).
        pipeline.append(LightingAug(
            pca_noise,
            [55.46, 4.794, 1.148],
            [[-0.5675, 0.7192, 0.4009],
             [-0.5808, -0.0045, -0.8140],
             [-0.5836, -0.6948, 0.4203]]))
    if mean is True:
        mean = np.array([123.68, 116.28, 103.53])
    if std is True:
        std = np.array([58.395, 57.12, 57.375])
    if mean is not None or std is not None:
        pipeline.append(ColorNormalizeAug(mean, std))
    return pipeline


# --------------------------------------------------------------------------
# Sample sources for ImageIter: each yields (label, raw_bytes) and supports
# reset(). Keeping them separate keeps the iterator itself source-agnostic.
# --------------------------------------------------------------------------

class _RecordSource:
    """Sequential or index-ordered reads from a .rec (+ optional .idx)."""

    def __init__(self, path_imgrec, path_imgidx=None):
        if path_imgidx:
            self.rec = recordio.MXIndexedRecordIO(path_imgidx, path_imgrec, "r")
            self.keys = list(self.rec.keys)
        else:
            self.rec = recordio.MXRecordIO(path_imgrec, "r")
            self.keys = None

    def reset(self):
        self.rec.reset()

    def read_sequential(self):
        blob = self.rec.read()
        if blob is None:
            raise StopIteration
        header, img = recordio.unpack(blob)
        return header.label, img

    def read_key(self, key):
        header, img = recordio.unpack(self.rec.read_idx(key))
        return header.label, img


class _ListSource:
    """Samples named by key → (label, filename-or-bytes) mapping."""

    def __init__(self, table, keys, path_root):
        self.table = table
        self.keys = keys
        self.root = path_root or ""

    def reset(self):
        pass

    def read_key(self, key):
        label, fname = self.table[key]
        with open(os.path.join(self.root, fname), "rb") as fh:
            return label, fh.read()


def _parse_lst_file(path):
    """Parse a .lst file: ``key \\t label... \\t relative-path`` per line."""
    table, keys = {}, []
    with open(path) as fh:
        for line in fh:
            cells = line.strip().split("\t")
            if len(cells) < 3:
                continue
            key = int(cells[0])
            table[key] = (np.array([float(v) for v in cells[1:-1]]), cells[-1])
            keys.append(key)
    return table, keys


def _parse_inline_list(entries):
    """Normalise a python list of (label, fname) pairs into a keyed table."""
    table, keys = {}, []
    for pos, (label, fname) in enumerate(entries, start=1):
        key = str(pos)
        label = np.asarray(label) if isinstance(label, (list, np.ndarray)) \
            else np.array([label])
        table[key] = (label, fname)
        keys.append(key)
    return table, keys


class ImageIter(_io.DataIter):
    """Decode-and-augment iterator over .rec files or image lists
    (ref image.py:ImageIter), emitting NCHW float batches."""

    def __init__(self, batch_size, data_shape, label_width=1,
                 path_imgrec=None, path_imglist=None, path_root=None,
                 path_imgidx=None, shuffle=False, part_index=0,
                 num_parts=1, aug_list=None, imglist=None,
                 data_name="data", label_name="softmax_label", **kwargs):
        super().__init__()
        if not (path_imgrec or path_imglist or isinstance(imglist, list)):
            raise ValueError("need path_imgrec, path_imglist, or imglist")

        self._record = _RecordSource(path_imgrec, path_imgidx) \
            if path_imgrec else None
        self._list = None
        if path_imglist:
            table, keys = _parse_lst_file(path_imglist)
            self._list = _ListSource(table, keys, path_root)
            self._order = keys
        elif isinstance(imglist, list):
            table, keys = _parse_inline_list(imglist)
            self._list = _ListSource(table, keys, path_root)
            self._order = keys
        else:
            self._order = self._record.keys   # None for non-indexed .rec

        self.batch_size = batch_size
        self.data_shape = tuple(data_shape)
        self.label_width = label_width
        self.shuffle = shuffle
        if num_parts > 1 and self._order is not None:
            if part_index >= num_parts:
                raise ValueError("part_index out of range")
            span = len(self._order) // num_parts
            self._order = self._order[part_index * span:
                                      (part_index + 1) * span]
        self.auglist = aug_list if aug_list is not None \
            else CreateAugmenter(data_shape, **kwargs)

        label_shape = (batch_size, label_width) if label_width > 1 \
            else (batch_size,)
        self.provide_data = [_io.DataDesc(data_name,
                                          (batch_size,) + self.data_shape)]
        self.provide_label = [_io.DataDesc(label_name, label_shape)]
        self._pos = 0
        self.reset()

    def reset(self):
        if self.shuffle and self._order is not None:
            _rng.shuffle(self._order)
        if self._record is not None:
            self._record.reset()
        self._pos = 0

    def next_sample(self):
        """Return one (label, raw-bytes) sample, honouring the shuffle order."""
        if self._order is None:
            return self._record.read_sequential()
        if self._pos >= len(self._order):
            raise StopIteration
        key = self._order[self._pos]
        self._pos += 1
        if self._record is not None:
            label, img = self._record.read_key(key)
            if self._list is not None:      # .lst labels override header
                label = self._list.table[key][0]
            return label, img
        return self._list.read_key(key)

    def next(self):
        c, h, w = self.data_shape
        data_buf = np.zeros((self.batch_size, h, w, c), dtype=np.float32)
        label_shape = (self.batch_size, self.label_width) \
            if self.label_width > 1 else (self.batch_size,)
        label_buf = np.zeros(label_shape, dtype=np.float32)

        filled = 0
        try:
            while filled < self.batch_size:
                label, blob = self.next_sample()
                img = imdecode(blob)
                for aug in self.auglist:
                    img = aug(img)[0]
                data_buf[filled] = _to_np(img).reshape(h, w, c)
                label_buf[filled] = label
                filled += 1
        except StopIteration:
            if filled == 0:
                raise
        # host HWC → device NCHW, single upload per batch
        batch = nd.array(data_buf.transpose(0, 3, 1, 2))
        return _io.DataBatch([batch], [nd.array(label_buf)],
                             pad=self.batch_size - filled)
