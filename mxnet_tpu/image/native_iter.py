"""ImageRecordIter: the native threaded decode pipeline's Python face.

Reference analogue: the registered native iterator
``src/io/iter_image_recordio_2.cc:723`` (M decode threads + prefetcher).
This binds native/image_loader.cc over ctypes: record indexing, JPEG
decode, resize, mirror and batch assembly all happen in C++ worker
threads, one batch prefetched ahead; Python sees ready float32 NCHW
buffers (scaled to [0, 1]) and uploads once per batch.

Falls back with ImportError when the shared object is absent (build with
``make -C native``).
"""
from __future__ import annotations

import ctypes

import numpy as np

from .. import io as _io
from .. import ndarray as nd

__all__ = ["ImageRecordIter"]

def _lib():
    from .._native import load_shared
    lib = load_shared("libimageloader.so",
                      required_symbol="mx_imgloader_last_failed")
    if lib is None:
        raise ImportError("libimageloader.so not built (make -C native)")
    lib.mx_imgloader_create.restype = ctypes.c_void_p
    lib.mx_imgloader_create.argtypes = [
        ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_int, ctypes.c_uint,
        ctypes.c_int]
    lib.mx_imgloader_num_samples.restype = ctypes.c_int64
    lib.mx_imgloader_num_samples.argtypes = [ctypes.c_void_p]
    lib.mx_imgloader_next.restype = ctypes.c_int
    lib.mx_imgloader_next.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_float),
        ctypes.POINTER(ctypes.c_float)]
    lib.mx_imgloader_reset.argtypes = [ctypes.c_void_p]
    lib.mx_imgloader_destroy.argtypes = [ctypes.c_void_p]
    lib.mx_imgloader_failures.restype = ctypes.c_long
    lib.mx_imgloader_failures.argtypes = [ctypes.c_void_p]
    lib.mx_imgloader_last_failed.restype = ctypes.c_int
    lib.mx_imgloader_last_failed.argtypes = [ctypes.c_void_p]
    return lib


class ImageRecordIter(_io.DataIter):
    """Threaded native .rec image iterator (ref iter_image_recordio_2.cc).

    Emits (data NCHW float32 in [0,1] — optionally mean/scale adjusted —
    and scalar labels).
    """

    def __init__(self, path_imgrec, data_shape, batch_size,
                 shuffle=False, preprocess_threads=4, rand_mirror=False,
                 seed=0, mean_rgb=None, scale=None, data_name="data",
                 label_name="softmax_label", allow_corrupt=False,
                 **kwargs):
        super().__init__(batch_size)
        c, h, w = data_shape
        self._allow_corrupt = bool(allow_corrupt)
        self._lib = _lib()
        self._handle = self._lib.mx_imgloader_create(
            str(path_imgrec).encode(), batch_size, h, w, c,
            int(preprocess_threads), int(bool(shuffle)), int(seed),
            int(bool(rand_mirror)))
        if not self._handle:
            raise IOError("cannot open record file %s" % path_imgrec)
        self.data_shape = (c, h, w)
        self._data_buf = np.empty((batch_size, c, h, w), np.float32)
        self._label_buf = np.empty((batch_size,), np.float32)
        self._mean = None if mean_rgb is None else \
            (np.asarray(mean_rgb, np.float32) / 255.0).reshape(1, -1, 1, 1)
        self._scale = scale
        self.provide_data = [_io.DataDesc(data_name,
                                          (batch_size,) + self.data_shape)]
        self.provide_label = [_io.DataDesc(label_name, (batch_size,))]

    @property
    def num_samples(self):
        return int(self._lib.mx_imgloader_num_samples(self._handle))

    @property
    def num_failed(self):
        """Cumulative records dropped for decode failure (only grows
        with allow_corrupt=True; strict mode raises instead)."""
        return int(self._lib.mx_imgloader_failures(self._handle))

    def reset(self):
        self._lib.mx_imgloader_reset(self._handle)

    def next(self):
        n = self._lib.mx_imgloader_next(
            self._handle,
            self._data_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            self._label_buf.ctypes.data_as(ctypes.POINTER(ctypes.c_float)))
        fresh = int(self._lib.mx_imgloader_last_failed(self._handle))
        if fresh and not self._allow_corrupt:
            # training on garbage must be loud; with allow_corrupt=True
            # corrupt records are COMPACTED OUT of the batch (true
            # skip-and-count, like the reference's skip-and-log)
            raise IOError(
                "%d record(s) failed to decode (corrupt or non-JPEG "
                "payload); pass allow_corrupt=True to skip them"
                % fresh)
        if n == 0:
            raise StopIteration
        data = self._data_buf
        if self._mean is not None:
            data = data - self._mean
        if self._scale is not None:
            data = data * self._scale
        return _io.DataBatch([nd.array(data)],
                             [nd.array(self._label_buf.copy())],
                             pad=self.batch_size - n)

    def __del__(self):
        handle, self._handle = getattr(self, "_handle", None), None
        if handle:
            self._lib.mx_imgloader_destroy(handle)
