"""Pluggable stream opener: URI-scheme dispatch for save/load/RecordIO.

Reference parity: dmlc-core streams let every reference save/load path
accept ``s3://`` and ``hdfs://`` URIs transparently
(``include/mxnet/ndarray.h:340`` Save/Load take dmlc::Stream;
``dmlc/io.h`` Stream::Create dispatches on the URI scheme). This rebuild
keeps the same shape with a registry of Python openers instead of C++
stream subclasses: anything with a scheme prefix routes to its registered
opener (an fsspec-style callable), bare paths go to ``open``.

Usage::

    import mxnet_tpu as mx

    def s3_opener(uri, mode):
        import s3fs                       # any fsspec filesystem
        return s3fs.S3FileSystem().open(uri, mode)

    mx.stream.register_scheme("s3", s3_opener)
    mx.nd.save("s3://bucket/model.params", {"w": w})   # just works

Zero-egress note: no cloud SDKs ship in this image, so the built-in
schemes are ``file`` and ``mem`` (an in-process store used by tests and
handy for ephemeral checkpoints); cloud filesystems plug in via the same
hook without framework changes.
"""
from __future__ import annotations

import io
import os
import re
import threading

from .base import MXNetError

__all__ = ["register_scheme", "unregister_scheme", "open_stream",
           "registered_schemes", "split_scheme"]

_SCHEME_RE = re.compile(r"^([A-Za-z][A-Za-z0-9+.-]*)://")

_REGISTRY = {}
_LOCK = threading.Lock()


def split_scheme(uri):
    """('s3', 'bucket/key') for 's3://bucket/key'; (None, uri) for bare
    paths. Windows drive letters ('C:/x') have no '//' so they stay
    bare paths."""
    if not isinstance(uri, (str, os.PathLike)):
        return None, uri
    s = os.fspath(uri)
    m = _SCHEME_RE.match(s)
    if not m:
        return None, s
    return m.group(1).lower(), s[m.end():]


def register_scheme(scheme, opener):
    """Install ``opener(uri, mode) -> file-like`` for ``scheme://`` URIs.

    The opener receives the FULL uri (scheme included, the fsspec
    convention) and a binary/text mode string. Re-registering a scheme
    replaces the previous opener (returned, for restore-style tests)."""
    if not re.match(r"^[A-Za-z][A-Za-z0-9+.-]*$", scheme or ""):
        raise MXNetError("invalid scheme %r" % (scheme,))
    with _LOCK:
        prev = _REGISTRY.get(scheme.lower())
        _REGISTRY[scheme.lower()] = opener
    return prev


def unregister_scheme(scheme):
    with _LOCK:
        return _REGISTRY.pop(scheme.lower(), None)


def registered_schemes():
    with _LOCK:
        return sorted(_REGISTRY)


def open_stream(uri, mode="rb"):
    """Open *uri* for reading/writing. Scheme-prefixed URIs dispatch to
    their registered opener; bare paths use the local filesystem."""
    scheme, _rest = split_scheme(uri)
    if scheme is None or scheme == "file":
        path = _rest if scheme == "file" else os.fspath(uri)
        return open(path, mode)
    with _LOCK:
        opener = _REGISTRY.get(scheme)
    if opener is None:
        raise MXNetError(
            "no stream opener registered for scheme %r (uri %r); "
            "register one with mxnet_tpu.stream.register_scheme"
            % (scheme, uri))
    return opener(os.fspath(uri), mode)


# ---------------------------------------------------------------------------
# mem:// — in-process store (tests, ephemeral checkpoints)
# ---------------------------------------------------------------------------

_MEM = {}
_MEM_LOCK = threading.Lock()


class _MemWriter(io.BytesIO):
    def __init__(self, key):
        super().__init__()
        self._key = key

    def close(self):
        with _MEM_LOCK:
            _MEM[self._key] = self.getvalue()
        super().close()


def _mem_opener(uri, mode):
    _, key = split_scheme(uri)
    if "w" in mode:
        writer = _MemWriter(key)
        return writer if "b" in mode else io.TextIOWrapper(writer)
    with _MEM_LOCK:
        if key not in _MEM:
            raise FileNotFoundError(uri)
        raw = io.BytesIO(_MEM[key])
    return raw if "b" in mode else io.TextIOWrapper(raw)


register_scheme("mem", _mem_opener)
