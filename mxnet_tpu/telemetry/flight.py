"""Flight recorder: always-on crash ring + post-mortem dump hooks.

The telemetry plane (``..core``) answers questions when a run ends
cleanly and someone remembers to dump.  Production jobs mostly don't die
cleanly: they segfault a worker, OOM, get SIGTERMed by the scheduler, or
hang in a collective.  This module keeps the last N interesting events in
a fixed-size ring at near-zero cost, and dumps

    flight_<pid>.json  =  ring + telemetry snapshot + every thread's
                          Python stack

whenever the process is about to die (uncaught exception on any thread,
SIGTERM/SIGABRT) or looks wedged (no step-span exit for
``MXNET_HANG_DUMP_SECS`` seconds).

Design constraints, in order:

* **Always on.**  Unlike spans (gated on ``MXNET_TELEMETRY``), the ring
  records whenever the process runs; ``MXNET_FLIGHT_EVENTS=0`` is the
  opt-out.  A crash you did not anticipate is the one you most need
  forensics for.
* **Lock-cheap.**  ``deque(maxlen=N).append`` is a single GIL-atomic
  operation — no lock on the record path, ever.  Readers (dump, the
  ``/flight`` endpoint) take a list() copy, which deque iteration makes
  safe enough for forensics (worst case: one racing eviction re-read).
* **Fail silent.**  Every dump path swallows its own errors: the flight
  recorder must never turn a SIGTERM into a hang or mask the original
  exception.

Feeders: span exits and compile events (``core``), host-engine pushes
(``mxnet_tpu.engine``), sanitizer violations (``mxnet_tpu.lint``).
Stdlib-only, and no sibling import at module level — ``core`` imports
this module, not vice versa (the snapshot needed at dump time is fetched
lazily).
"""
from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
import traceback
from collections import deque

__all__ = ["record", "note_span", "events", "configure", "capacity",
           "enabled", "step_count", "last_step_age", "payload", "dump",
           "thread_stacks", "install_crash_hooks", "start_hang_watchdog",
           "reset", "restore_progress"]

DEFAULT_EVENTS = 2048


def _env_capacity():
    try:
        return max(0, int(os.environ.get("MXNET_FLIGHT_EVENTS",
                                         DEFAULT_EVENTS)))
    except ValueError:
        return DEFAULT_EVENTS


def _env_hang_secs():
    try:
        return max(0.0, float(os.environ.get("MXNET_HANG_DUMP_SECS", 0)))
    except ValueError:
        return 0.0


DEFAULT_KEEP = 8


def _env_keep():
    try:
        return max(0, int(os.environ.get("MXNET_FLIGHT_KEEP",
                                         DEFAULT_KEEP)))
    except ValueError:
        return DEFAULT_KEEP


_CAPACITY = _env_capacity()
_KEEP = _env_keep()
_ring = deque(maxlen=_CAPACITY or 1)
_DUMP_DIR = os.environ.get("MXNET_FLIGHT_DIR", "") or None

# core injects its trace clock so ring timestamps line up with the
# Chrome traceEvents; standalone (tests importing flight directly) falls
# back to a private epoch
_t0 = time.perf_counter()
_clock = lambda: (time.perf_counter() - _t0) * 1e6   # noqa: E731


def set_clock(fn):
    global _clock
    _clock = fn


def enabled():
    return _CAPACITY > 0


def capacity():
    return _CAPACITY


def configure(max_events=None, keep=None):
    """Resize (or 0-disable) the ring / retention; tests and notebooks."""
    global _CAPACITY, _KEEP, _ring
    if max_events is not None:
        _CAPACITY = max(0, int(max_events))
        _ring = deque(list(_ring)[-(_CAPACITY or 1):],
                      maxlen=_CAPACITY or 1)
    if keep is not None:
        _KEEP = max(0, int(keep))


# --------------------------------------------------------------------------
# the ring
# --------------------------------------------------------------------------

def record(kind, name, **fields):
    """Append one event; the single deque.append is the whole cost."""
    if not _CAPACITY:
        return
    ev = {"ts_us": round(_clock(), 1), "kind": kind, "name": name}
    if fields:
        ev.update(fields)
    _ring.append(ev)


# step-progress clock for the hang watchdog and /healthz: monotonic
# timestamp + count of step-span exits.  Single-writer in practice (the
# training thread); worst case under races is a skewed age, never a crash.
_last_step = [0.0]
_steps = [0]


def note_span(name, cat, dur_us=None):
    """Span-exit feeder called by ``core.span.__exit__`` — with a
    duration on the traced path, without one on the telemetry-off path
    (where only step/program progress is worth the append)."""
    if cat == "step":
        _steps[0] += 1
        _last_step[0] = time.monotonic()
    if not _CAPACITY:
        return
    ev = {"ts_us": round(_clock(), 1), "kind": "span", "name": name,
          "cat": cat}
    if dur_us is not None:
        ev["dur_us"] = round(dur_us, 1)
    _ring.append(ev)


def events():
    """A list copy of the ring, oldest first."""
    return list(_ring)


def step_count():
    return _steps[0]


def last_step_age():
    """Seconds since the last step-span exit; None before the first."""
    if not _steps[0]:
        return None
    return time.monotonic() - _last_step[0]


def reset():
    """Clear ring + progress clock (tests); hooks stay installed."""
    _ring.clear()
    _steps[0] = 0
    _last_step[0] = 0.0


def restore_progress(steps):
    """Seed the step clock from a restored checkpoint so post-resume
    flight dumps and ``/healthz`` report fleet-cumulative steps instead
    of restarting from zero; the stall age restarts now."""
    _steps[0] = max(0, int(steps))
    _last_step[0] = time.monotonic()


# --------------------------------------------------------------------------
# post-mortem payload + dump
# --------------------------------------------------------------------------

def thread_stacks():
    """Python stack of every live thread, keyed "<name>-<ident>"."""
    names = {t.ident: t.name for t in threading.enumerate()}
    stacks = {}
    for ident, frame in sys._current_frames().items():
        label = "%s-%d" % (names.get(ident, "unknown"), ident)
        stacks[label] = traceback.format_stack(frame)
    return stacks


def payload(reason):
    """Everything a post-mortem needs, JSON-shaped.

    The snapshot is taken with bounded lock acquires: a signal handler
    runs on the main thread BETWEEN bytecodes, so any telemetry lock the
    interrupted code holds would never be released — a blocking acquire
    here would turn SIGTERM into a hang."""
    try:
        from . import core
        snap = core.snapshot(lock_timeout=1.0)
    except Exception:
        snap = None
    return {"version": 1,
            "reason": reason,
            "pid": os.getpid(),
            "argv": list(sys.argv),
            "unix_time": time.time(),
            "steps": _steps[0],
            "last_step_age_s": last_step_age(),
            "ring": events(),
            "snapshot": snap,
            "stacks": thread_stacks()}


def _sweep_old_dumps(directory, keep_path):
    """Retention: keep the newest ``MXNET_FLIGHT_KEEP`` flight dumps in
    *directory*, deleting oldest-first (by mtime).  A long-lived host
    that restarts workers for months accumulates one ``flight_<pid>``
    per incarnation; eight post-mortems back is plenty.  Only files
    matching the exact ``flight_<digits>.json`` pattern are candidates,
    the file just written never is, and every error is swallowed — the
    sweep must not turn a crash dump into a second crash."""
    if not _KEEP:
        return
    try:
        candidates = []
        for name in os.listdir(directory):
            if not (name.startswith("flight_") and name.endswith(".json")
                    and name[7:-5].isdigit()):
                continue
            path = os.path.join(directory, name)
            if path == keep_path:
                continue
            try:
                candidates.append((os.path.getmtime(path), path))
            except OSError:
                continue
        # keep_path occupies one retention slot
        excess = len(candidates) - (_KEEP - 1)
        for _, path in sorted(candidates)[:max(0, excess)]:
            try:
                os.remove(path)
            except OSError:
                pass
    except Exception:
        pass


def dump(reason="manual", directory=None):
    """Write ``flight_<pid>.json`` (MXNET_FLIGHT_DIR or cwd); returns the
    path.  One file per pid — a later dump (e.g. the excepthook after a
    hang dump) overwrites with the more recent state, atomically via a
    same-directory rename so a reader never sees a torn file.  After the
    write, dumps beyond ``MXNET_FLIGHT_KEEP`` (default 8) are swept
    oldest-first."""
    directory = directory or _DUMP_DIR or os.getcwd()
    path = os.path.join(directory, "flight_%d.json" % os.getpid())
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload(reason), f, indent=1, default=repr)
    os.replace(tmp, path)
    _sweep_old_dumps(directory, path)
    try:
        # best-effort counter bump: same signal-context rule as above —
        # never block on a lock the interrupted main thread may hold
        from . import core
        if core._mlock.acquire(timeout=0.5):
            try:
                core._counters["flight_dumps"] = \
                    core._counters.get("flight_dumps", 0) + 1
            finally:
                core._mlock.release()
    except Exception:
        pass
    return path


def _safe_dump(reason):
    try:
        return dump(reason)
    except Exception:           # forensics must never mask the crash
        return None


# --------------------------------------------------------------------------
# crash hooks
# --------------------------------------------------------------------------

_excepthooks_installed = False
_signals_installed = False
_CRASH_SIGNALS = ("SIGTERM", "SIGABRT")


def install_crash_hooks():
    """Chain the flight dump into ``sys.excepthook``,
    ``threading.excepthook``, and the default SIGTERM/SIGABRT handlers.

    Idempotent, and the two halves are tracked separately: signal
    handlers can only be installed from the main thread, so a first call
    from a worker thread (lazy import) installs the excepthooks and a
    later main-thread call still gets to claim the signals.  Signals are
    only taken over while their disposition is SIG_DFL — an application
    that registered its own SIGTERM handling keeps it.  The dump runs
    first, then the previous behavior (print-traceback / process death)
    proceeds unchanged.
    """
    global _excepthooks_installed, _signals_installed
    if not _CAPACITY:
        return
    if not _excepthooks_installed:
        _excepthooks_installed = True

        prev_except = sys.excepthook

        def _excepthook(exc_type, exc, tb):
            record("crash", getattr(exc_type, "__name__", str(exc_type)),
                   message=str(exc)[:500])
            _safe_dump("excepthook:%s"
                       % getattr(exc_type, "__name__", "?"))
            prev_except(exc_type, exc, tb)

        sys.excepthook = _excepthook

        prev_thread_except = threading.excepthook

        def _thread_excepthook(args):
            record("crash", getattr(args.exc_type, "__name__", "?"),
                   thread=getattr(args.thread, "name", "?"),
                   message=str(args.exc_value)[:500])
            _safe_dump("thread-excepthook:%s"
                       % getattr(args.exc_type, "__name__", "?"))
            prev_thread_except(args)

        threading.excepthook = _thread_excepthook

    if _signals_installed \
            or threading.current_thread() is not threading.main_thread():
        return                   # signal.signal only works on main
    _signals_installed = True
    for signame in _CRASH_SIGNALS:
        signum = getattr(signal, signame, None)
        if signum is None:
            continue
        try:
            if signal.getsignal(signum) is signal.SIG_DFL:
                signal.signal(signum, _signal_handler)
        except (ValueError, OSError):   # exotic embedding; skip
            pass


def _signal_handler(signum, frame):
    record("signal", signal.Signals(signum).name)
    _safe_dump("signal:%s" % signal.Signals(signum).name)
    # restore the default disposition and re-raise so the exit status
    # still says "killed by signal N" (process managers key off it)
    signal.signal(signum, signal.SIG_DFL)
    os.kill(os.getpid(), signum)


# --------------------------------------------------------------------------
# hang watchdog
# --------------------------------------------------------------------------

_watchdog = None


def start_hang_watchdog(secs=None):
    """Daemon thread that dumps the flight file when step-span exits stop
    for *secs* (default ``MXNET_HANG_DUMP_SECS``; unset/0 = no-op).

    Fires once per stall: after a dump it re-arms only when a new step
    lands, so a long shutdown tail doesn't spray dumps.  Hung steps with
    telemetry off are still seen — the step-progress clock ticks on the
    span off path too.
    """
    global _watchdog
    if secs is None:
        secs = _env_hang_secs()
    if secs <= 0 or not _CAPACITY or _watchdog is not None:
        return None
    stop = threading.Event()

    def _watch():
        fired_at = -1                       # step count at last dump
        poll = min(1.0, secs / 4.0)
        while not stop.wait(poll):
            age = last_step_age()
            if age is None or age < secs:
                continue
            if _steps[0] == fired_at:       # still the same stall
                continue
            fired_at = _steps[0]
            record("hang", "no step-span exit",
                   stalled_s=round(age, 3))
            _safe_dump("hang:%.0fs" % age)

    thread = threading.Thread(target=_watch, name="mxnet-flight-watchdog",
                              daemon=True)
    thread.start()
    _watchdog = (thread, stop)
    return thread


def stop_hang_watchdog():
    global _watchdog
    if _watchdog is not None:
        _watchdog[1].set()
        _watchdog = None
