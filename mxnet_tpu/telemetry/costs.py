"""XLA cost accounting: per-program FLOPs/bytes → MFU and roofline.

"Fast" is meaningless without a denominator.  XLA's compiler already
computes an analytical cost model for every program it emits — the same
style of model TVM (arxiv 1802.04799) and the Julia-to-TPU pipeline
(arxiv 1810.09868) build their schedulers on — and hands it to us for
free via ``compiled.cost_analysis()``.  This module turns that into the
three judgement numbers every perf PR gets measured against:

* ``step_model_flops``  — FLOPs the step's compiled programs executed
* ``step_mfu``          — model FLOP utilization: flops / (dur × peak)
* ``step_hbm_bw_util``  — bytes-accessed / (dur × peak HBM bandwidth)

Capture happens once per compile event (``core._WatchedJit`` calls
:func:`capture`): the freshly compiled program is re-lowered from
``ShapeDtypeStruct`` specs — metadata only, safe even when the call
donated and deleted its input buffers — and its cost analysis cached per
watched-jit name.  Every subsequent watched call inside an open step
span adds its cached cost to the step window; ``core`` closes the window
at step-span exit by calling :func:`finalize_step`.

Peaks come from a per-device-kind table (per JAX device, i.e. per TPU
core on v2/v3 and per chip from v4 on), multiplied by the local device
count — MFU of an 8-chip step is measured against 8 chips.  Override
with ``MXNET_PEAK_FLOPS`` / ``MXNET_PEAK_HBM_BW`` (aggregate values,
used verbatim), which is also how CPU runs get an honest denominator:
the CPU table entry is a placeholder, not a measurement.

Known approximations, accepted on purpose:

* cost is cached per watched-jit *name*; a name whose cache holds many
  shape variants reports its most recently compiled variant.
* ``cost_analysis`` counts model FLOPs (what the HLO asks for), not
  hardware FLOPs — that is exactly what MFU wants (padding and
  recomputation are waste, not work).
"""
from __future__ import annotations

import os

from . import core

__all__ = ["capture", "analyze_compiled", "finalize_step", "peaks",
           "peaks_if_resolved", "refresh_from_env", "machine_balance",
           "PEAK_TABLE", "ICI_TABLE"]

_TRUTHY = ("1", "true", "on", "yes")

# (peak FLOP/s, peak HBM bytes/s) per JAX device, keyed on device_kind.
# bf16/dense numbers from the published per-chip specs, halved for the
# two-core-per-chip generations where jax exposes cores as devices.
PEAK_TABLE = {
    "TPU v2":      (22.5e12, 350e9),
    "TPU v3":      (61.5e12, 450e9),
    "TPU v4":      (275e12, 1228e9),
    "TPU v4 lite": (137.5e12, 614e9),
    "TPU v5":      (459e12, 2765e9),
    "TPU v5p":     (459e12, 2765e9),
    "TPU v5 lite": (197e12, 819e9),
    "TPU v5e":     (197e12, 819e9),
    "TPU v6 lite": (918e12, 1640e9),
    "TPU v6e":     (918e12, 1640e9),
    # CPU: order-of-magnitude placeholder (a modern server socket's f32
    # peak); pin MXNET_PEAK_FLOPS for a real CPU MFU
    "cpu":         (1e11, 50e9),
}
_FALLBACK = PEAK_TABLE["cpu"]

# peak interconnect bytes/s per JAX device (aggregate over a chip's ICI
# links) — the denominator for the "comm" leg of the opprof roofline.
# Same caveat as PEAK_TABLE: spec-sheet order-of-magnitude numbers, not
# measurements; pin MXNET_PEAK_ICI_BW (aggregate, verbatim) for honesty.
ICI_TABLE = {
    "TPU v2":      (62e9,),
    "TPU v3":      (82e9,),
    "TPU v4":      (300e9,),
    "TPU v4 lite": (150e9,),
    "TPU v5":      (600e9,),
    "TPU v5p":     (600e9,),
    "TPU v5 lite": (200e9,),
    "TPU v5e":     (200e9,),
    "TPU v6 lite": (400e9,),
    "TPU v6e":     (400e9,),
    # CPU: virtual devices share one memory system; collectives are
    # memcpys, so the "interconnect" placeholder sits below HBM peak
    "cpu":         (10e9,),
}
_ICI_FALLBACK = ICI_TABLE["cpu"]


def _env_float(name):
    raw = os.environ.get(name, "").strip()
    if not raw:
        return None
    try:
        value = float(raw)
    except ValueError:
        return None
    return value if value > 0 else None


def _env_capture_enabled():
    return os.environ.get("MXNET_COST_ANALYSIS", "1").strip().lower() \
        not in ("0", "false", "off", "no")


# cached at import (JG006 cached-value pattern: finalize_step is on the
# step path); core.refresh_from_env() funnels into refresh_from_env()
_ENV_PEAK_FLOPS = _env_float("MXNET_PEAK_FLOPS")
_ENV_PEAK_BW = _env_float("MXNET_PEAK_HBM_BW")
_ENV_PEAK_ICI = _env_float("MXNET_PEAK_ICI_BW")
_CAPTURE = _env_capture_enabled()
_peaks = None                   # resolved {"flops","hbm_bw",...} or None


def refresh_from_env():
    """Re-read MXNET_PEAK_FLOPS / MXNET_PEAK_HBM_BW / MXNET_PEAK_ICI_BW
    / MXNET_COST_ANALYSIS and drop the resolved-peak cache."""
    global _ENV_PEAK_FLOPS, _ENV_PEAK_BW, _ENV_PEAK_ICI, _CAPTURE, _peaks
    _ENV_PEAK_FLOPS = _env_float("MXNET_PEAK_FLOPS")
    _ENV_PEAK_BW = _env_float("MXNET_PEAK_HBM_BW")
    _ENV_PEAK_ICI = _env_float("MXNET_PEAK_ICI_BW")
    _CAPTURE = _env_capture_enabled()
    _peaks = None


# --------------------------------------------------------------------------
# per-program capture
# --------------------------------------------------------------------------

def _spec(leaf):
    """Shape/dtype skeleton of one pytree leaf.  Works on donated (and
    already deleted) jax arrays: aval metadata survives buffer death."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return leaf              # python scalar etc: trace as-is
    import jax
    return jax.ShapeDtypeStruct(shape, dtype)


def _normalize(analysis):
    """cost_analysis() shape varies by jax version: dict, or a
    one-per-partition list of dicts."""
    if isinstance(analysis, (list, tuple)):
        analysis = analysis[0] if analysis else {}
    if not isinstance(analysis, dict):
        return None
    flops = float(analysis.get("flops", 0.0) or 0.0)
    nbytes = float(analysis.get("bytes accessed", 0.0) or 0.0)
    if flops <= 0 and nbytes <= 0:
        return None
    return (flops, nbytes)


def capture(fn, args, kwargs, force=False):
    """(flops, bytes_accessed) of *fn* compiled for *args*/*kwargs*, or
    None.  Called by the watchdog ON COMPILE EVENTS ONLY — the re-lower
    here re-traces the function once, which is noise next to the XLA
    compile that just happened, and buys shape-safe AOT introspection.
    *force* bypasses the ``MXNET_COST_ANALYSIS`` gate for explicit API
    calls (``Executor.cost_analysis``).
    """
    if not (_CAPTURE or force):
        return None
    import jax
    sargs, skwargs = jax.tree_util.tree_map(_spec, (tuple(args),
                                                    dict(kwargs)))
    compiled = fn.lower(*sargs, **skwargs).compile()
    return analyze_compiled(compiled)


def analyze_compiled(compiled):
    """(flops, bytes_accessed) of an ALREADY-compiled executable, or
    None — the AOT twin of :func:`capture` for callers that hold the
    executable themselves (the serving bucket table compiles its
    variants ahead of time and should not pay a second lower+compile
    just to read the cost model)."""
    try:
        return _normalize(compiled.cost_analysis())
    except Exception:
        return None


# --------------------------------------------------------------------------
# peaks + step finalization
# --------------------------------------------------------------------------

def peaks():
    """The aggregate (all local devices) peak FLOP/s and HBM bytes/s this
    process is measured against, resolved once and cached."""
    global _peaks
    if _peaks is not None:
        return _peaks
    kind, n_dev = "unknown", 1
    try:
        import jax
        devs = jax.local_devices()
        n_dev = max(1, len(devs))
        kind = getattr(devs[0], "device_kind", "unknown") or "unknown"
    except Exception:
        pass
    table_flops, table_bw = PEAK_TABLE.get(kind, _FALLBACK)
    (table_ici,) = ICI_TABLE.get(kind, _ICI_FALLBACK)
    flops = _ENV_PEAK_FLOPS if _ENV_PEAK_FLOPS is not None \
        else table_flops * n_dev
    bw = _ENV_PEAK_BW if _ENV_PEAK_BW is not None else table_bw * n_dev
    ici = _ENV_PEAK_ICI if _ENV_PEAK_ICI is not None else table_ici * n_dev
    _peaks = {"flops": flops, "hbm_bw": bw, "ici_bw": ici,
              "device_kind": kind, "n_devices": n_dev,
              "source": {"flops": "env" if _ENV_PEAK_FLOPS is not None
                         else "table",
                         "hbm_bw": "env" if _ENV_PEAK_BW is not None
                         else "table",
                         "ici_bw": "env" if _ENV_PEAK_ICI is not None
                         else "table"}}
    return _peaks


def machine_balance():
    """Peak FLOP/s over peak HBM bytes/s — the arithmetic-intensity
    knee of the roofline.  A unit whose FLOP/byte sits above this is
    compute-bound; below, HBM-bound."""
    pk = peaks()
    return pk["flops"] / pk["hbm_bw"] if pk["hbm_bw"] > 0 else 0.0


def peaks_if_resolved():
    """The cached peak dict without triggering device discovery (jax
    may not even be initialized when a snapshot is taken)."""
    return _peaks


def finalize_step(flops, nbytes, dur_us):
    """Close one step's cost window into the three gauges."""
    core.set_gauge("step_model_flops", flops)
    dur_s = dur_us / 1e6
    if dur_s <= 0:
        return
    pk = peaks()
    if flops > 0 and pk["flops"] > 0:
        core.set_gauge("step_mfu", flops / (dur_s * pk["flops"]))
    if nbytes > 0 and pk["hbm_bw"] > 0:
        core.set_gauge("step_hbm_bw_util", nbytes / (dur_s * pk["hbm_bw"]))
