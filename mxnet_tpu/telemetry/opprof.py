"""Hot-op observatory: per-op roofline attribution over the owned
program ledger, plus the count-keyed device-time budget gate.

ROADMAP item 2 (the Pallas kernel tier) is measurement-driven: pick the
2-3 kernels worth hand-writing from *measured* per-op cost, not vibes.
Whole-program `device_time_us` histograms (PR 14) cannot name which
fusion inside ``transformer_train_step`` deserves a kernel; this module
can.  It walks the **optimized HLO text** of every owned program —
AOT-compiled from the same ``tracecheck_programs()`` specimen ledger
the JX2xx trace tier and the JX204 memory gate already consume
(``tracecheck.compile_record``; zero new jitted entry points, the
graftcheck ledger is unchanged) — and for each top-level instruction or
fusion attributes:

* **flops** via a per-opcode cost-model table (dot = 2·out·contraction,
  reduce = input elements, transcendentals weighted, fusions recursed
  into their called computations);
* **bytes moved** as operand + result bytes at the call site (traffic
  internal to a fusion is exactly what fusion makes free);
* **op class** — dot / conv / elementwise / reduce / collective /
  fusion — and the roofline verdict against the ``costs.peaks()``
  tables: arithmetic intensity above the machine balance is
  compute-bound, below is HBM-bound, collectives are comm (ceilinged by
  the interconnect table, not HBM).

Attribution then fuses with the *measured* per-program device time (the
compiled specimen executed under the pinned topology, median of N reps)
to apportion each program's wall time across its units by
roofline-weighted share — ``est_us`` per unit, shares summing to 1 over
a program by construction.

Three consumers:

* ``tools/trace_report.py --ops`` renders the ranked hot-op table and
  the kernel-candidate list from the ``--json`` artifact this module's
  CLI writes;
* ``PERF_BASELINE.json`` — count-keyed per-program device-time budgets
  (digest-gated exactly like MEM_BASELINE) checked by
  :func:`check_perf` and gated by ``trace_report.py --gate-perf``
  (0 ok / 3 regressed / 4 unmeasurable / 2 usage, band via
  ``MXNET_PERF_TOLERANCE``);
* the introspection server's observe-only ``/profile`` endpoint
  (:func:`profile_view` via sys.modules delegation).

Known approximations, accepted on purpose and recorded here so the
numbers are honest: while-loop bodies are counted once (trip counts are
runtime values); convolution flops assume dense direct convolution;
the CPU "device time" is wall time of the compiled executable — on CPU
the roofline *shares* and the candidate *ranking* are the signal, the
absolute ceilings become real on TPU metal.

Import-light: jax loads inside functions only, and nothing here runs on
the step path — the sweep is an offline tool, like the lint driver.
"""
from __future__ import annotations

import json
import os
import re
import time

__all__ = ["parse_hlo", "analyze_hlo", "analyze_record", "classify",
           "sweep", "build_report", "kernel_candidates", "check_perf",
           "perf_tolerance", "load_perf_baseline", "save_perf_baseline",
           "default_perf_baseline_path", "profile_view", "main"]

# --------------------------------------------------------------------------
# optimized-HLO text parsing
# --------------------------------------------------------------------------

# computation headers sit at column 0:
#   %fused_computation.88 (param_0.185: f32[16], ...) -> f32[16,16] {
#   ENTRY %main.1285_spmd (...) -> (f32[...], ...) {
_COMP_RE = re.compile(r"^(ENTRY\s+)?%([\w.\-]+)\s*\(")
# instructions are indented:  [ROOT ]%name = TYPE opcode(OPERANDS), attrs
_INSTR_RE = re.compile(r"^\s+(ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*)$")
# the first lowercase-word-then-paren in the RHS is the opcode (type
# portions — f32[16]{1,0}, tuple types — never match first)
_OPCODE_RE = re.compile(r"([a-z][a-z0-9\-_.]*)\(")
_SHAPE_RE = re.compile(
    r"\b(pred|token|bf16|f8e\w+|c64|c128|[fsu]\d+)\[([0-9,]*)\]")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")
_CALLED_RE = re.compile(
    r"(?:calls|to_apply|body|condition|true_computation|"
    r"false_computation)=%([\w.\-]+)")
_BRANCHES_RE = re.compile(r"branches=\{([^}]*)\}")
_OP_NAME_RE = re.compile(r'op_name="([^"]*)"')
_CONTRACTING_RE = re.compile(r"lhs_contracting_dims=\{([0-9,]*)\}")

_DTYPE_BYTES = {"pred": 1, "token": 0, "bf16": 2, "c64": 8, "c128": 16}


def _dtype_bytes(dtype):
    if dtype in _DTYPE_BYTES:
        return _DTYPE_BYTES[dtype]
    if dtype.startswith("f8"):
        return 1
    m = re.match(r"[fsu](\d+)", dtype)
    return max(1, int(m.group(1)) // 8) if m else 4


def _shapes_in(text):
    """[(elems, bytes)] for every shape literal in *text*."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(text):
        elems = 1
        for d in dims.split(","):
            if d:
                elems *= int(d)
        out.append((elems, elems * _dtype_bytes(dtype)))
    return out


def parse_hlo(text):
    """Optimized HLO module text -> ``(computations, entry_name)``.

    ``computations`` maps computation name to an ordered instruction
    list; each instruction is a dict with ``name/opcode/out_elems/
    out_bytes/operands/attrs/called/op_name`` — enough for the cost
    model, deliberately no full graph semantics."""
    comps, entry_name, cur = {}, None, None
    for line in text.splitlines():
        if not line:
            continue
        if line[0] not in " \t":
            m = _COMP_RE.match(line)
            if m and line.rstrip().endswith("{"):
                cur = []
                comps[m.group(2)] = cur
                if m.group(1):
                    entry_name = m.group(2)
            elif line.startswith("}"):
                cur = None
            continue
        if cur is None:
            continue
        m = _INSTR_RE.match(line)
        if not m:
            continue
        name, rhs = m.group(2), m.group(3)
        om = _OPCODE_RE.search(rhs)
        if om is None:
            continue
        opcode = om.group(1)
        # scan the operand section with paren depth (tuple-typed
        # operands like get-tuple-element((s32[], f32[2,8]) %p), carry
        # internal parens)
        depth, i = 1, om.end()
        while i < len(rhs) and depth > 0:
            if rhs[i] == "(":
                depth += 1
            elif rhs[i] == ")":
                depth -= 1
            i += 1
        operand_str = rhs[om.end():i - 1]
        attrs = rhs[i:]
        out_shapes = _shapes_in(rhs[:om.start()])
        out_elems = sum(e for e, _b in out_shapes)
        out_bytes = sum(b for _e, b in out_shapes)
        called = _CALLED_RE.findall(attrs)
        bm = _BRANCHES_RE.search(attrs)
        if bm:
            called.extend(_OPERAND_NAME_RE.findall(bm.group(1)))
        op_name_m = _OP_NAME_RE.search(attrs)
        # dims of the (first) result shape — the dot cost model indexes
        # the lhs def-site's dimension sizes by lhs_contracting_dims
        dm = _SHAPE_RE.search(rhs[:om.start()])
        dims = [int(d) for d in dm.group(2).split(",") if d] \
            if dm else None
        cur.append({
            "name": name, "opcode": opcode,
            "out_elems": out_elems, "out_bytes": out_bytes, "dims": dims,
            "operands": _OPERAND_NAME_RE.findall(operand_str),
            "operand_text": operand_str, "attrs": attrs,
            "called": called,
            "op_name": op_name_m.group(1) if op_name_m else None,
        })
    return comps, entry_name


# --------------------------------------------------------------------------
# per-opcode cost model
# --------------------------------------------------------------------------

# structural plumbing: free at the unit level (no math, and their bytes
# show up as operands of whoever consumes them)
_SKIP_OPS = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "partition-id", "replica-id", "opt-barrier",
    "add-dependency", "domain",
})
_COLLECTIVE_OPS = frozenset({
    "all-reduce", "all-gather", "all-to-all", "reduce-scatter",
    "collective-permute", "collective-broadcast", "all-reduce-start",
    "all-reduce-done", "all-gather-start", "all-gather-done",
    "collective-permute-start", "collective-permute-done",
    "send", "send-done", "recv", "recv-done",
})
_COMPOUND_OPS = frozenset({"fusion", "call", "while", "conditional"})
# ~8 flops per element for the polynomial/Newton expansions
_TRANSCENDENTAL_OPS = frozenset({
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "logistic", "tanh", "sqrt", "rsqrt", "cbrt", "power", "sine",
    "cosine", "tan", "erf", "erf-inv", "atan2",
})
_TRANSCENDENTAL_WEIGHT = 8
# per-element-of-input reductions (regions counted via element count,
# never recursed: the region is the per-element combiner)
_REDUCE_OPS = frozenset({
    "reduce", "reduce-window", "select-and-scatter", "scatter", "sort",
    "map",
})
# pure data movement: zero flops, bytes are the whole story
_DATA_OPS = frozenset({
    "broadcast", "reshape", "transpose", "slice", "concatenate", "pad",
    "reverse", "dynamic-slice", "dynamic-update-slice", "gather",
    "copy", "copy-start", "copy-done", "iota", "convert",
    "rng-bit-generator", "rng-get-and-update-state",
})


def classify(opcode):
    """The six-way op class of the ranked table."""
    if opcode == "dot":
        return "dot"
    if opcode == "convolution":
        return "conv"
    if opcode in _COMPOUND_OPS:
        return "fusion"
    if opcode in _COLLECTIVE_OPS:
        return "collective"
    if opcode in _REDUCE_OPS:
        return "reduce"
    if opcode in _SKIP_OPS:
        return "other"
    if opcode in _DATA_OPS or opcode in _TRANSCENDENTAL_OPS:
        return "elementwise"
    return "elementwise"


def _operand_sizes(ins, by_name):
    """Total (elems, bytes) across *ins*'s operands, resolved through
    the def-site instruction (operands are bare %names in optimized
    HLO; their shapes live on the defining instruction)."""
    elems = nbytes = 0
    seen_inline = _shapes_in(ins["operand_text"])
    if seen_inline and not ins["operands"]:
        return (sum(e for e, _ in seen_inline),
                sum(b for _, b in seen_inline))
    for op in ins["operands"]:
        d = by_name.get(op)
        if d is not None:
            elems += d["out_elems"]
            nbytes += d["out_bytes"]
    return elems, nbytes


def _instr_flops(ins, comps, by_name, memo):
    op = ins["opcode"]
    if op in _SKIP_OPS or op in _DATA_OPS:
        return 0
    if op == "dot":
        cm = _CONTRACTING_RE.search(ins["attrs"])
        contracting = 1
        if cm and ins["operands"]:
            lhs = by_name.get(ins["operands"][0])
            lhs_dims = lhs["dims"] if lhs else None
            if lhs_dims:
                for d in cm.group(1).split(","):
                    if d and int(d) < len(lhs_dims):
                        contracting *= lhs_dims[int(d)]
        return 2 * ins["out_elems"] * max(1, contracting)
    if op == "convolution":
        # dense direct conv: 2 * out * (kernel elems / out channels);
        # rhs (the kernel) is operand 1
        kernel = by_name.get(ins["operands"][1]) \
            if len(ins["operands"]) > 1 else None
        k_elems = kernel["out_elems"] if kernel else 1
        out_ch = (ins.get("dims") or [1])[-1] or 1
        return 2 * ins["out_elems"] * max(1, k_elems // max(1, out_ch))
    if op in _REDUCE_OPS:
        elems, _b = _operand_sizes(ins, by_name)
        return max(elems, ins["out_elems"])
    if op in _COLLECTIVE_OPS:
        # all-reduce does one add per element; pure-movement collectives
        # do none
        return ins["out_elems"] if op.startswith("all-reduce") \
            or op == "reduce-scatter" else 0
    if op in _COMPOUND_OPS:
        total = 0
        for cname in ins["called"]:
            total += _comp_flops(cname, comps, memo)
        return total
    weight = _TRANSCENDENTAL_WEIGHT if op in _TRANSCENDENTAL_OPS else 1
    return weight * ins["out_elems"]


def _comp_flops(cname, comps, memo):
    if cname in memo:
        return memo[cname]
    memo[cname] = 0              # cycle guard; HLO comps are acyclic
    instrs = comps.get(cname, [])
    by_name = {i["name"]: i for i in instrs}
    total = 0
    for ins in instrs:
        total += _instr_flops(ins, comps, by_name, memo)
    memo[cname] = total
    return total


def analyze_hlo(text, peaks):
    """Parse + cost one program's optimized HLO.  Returns
    ``{"units": [...], "flops": F, "bytes": B}`` where units are the
    entry computation's non-structural instructions, each carrying
    flops/bytes/op_class/intensity/bound/ceiling/est_us/share (shares
    sum to 1 over the program by construction)."""
    comps, entry = parse_hlo(text)
    if entry is None or entry not in comps:
        return {"units": [], "flops": 0, "bytes": 0}
    memo = {}
    instrs = comps[entry]
    by_name = {i["name"]: i for i in instrs}
    balance = peaks["flops"] / peaks["hbm_bw"] if peaks["hbm_bw"] else 0
    units = []
    for ins in instrs:
        if ins["opcode"] in _SKIP_OPS:
            continue
        flops = _instr_flops(ins, comps, by_name, memo)
        _oe, obytes = _operand_sizes(ins, by_name)
        nbytes = obytes + ins["out_bytes"]
        op_class = classify(ins["opcode"])
        intensity = (flops / nbytes) if nbytes > 0 else 0.0
        if op_class == "collective":
            bound = "comm"
            ceiling = peaks.get("ici_bw", peaks["hbm_bw"])
            est_s = nbytes / ceiling if ceiling > 0 else 0.0
            ceiling_kind = "bytes_per_s"
        else:
            bound = "compute" if intensity >= balance else "hbm"
            ceiling = min(peaks["flops"], intensity * peaks["hbm_bw"]) \
                if intensity > 0 else 0.0
            est_s = max(flops / peaks["flops"] if peaks["flops"] else 0,
                        nbytes / peaks["hbm_bw"] if peaks["hbm_bw"]
                        else 0)
            ceiling_kind = "flops_per_s"
        units.append({
            "unit": "%" + ins["name"], "opcode": ins["opcode"],
            "op_class": op_class, "op_name": ins["op_name"],
            "flops": int(flops), "bytes": int(nbytes),
            "intensity": round(intensity, 4), "bound": bound,
            "ceiling": ceiling, "ceiling_kind": ceiling_kind,
            "est_us": est_s * 1e6,
        })
    total_est = sum(u["est_us"] for u in units)
    for u in units:
        u["share"] = (u["est_us"] / total_est) if total_est > 0 else 0.0
    return {"units": units,
            "flops": sum(u["flops"] for u in units),
            "bytes": sum(u["bytes"] for u in units)}


def analyze_record(rec, peaks):
    """analyze_hlo over a ProgramRecord's compiled HLO, or None when
    the record cannot be compiled (recorded upstream as a problem, not
    silently skipped)."""
    from ..lint import tracecheck
    compiled = tracecheck.compile_record(rec)
    if compiled is None:
        return None, None
    try:
        text = compiled.as_text()
    except Exception:
        return None, compiled
    return analyze_hlo(text, peaks), compiled


# --------------------------------------------------------------------------
# measured device time (the compiled specimen, executed)
# --------------------------------------------------------------------------

def _materialize(leaf):
    """Concrete arg for an AOT-compiled call.  Providers hand a mix of
    ``jax.ShapeDtypeStruct`` skeletons (kvstore) and live arrays
    already committed to provider-side shardings (transformer) — the
    executable here was compiled from specs, so committed arrays fail
    its input-sharding check.  Uncommitted numpy zeros of the declared
    shape/dtype satisfy every case: the compiled call places them
    according to its own input shardings."""
    import numpy as np
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return leaf
    return np.zeros(tuple(int(d) for d in shape), dtype)


def measure_device_time(compiled, args, kwargs, reps=5, warmup=1):
    """Median wall-µs of the compiled executable over *reps* calls
    (after *warmup*), or None when execution fails.  On CPU this is
    wall time; the relative per-program ordering is the budget, the
    tolerance band absorbs host noise."""
    import jax
    try:
        cargs, ckwargs = jax.tree_util.tree_map(
            _materialize, (tuple(args), dict(kwargs or {})))
    except Exception:
        return None
    times = []
    try:
        for i in range(warmup + reps):
            t0 = time.perf_counter()
            out = compiled(*cargs, **ckwargs)
            jax.block_until_ready(out)
            dt = (time.perf_counter() - t0) * 1e6
            if i >= warmup:
                times.append(dt)
    except Exception:
        return None
    times.sort()
    return times[len(times) // 2]


# --------------------------------------------------------------------------
# the sweep: every owned specimen, attributed and timed
# --------------------------------------------------------------------------

def sweep(entries=None, reps=5, progress=None):
    """Trace, compile, attribute, and time every owned specimen.
    Returns ``(programs, problems)``:

    * programs: ``{name: {origin, specimens, digest, median_us,
      measured, flops, bytes, units}}`` — count-keyed per program NAME
      like measure_programs (k specimens sum their medians and unit
      lists; dropping a specimen is as visible as growing one);
    * problems: provider/trace/compile failures as strings — a specimen
      the sweep cannot see must be reported, never silently skipped.
    """
    import hashlib
    import importlib
    from ..lint import tracecheck
    from . import costs
    pk = costs.peaks()
    programs, problems = {}, []
    for group, modpath in tracecheck.ENTRY_POINTS:
        if entries is not None and group not in entries:
            continue
        origin = modpath.replace(".", "/") + ".py"
        try:
            mod = importlib.import_module(modpath)
            specs = list(mod.tracecheck_programs())
        except Exception as exc:
            problems.append("provider %s failed: %r" % (modpath, exc))
            continue
        for spec in specs:
            name, fn, args, kwargs = spec[:4]
            meta = spec[4] if len(spec) > 4 else None
            if progress:
                progress(name)
            try:
                rec = tracecheck.trace_program(
                    name, fn, args, kwargs, origin=origin, meta=meta)
            except Exception as exc:
                problems.append("tracing %s (%s) failed: %r"
                                % (name, origin, exc))
                continue
            entry = programs.setdefault(name, {
                "origin": origin, "specimens": 0, "digests": [],
                "median_us": 0.0, "measured": True,
                "flops": 0, "bytes": 0, "units": []})
            entry["specimens"] += 1
            entry["digests"].append(tracecheck.record_digest(rec))
            analysis, compiled = analyze_record(rec, pk)
            if compiled is None:
                problems.append("compiling %s failed" % name)
                entry["measured"] = False
                continue
            if analysis is not None:
                tag = "s%d:" % (entry["specimens"] - 1) \
                    if entry["specimens"] > 1 else ""
                for u in analysis["units"]:
                    u = dict(u, unit=tag + u["unit"])
                    entry["units"].append(u)
                entry["flops"] += analysis["flops"]
                entry["bytes"] += analysis["bytes"]
            med = measure_device_time(compiled, args, kwargs, reps=reps)
            if med is None:
                problems.append("executing %s failed" % name)
                entry["measured"] = False
            else:
                entry["median_us"] += med
    for entry in programs.values():
        digest = hashlib.sha1(
            ",".join(sorted(entry.pop("digests"))).encode()).hexdigest()
        entry["digest"] = digest[:12]
        # renormalize unit shares over the merged specimen set and
        # apportion the measured program time by roofline share
        total_est = sum(u["est_us"] for u in entry["units"])
        for u in entry["units"]:
            u["share"] = (u["est_us"] / total_est) if total_est else 0.0
            u["attributed_us"] = u["share"] * entry["median_us"]
        entry["units"].sort(key=lambda u: u["share"], reverse=True)
    return programs, problems


# --------------------------------------------------------------------------
# kernel candidates: the handoff ROADMAP item 2 consumes
# --------------------------------------------------------------------------

# Pallas-candidate score = global time share × class weight.  Compute
# classes where a hand kernel can beat XLA rank high; raw elementwise
# is usually fused already; "other" is plumbing.
_CLASS_WEIGHT = {"dot": 1.0, "conv": 1.0, "fusion": 0.9, "reduce": 0.8,
                 "collective": 0.8, "elementwise": 0.5, "other": 0.2}
_COMPUTE_CLASSES = ("dot", "conv", "fusion", "reduce")


def kernel_candidates(programs, n_compute=3, n_comm=2):
    """Rank Pallas candidates two ways: the top compute units by
    score = global_share × class weight, and the top collective cores
    ranked within the comm class (their µs are tiny next to the
    matmuls, but they own the interconnect ceiling — a fused
    chunk-sum kernel is a latency win the global ranking would hide)."""
    total_us = sum(p["median_us"] for p in programs.values()) or 1.0
    pool = []
    for name, p in programs.items():
        for u in p["units"]:
            gshare = u.get("attributed_us", 0.0) / total_us
            pool.append(dict(
                kind=None, program=name, unit=u["unit"],
                opcode=u["opcode"], op_class=u["op_class"],
                op_name=u["op_name"], bound=u["bound"],
                intensity=u["intensity"], ceiling=u["ceiling"],
                ceiling_kind=u["ceiling_kind"],
                attributed_us=round(u.get("attributed_us", 0.0), 2),
                global_share=round(gshare, 6),
                score=round(gshare * _CLASS_WEIGHT.get(
                    u["op_class"], 0.2), 6)))
    compute = sorted(
        (c for c in pool if c["op_class"] in _COMPUTE_CLASSES),
        key=lambda c: c["score"], reverse=True)[:n_compute]
    comm = sorted(
        (c for c in pool if c["op_class"] == "collective"),
        key=lambda c: (c["attributed_us"], c["score"]),
        reverse=True)[:n_comm]
    for c in compute:
        c["kind"] = "compute"
    for c in comm:
        c["kind"] = "comm"
    return compute + comm


# --------------------------------------------------------------------------
# PERF_BASELINE: count-keyed device-time budgets, digest-gated
# --------------------------------------------------------------------------

def default_perf_baseline_path():
    from ..lint.core import repo_root
    return os.path.join(repo_root(), "PERF_BASELINE.json")


def perf_tolerance(default=1.5):
    """The MXNET_PERF_TOLERANCE fractional band (1.5 = +150% headroom —
    CPU wall time is noisy; a real regression is a multiple, not a
    percent).  Parsed per call — this only runs in the offline sweep
    and the gate, never on the step path."""
    raw = os.environ.get("MXNET_PERF_TOLERANCE", "")  # graftlint: disable=JG006
    try:
        val = float(raw) if raw else default
    except ValueError:
        return default
    return val if val >= 0 else default


# absolute jitter floor: sub-500µs swings on micro-programs are host
# scheduling noise, not regressions — the band is fractional, this is µs
_PERF_SLACK_US = 500.0


def load_perf_baseline(path=None):
    """PERF_BASELINE.json -> dict, or None when absent/unreadable."""
    path = path or default_perf_baseline_path()
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(payload.get("programs"), dict):
        return None
    return payload


def save_perf_baseline(programs, path=None, n_devices=None, reps=5):
    """Write sweep results as the committed device-time budget."""
    path = path or default_perf_baseline_path()
    if n_devices is None:
        import jax
        n_devices = len(jax.devices())
    payload = {
        "version": 1, "n_devices": int(n_devices),
        "tolerance": perf_tolerance(), "reps": int(reps),
        "programs": {
            name: {"specimens": p["specimens"], "digest": p["digest"],
                   "median_us": round(p["median_us"], 1)}
            for name, p in sorted(programs.items()) if p["measured"]}}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    return payload


def check_perf(programs, baseline=None, tolerance=None, full=True,
               n_devices=None):
    """Measured sweep *programs* vs a loaded PERF_BASELINE payload.
    Mirrors tracecheck.check_memory: count-keyed, digest-gated (a
    budget whose trace signature or specimen count no longer matches
    the program is not a budget — ``unbudgeted``, loud), topology-honest
    (device-time is a function of the pinned mesh; mismatch means the
    gate CANNOT compare and must say so, rc 4 downstream)."""
    tol = perf_tolerance() if tolerance is None else tolerance
    if n_devices is None:
        import jax
        n_devices = len(jax.devices())
    base_progs = (baseline or {}).get("programs", {})
    base_dev = (baseline or {}).get("n_devices")
    topology_match = baseline is not None \
        and int(base_dev or 0) == int(n_devices)
    report_programs = []
    for name in sorted(programs):
        p = programs[name]
        entry = {"name": name, "origin": p["origin"],
                 "specimens": p["specimens"], "digest": p["digest"],
                 "median_us": round(p["median_us"], 1),
                 "budget_us": None, "over_budget": False,
                 "unbudgeted": False}
        budget = base_progs.get(name) if topology_match else None
        if not p["measured"]:
            entry["unbudgeted"] = True
        elif budget is None:
            entry["unbudgeted"] = True
        else:
            stale = (int(budget.get("specimens", 1)) != p["specimens"]
                     or budget.get("digest") != p["digest"])
            if stale:
                entry["unbudgeted"] = True
            b_us = float(budget.get("median_us", 0.0))
            entry["budget_us"] = b_us
            limit = b_us + max(b_us * tol, _PERF_SLACK_US)
            if not stale and p["median_us"] > limit:
                entry["over_budget"] = True
        report_programs.append(entry)
    stale_budgets = []
    if topology_match and full:
        stale_budgets = sorted(set(base_progs) - set(programs))
    return {"schema": "opprof-v1", "n_devices": int(n_devices),
            "tolerance": tol, "slack_us": _PERF_SLACK_US,
            "baseline_n_devices": base_dev,
            "baseline_present": baseline is not None,
            "topology_match": bool(topology_match),
            "stale_budgets": stale_budgets,
            "programs": report_programs}


# --------------------------------------------------------------------------
# the artifact + /profile view
# --------------------------------------------------------------------------

_UNITS_KEPT = 12          # per program in the artifact; counts recorded

_LAST_REPORT = None       # most recent build_report in this process


def build_report(programs, problems, perf, peaks, reps=5):
    """The ``--json`` artifact trace_report consumes.  Unit lists are
    capped at the top _UNITS_KEPT per program BY SHARE with the dropped
    tail recorded (units_omitted / share_omitted) — a silent cap would
    read as full coverage."""
    global _LAST_REPORT
    total_us = sum(p["median_us"] for p in programs.values())
    out_programs = {}
    for name, p in sorted(programs.items()):
        kept = p["units"][:_UNITS_KEPT]
        omitted = p["units"][_UNITS_KEPT:]
        out_programs[name] = {
            "origin": p["origin"], "specimens": p["specimens"],
            "digest": p["digest"], "measured": p["measured"],
            "median_us": round(p["median_us"], 1),
            "flops": p["flops"], "bytes": p["bytes"],
            "units": [
                {k: (round(v, 6 if k in ("share", "intensity") else 2)
                     if isinstance(v, float) else v)
                 for k, v in u.items()} for u in kept],
            "units_total": len(p["units"]),
            "units_omitted": len(omitted),
            "share_omitted": round(sum(u["share"] for u in omitted), 4),
        }
    report = {
        "schema": "opprof-ops-v1",
        "n_devices": peaks.get("n_devices"),
        "device_kind": peaks.get("device_kind"),
        "peaks": {"flops": peaks["flops"], "hbm_bw": peaks["hbm_bw"],
                  "ici_bw": peaks.get("ici_bw")},
        "machine_balance": round(
            peaks["flops"] / peaks["hbm_bw"], 4) if peaks["hbm_bw"]
        else 0.0,
        "reps": reps,
        "total_measured_us": round(total_us, 1),
        "problems": problems,
        "programs": out_programs,
        "candidates": kernel_candidates(programs),
        "perf": perf,
    }
    _LAST_REPORT = report
    return report


def profile_view(top=8):
    """The observe-only ``/profile`` summary: committed budgets + the
    in-process report when a sweep ran here, trimmed for a browser.
    Stdlib-only and never triggers a sweep — the endpoint observes."""
    baseline = load_perf_baseline()
    view = {"available": _LAST_REPORT is not None,
            "baseline": None, "candidates": None, "top_programs": None}
    if baseline is not None:
        progs = baseline.get("programs", {})
        ranked = sorted(progs.items(),
                        key=lambda kv: kv[1].get("median_us", 0),
                        reverse=True)
        view["baseline"] = {
            "n_devices": baseline.get("n_devices"),
            "programs": len(progs),
            "top_budgets_us": [
                {"name": k, "median_us": v.get("median_us")}
                for k, v in ranked[:top]]}
    if _LAST_REPORT is not None:
        view["candidates"] = _LAST_REPORT.get("candidates")
        ranked = sorted(
            _LAST_REPORT.get("programs", {}).items(),
            key=lambda kv: kv[1].get("median_us", 0), reverse=True)
        view["top_programs"] = [
            {"name": k, "median_us": v.get("median_us"),
             "top_unit": (v.get("units") or [{}])[0].get("op_name")
             or (v.get("units") or [{}])[0].get("unit")}
            for k, v in ranked[:top]]
    return view


# --------------------------------------------------------------------------
# CLI
# --------------------------------------------------------------------------

def _programs_from_artifact(artifact):
    """Reconstruct the check_perf input from a prior --json artifact
    (the --from path: re-gate doctored budgets without recompiling)."""
    out = {}
    for name, p in artifact.get("programs", {}).items():
        out[name] = {"origin": p["origin"], "specimens": p["specimens"],
                     "digest": p["digest"], "measured": p["measured"],
                     "median_us": float(p["median_us"]),
                     "flops": p.get("flops", 0),
                     "bytes": p.get("bytes", 0),
                     "units": p.get("units", [])}
    return out


def main(argv=None):
    import argparse
    ap = argparse.ArgumentParser(
        prog="python -m mxnet_tpu.telemetry.opprof",
        description="per-op roofline attribution + device-time budgets "
                    "over the owned program ledger (run under the "
                    "pinned topology: JAX_PLATFORMS=cpu XLA_FLAGS="
                    "--xla_force_host_platform_device_count=8)")
    ap.add_argument("--json", metavar="PATH",
                    help="write the ops artifact (trace_report --ops)")
    ap.add_argument("--perf-baseline", metavar="PATH",
                    help="PERF_BASELINE.json to check against "
                         "(default: the committed one)")
    ap.add_argument("--write-perf-baseline", action="store_true",
                    help="save measured medians as the budget, then "
                         "self-check against it")
    ap.add_argument("--from", dest="from_json", metavar="OPSJSON",
                    help="reuse a prior artifact's measurements instead "
                         "of sweeping (re-gate without recompiling)")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--top", type=int, default=10,
                    help="units shown in the stdout summary")
    args = ap.parse_args(argv)

    baseline_path = args.perf_baseline or default_perf_baseline_path()

    if args.from_json:
        try:
            with open(args.from_json, encoding="utf-8") as f:
                artifact = json.load(f)
        except (OSError, ValueError) as exc:
            ap.error("unreadable --from artifact: %s" % exc)
        programs = _programs_from_artifact(artifact)
        problems = artifact.get("problems", [])
        peaks = dict(artifact.get("peaks", {}),
                     n_devices=artifact.get("n_devices"),
                     device_kind=artifact.get("device_kind"))
        perf = check_perf(programs, load_perf_baseline(baseline_path),
                          n_devices=artifact.get("n_devices"))
        report = build_report(programs, problems, perf, peaks,
                              reps=artifact.get("reps", args.reps))
    else:
        from . import costs
        peaks = costs.peaks()
        programs, problems = sweep(reps=args.reps)
        if args.write_perf_baseline:
            save_perf_baseline(programs, baseline_path, reps=args.reps)
            print("wrote %s (%d programs)"
                  % (baseline_path,
                     sum(1 for p in programs.values() if p["measured"])))
        perf = check_perf(programs, load_perf_baseline(baseline_path))
        report = build_report(programs, problems, perf, peaks,
                              reps=args.reps)

    if args.json:
        with open(args.json, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1, sort_keys=True)
            f.write("\n")

    # stdout summary: programs by measured time, then the candidates
    progs = sorted(report["programs"].items(),
                   key=lambda kv: kv[1]["median_us"], reverse=True)
    print("opprof: %d programs, %.1f ms measured total, "
          "machine balance %.2f FLOP/B"
          % (len(progs), report["total_measured_us"] / 1e3,
             report["machine_balance"]))
    for name, p in progs[:args.top]:
        top_u = (p["units"] or [{}])[0]
        print("  %-34s %9.1f us  top: %s %s (%s, share %.2f)"
              % (name, p["median_us"], top_u.get("op_class", "-"),
                 top_u.get("unit", "-"), top_u.get("bound", "-"),
                 top_u.get("share", 0.0)))
    print("kernel candidates:")
    for c in report["candidates"]:
        print("  [%s] %s :: %s (%s, %s) share %.4f score %.4f"
              % (c["kind"], c["program"], c["unit"], c["op_class"],
                 c["bound"], c["global_share"], c["score"]))
    for prob in report["problems"]:
        print("problem: %s" % prob)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
