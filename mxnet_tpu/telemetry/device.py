"""Device-time attribution: where the step actually runs.

The spans in :mod:`.core` measure **host wall time** — when the step
thread entered and left a region.  On an async backend that is a lie of
omission: ``fused_optimizer_step`` returns the instant XLA *dispatches*
the program, and the device keeps executing long after the span closed.
Host spans therefore cannot answer the questions the perf arc is graded
by (ROADMAP items 2–3): how long did the program run **on device**, and
did the collective overlap the compute or serialize behind it?

This module answers both with zero extra XLA programs:

* **Sampled blocking** (``MXNET_DEVICE_TIME=1`` or a rate like ``0.25``):
  on sampled steps every watched-jit call ``block_until_ready``s its
  outputs, so the call's wall delta ≈ dispatch + device execution.  The
  sampled step pays full serialization (that is the probe's cost — why
  sampling exists); un-sampled steps run free and feed the *overlapped*
  wall-time baseline the overlap estimate needs.
* **Per-program device-time histograms**: every sampled call lands in
  the ``device_time_us`` histogram and a per-program table
  (:func:`device_report`), the device-truth twin of the host self-time
  sweep in ``tools/trace_report.py``.
* **Step-timeline decomposition**: a window opens when a ``step``-span
  opens and resolves at its exit into

      data-wait   io_batch_wait_us captured at window open (the input
                  pipeline's contribution, spent before the span)
      device      summed blocked time of compute programs
      collective  summed blocked time of collective programs (kvstore
                  reduce / reduce-scatter — :func:`register_collective`)
      host-gap    span wall minus device minus collective

  plus ``overlap_ratio`` — the fraction of collective time hidden under
  compute: ``(serialized_wall - free_wall) / collective`` clamped to
  [0, 1], where ``free_wall`` is the EWMA of un-sampled step walls.
  This is THE number ROADMAP item 2 (comm/compute overlap) must move;
  at sample rate 1.0 every step serializes, so no free baseline exists
  and the ratio reads 0 — use a rate < 1 to measure overlap.

Windows are thread-local: a training step on the main thread and a
serving batch on an engine thread never contaminate each other's
decomposition.  Stdlib-only at import; jax is touched only inside the
sampled block call.  Off path (``MXNET_DEVICE_TIME`` unset) is one
cached-bool check in ``_WatchedJit`` — nothing else runs.
"""
from __future__ import annotations

import os
import threading
from collections import deque

from . import core as _core

__all__ = ["enabled", "sample_period", "configure", "refresh_from_env",
           "register_collective", "is_collective", "maybe_time",
           "take_serving_sample", "record_program", "note_overlap",
           "open_step_window", "close_step_window", "device_report",
           "opprof_enabled", "timelines", "reset"]


def _parse_rate(raw):
    """MXNET_DEVICE_TIME: '0'/unset = off; '1' = every step; a rate in
    (0,1) samples every round(1/rate)-th step (deterministic)."""
    try:
        rate = float(raw)
    except (TypeError, ValueError):
        return 0
    if rate <= 0:
        return 0
    if rate >= 1:
        return 1
    return max(1, int(round(1.0 / rate)))


def _parse_opprof(raw):
    """MXNET_OPPROF (default on): feed sampled per-program device time
    into the timeseries rings at step-window close.  Piggybacks on the
    MXNET_DEVICE_TIME sampling gate, so with device-time off this costs
    nothing regardless of the setting."""
    return str(raw).strip().lower() not in ("0", "false", "off", "no")


_PERIOD = _parse_rate(os.environ.get("MXNET_DEVICE_TIME", "0"))
_OPPROF = _parse_opprof(os.environ.get("MXNET_OPPROF", "1"))
_EWMA_ALPHA = 0.3
_TIMELINE_CAP = 64


def enabled():
    return _PERIOD > 0


def sample_period():
    """Steps between samples (1 = every step; 0 = off)."""
    return _PERIOD


def _push_flag():
    """Mirror the cached gate into core so the watched-jit hot path pays
    one module-global read, not a cross-module call."""
    _core._set_device_time(_PERIOD > 0)


def configure(rate=None, opprof=None):
    """Programmatic override of MXNET_DEVICE_TIME / MXNET_OPPROF
    (tests / notebooks)."""
    global _PERIOD, _OPPROF
    if rate is not None:
        _PERIOD = _parse_rate(rate)
    if opprof is not None:
        _OPPROF = bool(opprof)
    _push_flag()


def opprof_enabled():
    return _OPPROF


def refresh_from_env():
    global _PERIOD, _OPPROF
    _PERIOD = _parse_rate(os.environ.get("MXNET_DEVICE_TIME", "0"))
    _OPPROF = _parse_opprof(os.environ.get("MXNET_OPPROF", "1"))
    _push_flag()


# --------------------------------------------------------------------------
# program classification: compute vs collective
# --------------------------------------------------------------------------

# collective-communication programs by watched-jit name prefix; kvstore
# registers its reduce/scatter programs at import so the set stays next
# to the code that owns the names
_COLLECTIVE_PREFIXES = {"kvstore"}
_coll_lock = threading.Lock()


def register_collective(prefix):
    """Declare every watched program whose name starts with *prefix* as
    collective communication for the step-timeline decomposition."""
    with _coll_lock:
        _COLLECTIVE_PREFIXES.add(str(prefix))


def is_collective(name):
    return any(name.startswith(p) for p in _COLLECTIVE_PREFIXES)


# --------------------------------------------------------------------------
# sampling state
# --------------------------------------------------------------------------

class _Window:
    """One step (or serving batch) being decomposed."""

    __slots__ = ("sampled", "compute_us", "collective_us", "data_wait_us",
                 "overlap_hidden_us", "overlap_exposed_us", "programs")

    def __init__(self, sampled, data_wait_us):
        self.sampled = sampled
        self.compute_us = 0.0
        self.collective_us = 0.0
        self.data_wait_us = data_wait_us
        self.programs = {}     # name -> µs this window (the opprof feed)
        # direct measurement from the overlap tier (gluon/overlap.py):
        # collective wall time hidden under backward vs exposed in the
        # step's drain — None when the step ran un-overlapped
        self.overlap_hidden_us = None
        self.overlap_exposed_us = None


_tls = threading.local()               # .window — thread-local, see above

_lock = threading.Lock()
_step_seq = 0                          # sampling counter for step windows
_free_seq = 0                          # fallback counter outside windows
_serving_seq = 0                       # serving-batch sampling counter
_free_wall_ewma = None                 # EWMA of un-sampled step walls (µs)
_programs = {}                         # name -> [samples, total_us, max_us]
_timelines = deque(maxlen=_TIMELINE_CAP)
_last_timeline = None


def _take(counter_name):
    """Advance the named sampling counter; True on sampled ticks."""
    global _step_seq, _free_seq, _serving_seq
    with _lock:
        if not _PERIOD:       # disabled between the gate and this call
            return False
        if counter_name == "step":
            _step_seq += 1
            return (_step_seq - 1) % _PERIOD == 0
        if counter_name == "serving":
            _serving_seq += 1
            return (_serving_seq - 1) % _PERIOD == 0
        _free_seq += 1
        return (_free_seq - 1) % _PERIOD == 0


def take_serving_sample():
    """Whether this serving batch should block for true execute time
    (the serving twin of the step-window decision)."""
    if not _PERIOD:
        return False
    return _take("serving")


# --------------------------------------------------------------------------
# the watched-jit hook
# --------------------------------------------------------------------------

def maybe_time(name, t0_us, out):
    """Called by ``_WatchedJit`` after a (non-compiling) call: on sampled
    steps, block on *out* and book the wall delta as device time."""
    win = getattr(_tls, "window", None)
    if win is not None:
        if not win.sampled:
            return
    elif not _take("free"):
        return
    try:
        import jax
        jax.block_until_ready(out)
    except Exception:       # a non-jax return value: nothing to block on
        return
    record_program(name, _core.now_us() - t0_us, window=win)


def note_overlap(hidden_us, exposed_us):
    """Attribute one drained overlap step to the current thread's step
    window: *hidden_us* of collective wall time ran under backward
    (engine-thread bucket tasks completed before the drain), and
    *exposed_us* was paid inside the step (the drain wait plus any
    bucket that could not run off-thread).  With these present the
    window's ``overlap_ratio`` is the DIRECT measurement
    ``hidden / (hidden + exposed)`` instead of the EWMA estimate — it
    works even at sample rate 1.0, where every step serializes and the
    free-wall baseline never exists.  No window (device time off, or
    called outside a step span) = no-op."""
    win = getattr(_tls, "window", None)
    if win is None:
        return
    win.overlap_hidden_us = float(hidden_us)
    win.overlap_exposed_us = float(exposed_us)


def record_program(name, dur_us, window=None, collective=None):
    """Book one sampled device-time measurement for program *name*."""
    if collective is None:
        collective = is_collective(name)
    with _lock:
        rec = _programs.setdefault(name, [0, 0.0, 0.0])
        rec[0] += 1
        rec[1] += dur_us
        rec[2] = max(rec[2], dur_us)
    _core.bump("device_time_samples")
    _core.observe("device_time_us", dur_us)
    if window is not None:
        if collective:
            window.collective_us += dur_us
        else:
            window.compute_us += dur_us
        window.programs[name] = window.programs.get(name, 0.0) + dur_us


# --------------------------------------------------------------------------
# step windows (opened/closed by core's step-span hooks)
# --------------------------------------------------------------------------

def open_step_window():
    if not _PERIOD:
        return
    _tls.window = _Window(_take("step"),
                          _core.gauge("io_batch_wait_us", 0.0))


def close_step_window(dur_us):
    global _free_wall_ewma, _last_timeline
    win = getattr(_tls, "window", None)
    if win is None:
        return
    _tls.window = None
    if not win.sampled:
        # un-sampled steps run un-serialized: their wall time is the
        # overlapped baseline the overlap estimate divides against
        with _lock:
            if _free_wall_ewma is None:
                _free_wall_ewma = dur_us
            else:
                _free_wall_ewma += _EWMA_ALPHA * (dur_us - _free_wall_ewma)
        return
    host_us = max(0.0, dur_us - win.compute_us - win.collective_us)
    with _lock:
        base = _free_wall_ewma
    overlap = 0.0
    if win.overlap_hidden_us is not None:
        # direct measurement from the overlap tier: fraction of the
        # step's collective wall time that ran under backward
        total = win.overlap_hidden_us + (win.overlap_exposed_us or 0.0)
        if total > 0:
            overlap = min(1.0, max(0.0, win.overlap_hidden_us / total))
    elif win.collective_us > 0 and base is not None:
        overlap = min(1.0, max(0.0, (dur_us - base) / win.collective_us))
    entry = {"wall_us": round(dur_us, 1),
             "data_wait_us": round(win.data_wait_us, 1),
             "host_us": round(host_us, 1),
             "device_us": round(win.compute_us, 1),
             "collective_us": round(win.collective_us, 1),
             "overlap_ratio": round(overlap, 4),
             "overlap_hidden_us": None if win.overlap_hidden_us is None
             else round(win.overlap_hidden_us, 1),
             "overlap_exposed_us": None if win.overlap_exposed_us is None
             else round(win.overlap_exposed_us, 1),
             "free_wall_us": None if base is None else round(base, 1)}
    with _lock:
        _timelines.append(entry)
        _last_timeline = entry
    _core.set_gauge("step_data_wait_us", win.data_wait_us)
    _core.set_gauge("step_host_us", host_us)
    _core.set_gauge("step_device_us", win.compute_us)
    _core.set_gauge("step_collective_us", win.collective_us)
    _core.set_gauge("overlap_ratio", overlap)
    if _OPPROF and win.programs:
        # per-program device-time drift feed: sys.modules delegation so
        # this module never imports timeseries (import-light contract);
        # device close runs before note_step_exit, so the rings book
        # under the same step index core is about to assign
        import sys
        ts = sys.modules.get("mxnet_tpu.telemetry.timeseries")
        if ts is not None:
            try:
                ts.record_device_programs(win.programs)
            except Exception:
                pass


# --------------------------------------------------------------------------
# report / reset
# --------------------------------------------------------------------------

def timelines():
    """The last N sampled step decompositions, oldest first."""
    with _lock:
        return list(_timelines)


def device_report():
    """JSON-shaped view for snapshots and ``trace_report``."""
    with _lock:
        programs = {name: {"samples": rec[0],
                           "total_us": round(rec[1], 1),
                           "mean_us": round(rec[1] / rec[0], 1),
                           "max_us": round(rec[2], 1),
                           "collective": is_collective(name)}
                    for name, rec in sorted(_programs.items())}
        return {"enabled": _PERIOD > 0,
                "sample_period": _PERIOD,
                "free_wall_ewma_us": None if _free_wall_ewma is None
                else round(_free_wall_ewma, 1),
                "programs": programs,
                "last_step": _last_timeline,
                "timelines": list(_timelines)}


def reset():
    """Clear accumulated samples/windows (tests)."""
    global _step_seq, _free_seq, _serving_seq, _free_wall_ewma
    global _last_timeline
    with _lock:
        _programs.clear()
        _timelines.clear()
        _step_seq = _free_seq = _serving_seq = 0
        _free_wall_ewma = None
        _last_timeline = None
    _tls.window = None


_push_flag()
