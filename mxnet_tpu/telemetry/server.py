"""Live introspection: an in-process HTTP server + background sampler.

``MXNET_TELEMETRY_HTTP=<port>`` starts a stdlib ``http.server`` daemon
thread bound to localhost (port 0 = ephemeral, read it back from
``server.port``) so a live job can be asked what it is doing without
touching the training loop:

    /metrics    Prometheus text exposition (scrape target)
    /healthz    liveness verdict: steps progressing? retrace storm?
                sanitizer violations?  200 when healthy, 503 when not
    /snapshot   full telemetry snapshot (counters/gauges/histograms/
                retraces/costs) as JSON
    /trace      the Chrome traceEvents buffer (load in Perfetto)
    /flight     the flight-recorder payload (ring + stacks + snapshot)
    /stacks     every thread's Python stack, plain text
    /checkpoints  the active CheckpointManager: committed checkpoints,
                last step, preemption state (an inactive stub before a
                manager is constructed)

A background sampler (default 500 ms, ``MXNET_TELEMETRY_SAMPLE_MS``)
keeps the passive gauges honest between steps: host-engine backlog
(``engine_pending_tasks``), device memory watermarks, and the
``step_rate_per_s`` moving rate.  The sampler only *observes* — it looks
the engine and jax up in ``sys.modules`` and never imports, so a process
that never touched the engine never pays for one.

Localhost-only on purpose: these endpoints expose argv and stack traces.
Front with a real proxy if you need the metrics off-host.
"""
from __future__ import annotations

import json
import logging
import os
import sys
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import core, flight

__all__ = ["IntrospectionServer", "start_server", "stop_server",
           "get_server", "health", "start_from_env",
           "start_sampler", "stop_sampler", "sample_once"]

_LOG = logging.getLogger("mxnet_tpu.telemetry")


def _env_port():
    raw = os.environ.get("MXNET_TELEMETRY_HTTP", "").strip()
    if not raw:
        return None
    try:
        port = int(raw)
    except ValueError:
        return None
    return port if 0 <= port <= 65535 else None


def _env_sampler_ms():
    try:
        return max(50.0, float(os.environ.get("MXNET_TELEMETRY_SAMPLE_MS",
                                              500)))
    except ValueError:
        return 500.0


def _env_stall_secs():
    try:
        return max(1.0, float(os.environ.get("MXNET_HEALTH_STALL_SECS",
                                             120)))
    except ValueError:
        return 120.0


_STALL_SECS = _env_stall_secs()


# --------------------------------------------------------------------------
# health verdict
# --------------------------------------------------------------------------

def _guardian_health():
    """Guardian contribution to the 503 criteria — observe-only
    (``sys.modules`` lookup; a process without an installed guardian
    contributes nothing).  Unhealthy when the consecutive-skip budget is
    exhausted (rollback imminent or, with no manager, the job is
    spinning on poisoned batches) or a rollback is in progress (the last
    step's verdict forced a restore and no applied step has landed
    since)."""
    gmod = sys.modules.get("mxnet_tpu.guardian")
    if gmod is None:
        return None
    try:
        guard = gmod.current()
    except Exception:
        return None
    if guard is None:
        return None
    try:
        desc = guard.describe()
    except Exception:
        return None
    skips = int(desc.get("consecutive_skips") or 0)
    budget = int(desc.get("max_skips") or 0)
    exhausted = budget > 0 and skips >= budget
    rolling_back = desc.get("last_action") == "rollback"
    return {"ok": not (exhausted or rolling_back),
            "consecutive_skips": skips,
            "max_skips": budget,
            "skip_budget_exhausted": exhausted,
            "rollback_in_progress": rolling_back,
            "last_action": desc.get("last_action"),
            "rollbacks": core.counter("guardian_rollbacks")}


def health():
    """(ok, detail-dict).  Healthy means: if training has started, a step
    landed within MXNET_HEALTH_STALL_SECS; no retrace storm; no sanitizer
    violations; and no installed guardian reporting an exhausted skip
    budget or an in-progress rollback.  A process that never steps (pure
    inference, a notebook) is healthy by the step criterion."""
    age = flight.last_step_age()
    stalled = age is not None and age > _STALL_SECS
    storms = core.counter("retrace_storms")
    violations = core.counter("sanitizer_violations")
    guardian = _guardian_health()
    ok = not stalled and storms == 0 and violations == 0 \
        and (guardian is None or guardian["ok"])
    return ok, {
        "ok": ok,
        "steps": {"count": flight.step_count(),
                  "last_step_age_s": None if age is None
                  else round(age, 3),
                  "stalled": stalled,
                  "stall_limit_s": _STALL_SECS},
        "retrace_storms": storms,
        "sanitizer_violations": violations,
        "guardian": guardian,
        "engine_pending_tasks": core.gauge("engine_pending_tasks"),
        "flight_dumps": core.counter("flight_dumps"),
    }


# --------------------------------------------------------------------------
# HTTP server
# --------------------------------------------------------------------------

_INDEX = ("mxnet_tpu introspection\n"
          "endpoints: /metrics /healthz /readyz /snapshot /trace "
          "/flight /stacks /checkpoints /peers /fleet /guardian "
          "/timeseries /profile\n"
          "serving:   /v1/models  /v1/models/<name>[/predict|/load|"
          "/unload|/reload]\n")


def _serving_reply(method, path, body, allow_import=False):
    """Delegate a /v1 path to the serving tier.  GETs and predicts only
    observe (``sys.modules`` lookup — a process that never imported
    serving answers 404 and initializes nothing); *allow_import* is set
    for the explicit management POSTs, where the operator is asking this
    process to BECOME a server."""
    serving = sys.modules.get("mxnet_tpu.serving")
    if serving is None and allow_import:
        import importlib
        serving = importlib.import_module("mxnet_tpu.serving")
    if serving is None:
        return (404, "application/json",
                json.dumps({"error": "serving tier not initialized "
                            "(import mxnet_tpu.serving and load a model, "
                            "or POST /v1/models/<name>/load)"}))
    return serving.handle_http(method, path, body)


class _Handler(BaseHTTPRequestHandler):
    server_version = "mxnet-tpu-introspect/1"

    def log_message(self, *args):            # quiet: we ARE the telemetry
        pass

    def _reply(self, code, content_type, body, headers=()):
        if isinstance(body, str):
            body = body.encode("utf-8")
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        for key, value in headers:
            self.send_header(key, value)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, obj, code=200):
        self._reply(code, "application/json",
                    json.dumps(obj, default=repr))

    def do_GET(self):                        # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/":
                self._reply(200, "text/plain; charset=utf-8", _INDEX)
            elif path == "/metrics":
                self._reply(200,
                            "text/plain; version=0.0.4; charset=utf-8",
                            core.prometheus_text())
            elif path == "/healthz":
                ok, detail = health()
                self._reply_json(detail, 200 if ok else 503)
            elif path == "/readyz":
                # READINESS, split from /healthz LIVENESS: "safe to
                # route new traffic here" vs "process is not wedged".
                # A replica compiling/warming/draining is alive (200 on
                # /healthz) but not ready (503 here) — the router and
                # any external LB key off this one.  Observe-only
                # sys.modules delegation like /v1: a process without a
                # serving tier is trivially ready.
                serving = sys.modules.get("mxnet_tpu.serving")
                if serving is None:
                    self._reply_json({"ok": True, "serving": False}, 200)
                else:
                    ok, detail = serving.readiness()
                    self._reply_json(dict(detail, ok=ok, serving=True),
                                     200 if ok else 503)
            elif path == "/snapshot":
                self._reply_json(core.snapshot())
            elif path == "/trace":
                self._reply_json(core.chrome_trace_payload())
            elif path == "/flight":
                self._reply_json(flight.payload("http"))
            elif path == "/checkpoints":
                # observe-only sys.modules lookup, like /v1 — never
                # initializes anything.  `import mxnet_tpu` pulls the
                # checkpoint package in, so in practice this answers the
                # inactive stub until a CheckpointManager exists; the
                # 404 arm only covers a standalone-telemetry embedding.
                ckpt = sys.modules.get("mxnet_tpu.checkpoint")
                if ckpt is None:
                    self._reply_json(
                        {"error": "checkpoint subsystem not initialized "
                                  "(construct a CheckpointManager)"}, 404)
                else:
                    self._reply_json(ckpt.http_view())
            elif path == "/guardian":
                # observe-only sys.modules lookup, like /checkpoints:
                # `import mxnet_tpu` pulls gluon (hence guardian) in, so
                # in practice this answers the inactive stub until a
                # TrainingGuardian is installed; the 404 arm only covers
                # a standalone-telemetry embedding.
                guard = sys.modules.get("mxnet_tpu.guardian")
                if guard is None:
                    self._reply_json(
                        {"error": "guardian subsystem not initialized "
                                  "(construct a TrainingGuardian)"}, 404)
                else:
                    self._reply_json(guard.http_view())
            elif path == "/fleet":
                # observe-only sys.modules lookup, like /peers: the
                # dist part reports the scheduler's live digest table
                # (or a worker's cached snapshot); the serving part
                # reports the in-process FleetRouter's replica table —
                # never network IO from this handler.
                out = {}
                dist = sys.modules.get("mxnet_tpu.dist_ps")
                if dist is not None:
                    out = dist.fleet_view()
                fleet_mod = sys.modules.get("mxnet_tpu.serving.fleet")
                router = fleet_mod.current_router() \
                    if fleet_mod is not None else None
                if router is not None:
                    out["serving_fleet"] = router.http_view()
                if not out:
                    self._reply_json(
                        {"error": "no fleet in this process (neither "
                                  "mxnet_tpu.dist_ps nor a serving "
                                  "FleetRouter is initialized)"}, 404)
                else:
                    self._reply_json(out)
            elif path == "/peers":
                # observe-only sys.modules lookup, like /checkpoints: a
                # process that never touched the dist transport answers
                # 404 and initializes nothing.  peer_view() itself does
                # no network IO — it reports the heartbeat thread's
                # cached scheduler snapshot (or the live table when this
                # process IS the scheduler).
                dist = sys.modules.get("mxnet_tpu.dist_ps")
                if dist is None:
                    self._reply_json(
                        {"error": "dist transport not initialized "
                                  "(no mxnet_tpu.dist_ps in this "
                                  "process)"}, 404)
                else:
                    self._reply_json(dist.peer_view())
            elif path == "/timeseries":
                # observe-only sys.modules lookup, like /checkpoints:
                # the summary reports per-ring bounds and last values,
                # never the full rings (timeseries.export_json is the
                # bulk path); ?full=1 serves the whole export for a
                # quick scrape of a short run
                ts = sys.modules.get("mxnet_tpu.telemetry.timeseries")
                if ts is None:
                    self._reply_json(
                        {"error": "timeseries store not initialized "
                                  "(import mxnet_tpu.telemetry)"}, 404)
                elif "full=1" in (self.path.split("?", 1) + [""])[1]:
                    self._reply_json(ts.export())
                else:
                    self._reply_json(ts.summary())
            elif path == "/profile":
                # observe-only: the runtime per-program device-time
                # table plus the opprof hot-op/budget summary, each via
                # sys.modules — a process that never imported device or
                # ran an opprof sweep reports what it has, triggers
                # nothing (opprof is deliberately NOT in telemetry's
                # import set; absent means None, not an import)
                dev = sys.modules.get("mxnet_tpu.telemetry.device")
                opp = sys.modules.get("mxnet_tpu.telemetry.opprof")
                payload = {
                    "device": dev.device_report()
                    if dev is not None else None,
                    "opprof": None}
                if opp is not None:
                    try:
                        payload["opprof"] = opp.profile_view()
                    except Exception:
                        pass
                self._reply_json(payload)
            elif path == "/stacks":
                stacks = flight.thread_stacks()
                text = "\n".join("--- %s ---\n%s" % (k, "".join(v))
                                 for k, v in sorted(stacks.items()))
                self._reply(200, "text/plain; charset=utf-8", text)
            elif path.startswith("/v1/"):
                self._reply(*_serving_reply("GET", path, None))
            else:
                self._reply(404, "text/plain; charset=utf-8",
                            "unknown endpoint\n" + _INDEX)
        except BrokenPipeError:              # client went away mid-reply
            pass
        except Exception as exc:             # introspection never kills
            try:
                self._reply(500, "text/plain; charset=utf-8",
                            "introspection error: %r" % (exc,))
            except Exception:
                pass

    def do_POST(self):                       # noqa: N802 (stdlib API)
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            try:
                length = int(self.headers.get("Content-Length") or 0)
            except ValueError:
                length = 0
            body = self.rfile.read(length) if length > 0 else b""
            if path.startswith("/v1/"):
                # management actions (load/unload/reload) may initialize
                # the serving tier; predict stays observe-only
                allow_import = path.rsplit("/", 1)[-1] == "load"
                code, ctype, payload = _serving_reply("POST", path, body,
                                                      allow_import)
                # shed load politely: retry soon
                headers = (("Retry-After", "1"),) if code == 503 else ()
                self._reply(code, ctype, payload, headers=headers)
            else:
                self._reply(404, "text/plain; charset=utf-8",
                            "unknown endpoint\n" + _INDEX)
        except BrokenPipeError:
            pass
        except Exception as exc:
            try:
                self._reply(500, "text/plain; charset=utf-8",
                            "introspection error: %r" % (exc,))
            except Exception:
                pass


class IntrospectionServer:
    """One ThreadingHTTPServer on localhost + its serve thread."""

    def __init__(self, port):
        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), _Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="mxnet-introspect-http", daemon=True)

    @property
    def port(self):
        return self._httpd.server_address[1]

    def start(self):
        self._thread.start()
        return self

    def stop(self):
        self._httpd.shutdown()
        self._httpd.server_close()


_server = None
_server_lock = threading.Lock()


def start_server(port=None, sample_ms=None):
    """Start (or return the running) introspection server; also starts
    the background sampler.  *port* 0 binds an ephemeral port."""
    global _server
    with _server_lock:
        if _server is None:
            if port is None:
                port = _env_port()
            if port is None:
                raise ValueError(
                    "no port: pass one or set MXNET_TELEMETRY_HTTP")
            _server = IntrospectionServer(port).start()
            _LOG.info("introspection server on http://127.0.0.1:%d "
                      "(/metrics /healthz /snapshot /trace /flight "
                      "/stacks)", _server.port)
        server = _server
    start_sampler(sample_ms)
    return server


def get_server():
    return _server


def stop_server():
    global _server
    with _server_lock:
        server, _server = _server, None
    if server is not None:
        server.stop()
    stop_sampler()


def start_from_env():
    """Import-time hook: start iff MXNET_TELEMETRY_HTTP is set."""
    if _env_port() is None:
        return None
    try:
        return start_server()
    except OSError as exc:       # port taken: log, never break import
        _LOG.warning("introspection server failed to bind: %s", exc)
        return None


# --------------------------------------------------------------------------
# background sampler
# --------------------------------------------------------------------------

_sampler = None
_sampler_lock = threading.Lock()


def sample_once(rate_state=None):
    """One sampler tick: engine backlog, device memory, step rate.
    *rate_state* is the (prev_steps, prev_monotonic) carried between
    ticks; returns the updated tuple."""
    core._sample_engine_pending()
    if "jax" in sys.modules:     # observe-only: never initialize jax
        core.sample_memory()
    serving = sys.modules.get("mxnet_tpu.serving")
    if serving is not None:      # observe-only: refresh queue-depth gauges
        try:
            serving.refresh_gauges()
        except Exception:
            pass
    dist = sys.modules.get("mxnet_tpu.dist_ps")
    if dist is not None:         # observe-only: ps_dead_peers gauge
        try:
            dist.refresh_gauges()
        except Exception:
            pass
    now = time.monotonic()
    steps = flight.step_count()
    if rate_state is not None:
        prev_steps, prev_t = rate_state
        dt = now - prev_t
        if dt > 0:
            core.set_gauge("step_rate_per_s",
                           max(0, steps - prev_steps) / dt)
    return (steps, now)


def start_sampler(sample_ms=None):
    """Start the daemon sampler thread (idempotent)."""
    global _sampler
    with _sampler_lock:
        if _sampler is not None:
            return _sampler[0]
        if sample_ms is None:
            sample_ms = _env_sampler_ms()
        interval = max(0.05, sample_ms / 1e3)
        stop = threading.Event()

        def _loop():
            state = (flight.step_count(), time.monotonic())
            while not stop.wait(interval):
                try:
                    state = sample_once(state)
                except Exception:    # a dying backend must not kill us
                    pass

        thread = threading.Thread(target=_loop,
                                  name="mxnet-telemetry-sampler",
                                  daemon=True)
        thread.start()
        _sampler = (thread, stop)
        return thread


def stop_sampler():
    global _sampler
    with _sampler_lock:
        sampler, _sampler = _sampler, None
    if sampler is not None:
        sampler[1].set()
