"""Telemetry: the observability plane of the TPU build, as one package.

Three tiers, youngest on top:

* :mod:`.core` — the in-process plane (PR 2): hierarchical contextvar
  spans feeding a Chrome-trace ring, the typed Counter/Gauge/Histogram
  registry, the retrace watchdog around every owned jit entry point, and
  the Chrome/Prometheus/JSON exporters.  Everything here is re-exported
  at package level: ``from mxnet_tpu import telemetry; telemetry.span``
  keeps working exactly as when this was a single module.
* :mod:`.flight` + :mod:`.server` — the post-mortem and live tier:
  an always-on crash ring with excepthook/SIGTERM/hang dump hooks
  (``flight_<pid>.json``), and the ``MXNET_TELEMETRY_HTTP`` localhost
  endpoints (/metrics /healthz /snapshot /trace /flight /stacks) with a
  background gauge sampler.
* :mod:`.costs` — XLA cost accounting: ``cost_analysis()`` captured per
  compiled program, folded into ``step_model_flops`` / ``step_mfu`` /
  ``step_hbm_bw_util`` at step-span exit against a per-device peak
  table (``MXNET_PEAK_FLOPS`` / ``MXNET_PEAK_HBM_BW`` override).
* :mod:`.timeseries` — the step-indexed health record: bounded
  per-metric rings fed at every step-span exit (and by the
  MXNET_MODEL_STATS recorder), JSON export/merge, the ``/timeseries``
  endpoint, and the raw material of ``tools/health_gate.py``'s drift
  envelopes (docs/OBSERVABILITY.md §model-health).

Import side effects, all cheap and all opt-out-able: crash hooks are
chained (``MXNET_FLIGHT_EVENTS=0`` disables), the hang watchdog starts
iff ``MXNET_HANG_DUMP_SECS`` is set, and the HTTP server starts iff
``MXNET_TELEMETRY_HTTP`` is set.  docs/OBSERVABILITY.md is the guide.
"""
from __future__ import annotations

from . import core, costs, device, flight, server, timeseries  # noqa: F401
from .core import *                                # noqa: F401,F403
from .core import (_set_profiler_running,          # noqa: F401  (profiler)
                   current_span, refresh_from_env, retrace_limit)
from .flight import (dump as dump_flight,          # noqa: F401
                     install_crash_hooks, start_hang_watchdog,
                     thread_stacks)
from .server import (health, start_server,         # noqa: F401
                     stop_server)

__all__ = list(core.__all__) + [
    "current_span", "refresh_from_env", "retrace_limit",
    "core", "costs", "device", "flight", "server", "timeseries",
    "dump_flight", "install_crash_hooks", "start_hang_watchdog",
    "thread_stacks", "health", "start_server", "stop_server",
]

# post-mortem tier wiring (each is a no-op when its env gate says so)
install_crash_hooks()
start_hang_watchdog()
server.start_from_env()
