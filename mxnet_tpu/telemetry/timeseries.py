"""Step-indexed time series: the run's health, one bounded ring per metric.

Spans answer "where did THIS step go"; counters answer "how many, ever".
Neither can answer the convergence questions ROADMAP item 4 is gated on
— was the loss at step N where the last good run had it, did the grad
norm spike, did an update/weight ratio wander out of its band.  Those
need values **keyed by step**, kept for the whole run, exportable, and
comparable across runs.  This module is that store:

* every **step-span exit** (``core._close_step_window``) appends the
  step's wall time and the live step gauges (overlap_ratio, MFU,
  device/collective decomposition, queue depths) to per-metric rings,
  keyed by an internal step counter (the count of step-span exits);
* every **model-stats fetch** (``mxnet_tpu.model_stats.Recorder``)
  appends per-param ``model/<param>/<stat>`` series plus ``model/loss``,
  keyed by the recorder's OPTIMIZER step — the two step clocks are
  recorded as-is and documented apart (a guardian-skipped step advances
  the optimizer-step clock but may share one step span with a retry);
* rings are bounded at ``MXNET_TIMESERIES_STEPS`` points (default 4096;
  the JG006 read-once + ``refresh_from_env`` contract), evictions are
  counted (``timeseries_evictions``) — a week-long run cannot grow host
  RSS through its own health record;
* :func:`export` / :func:`export_json` produce the JSON
  ``tools/health_gate.py`` and ``tools/trace_report.py --health``
  consume; :func:`merge` folds several exports (fleet ranks, or the
  chunks of a long run) into one; the ``/timeseries`` endpoint serves a
  live observe-only summary.

Stdlib-only at import; recording is a deque append under one lock.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque

from . import core as _core

__all__ = ["cap", "configure", "refresh_from_env", "record",
           "note_step_exit", "record_device_programs",
           "record_model_stats", "series", "names",
           "export", "export_json", "load_export", "merge", "summary",
           "reset"]

_DEFAULT_CAP = 4096


def _parse_cap(raw):
    """MXNET_TIMESERIES_STEPS: points kept per metric ring (default
    4096); anything unparsable or < 1 falls back to the default."""
    try:
        n = int(raw)
    except (TypeError, ValueError):
        return _DEFAULT_CAP
    return n if n >= 1 else _DEFAULT_CAP


_CAP = _parse_cap(os.environ.get("MXNET_TIMESERIES_STEPS"))

_lock = threading.Lock()
_series = {}                 # name -> deque((step, value), maxlen=_CAP)
_step_seq = 0                # step-span exits seen (the gauge-series key)

# gauges snapshotted at every step-span exit — only the ones actually
# set this run land (a CPU run without MXNET_DEVICE_TIME has no
# overlap_ratio to record, and records none)
_GAUGE_SERIES = ("overlap_ratio", "step_mfu", "step_model_flops",
                 "step_hbm_bw_util", "step_device_us",
                 "step_collective_us", "step_data_wait_us",
                 "step_host_us", "io_batch_wait_us",
                 "engine_pending_tasks", "serving_queue_depth",
                 "guardian_loss_scale")


def cap():
    return _CAP


def configure(steps=None):
    """Programmatic override of MXNET_TIMESERIES_STEPS.  Existing rings
    are re-bounded in place (oldest points drop first on a shrink)."""
    global _CAP
    if steps is None:
        return
    new = max(1, int(steps))
    with _lock:
        _CAP = new
        for name, ring in list(_series.items()):
            _series[name] = deque(ring, maxlen=new)


def refresh_from_env():
    configure(_parse_cap(os.environ.get("MXNET_TIMESERIES_STEPS")))


def record(name, step, value):
    """Append one (step, value) point to *name*'s ring."""
    value = float(value)
    with _lock:
        ring = _series.get(name)
        if ring is None:
            ring = _series[name] = deque(maxlen=_CAP)
        evict = len(ring) == _CAP
        ring.append((int(step), value))
    if evict:
        _core.bump("timeseries_evictions")


def note_step_exit(dur_us):
    """Step-span exit hook (called by ``core._close_step_window`` at
    depth 0): book the step's wall time and whichever step gauges are
    live under the next step index."""
    global _step_seq
    with _lock:
        step = _step_seq
        _step_seq += 1
        with _core._mlock:
            live = [(n, _core._gauges[n]) for n in _GAUGE_SERIES
                    if n in _core._gauges]
    record("step_time_us", step, dur_us)
    for name, value in live:
        record(name, step, value)


def record_device_programs(programs):
    """Book one sampled step's per-program device time as
    ``device/<program>/us`` rings — the opprof drift feed
    (``device.close_step_window`` delegates here, gated by
    MXNET_OPPROF).  Device close runs before :func:`note_step_exit`, so
    the current ``_step_seq`` is exactly the index this step's gauge
    series are about to book under.  Evictions are counted by
    :func:`record` like every other ring — a long sampled run pays the
    same honest accounting."""
    with _lock:
        step = _step_seq
    for name in sorted(programs):
        record("device/%s/us" % name, step, float(programs[name]))


def record_model_stats(step, names, stats, loss=None):
    """Book one fetched model-stats block (``model_stats.Recorder``):
    per-param ``model/<param>/<stat>`` series in STAT_NAMES column
    order, plus ``model/loss`` when the step carried one.  Keyed by the
    OPTIMIZER step the recorder counted, not the step-span clock."""
    from .. import model_stats as _ms
    for row, pname in enumerate(names):
        for col, sname in enumerate(_ms.STAT_NAMES):
            record("model/%s/%s" % (pname, sname), step,
                   stats[row][col])
    if loss is not None:
        record("model/loss", step, loss)


def names():
    with _lock:
        return sorted(_series)


def series(name):
    """The (step, value) points of one metric, oldest first."""
    with _lock:
        ring = _series.get(name)
        return [] if ring is None else list(ring)


def export():
    """JSON-shaped dump of every ring — the wire format health_gate and
    ``trace_report --health`` consume (and :func:`merge` folds)."""
    with _lock:
        return {"version": 1, "cap": _CAP,
                "steps_seen": _step_seq,
                "series": {name: [[s, v] for s, v in ring]
                           for name, ring in sorted(_series.items())}}


def export_json(path):
    with open(path, "w") as fh:
        json.dump(export(), fh, indent=1, sort_keys=True)
    return path


def load_export(path):
    with open(path) as fh:
        out = json.load(fh)
    if not isinstance(out, dict) or "series" not in out:
        raise ValueError("%s is not a timeseries export "
                         "(missing 'series')" % path)
    return out


def merge(exports):
    """Fold several exports into one (the ``--fleet`` shape: one file
    per rank, or one per chunk of a long run): same-name series are
    concatenated and sorted by step — duplicate steps are kept in input
    order, so callers can tell ranks apart by position if they need to."""
    merged = {}
    steps_seen = 0
    for exp in exports:
        steps_seen = max(steps_seen, int(exp.get("steps_seen", 0)))
        for name, points in exp.get("series", {}).items():
            merged.setdefault(name, []).extend(
                (int(s), float(v)) for s, v in points)
    for name in merged:
        merged[name].sort(key=lambda p: p[0])
    return {"version": 1, "cap": None, "steps_seen": steps_seen,
            "series": {name: [[s, v] for s, v in pts]
                       for name, pts in sorted(merged.items())}}


def summary():
    """Live observe-only view for the ``/timeseries`` endpoint: per-ring
    bounds and last value, never the full payload (export_json is the
    bulk path)."""
    with _lock:
        out = {}
        for name, ring in sorted(_series.items()):
            first = ring[0]
            last = ring[-1]
            out[name] = {"points": len(ring),
                         "first_step": first[0], "last_step": last[0],
                         "last_value": last[1]}
        return {"cap": _CAP, "steps_seen": _step_seq,
                "n_series": len(out), "series": out}


def reset():
    """Clear every ring and the step clock (tests / new session)."""
    global _step_seq
    with _lock:
        _series.clear()
        _step_seq = 0
