"""Runtime telemetry: hierarchical spans, metrics, retrace watchdog, exporters.

The reference engine stamps every op with ``OprExecStat`` and dumps Chrome
trace JSON (``src/engine/profiler.{h,cc}``, SURVEY §5.1).  On the TPU build
the unit of execution is a compiled XLA program, so the observability plane
is organised around four questions instead of one:

1. **Where does wall time go?**  Hierarchical spans (``span()``): a
   contextvar carries the enclosing span, so a ``trainer_step`` span
   contains its kvstore-bucket and optimizer-program children.  Spans land
   in the same Chrome ``traceEvents`` buffer the profiler always produced
   (nesting renders by time containment per tid; each event also carries
   ``args.parent``/``args.depth`` for tooling).
2. **How many programs / bytes?**  A typed metrics registry — monotonic
   :class:`Counter`, last-value :class:`Gauge`, fixed-bucket
   :class:`Histogram` — supersedes the loose ``profiler._counters`` dict.
   ``profiler.bump()/counter()`` remain as shims onto it, and the counter
   fast path stays a lock+int-add (tests gate perf contracts on deltas of
   ``xla_program_calls``; that must never get slower or gated).
3. **What recompiles?**  The retrace watchdog (:func:`watch_jit`) wraps
   every jit entry point the framework owns.  A wrapped callable whose
   jit cache grows during a call records a compile event (name, wall time,
   cache size) and, past ``MXNET_TELEMETRY_RETRACE_LIMIT`` compiles for one
   name, logs ONE structured retrace-storm warning — the signature of a
   shape-unstable input pipeline silently recompiling every step.
4. **How do I read it?**  Exporters: :func:`dump_chrome_trace` (merged
   trace + ``ph:"M"`` track-name metadata), :func:`prometheus_text`
   (text exposition), :func:`snapshot`/:func:`dump_snapshot` (JSON),
   consumed by ``tools/trace_report.py``.
5. **What was the hardware doing?**  Step-span exits close a cost window
   fed by :class:`_WatchedJit`'s XLA ``cost_analysis()`` capture: the
   gauges ``step_model_flops`` / ``step_mfu`` / ``step_hbm_bw_util``
   relate each step to the per-device peak table in
   :mod:`mxnet_tpu.telemetry.costs`.

The post-mortem / live tier lives in the sibling modules of this package:
:mod:`..flight` (always-on crash ring + dump hooks, fed from span exits
and compile events here), :mod:`..server` (the ``MXNET_TELEMETRY_HTTP``
introspection endpoints), :mod:`..costs` (MFU/roofline accounting).

Gating: ``MXNET_TELEMETRY=1`` enables spans/histograms/watchdog/memory
sampling.  Counters are ALWAYS on; with telemetry off every other hook is
one cached-bool check (plus, for step/program spans, the one attribute
compare that keeps the flight recorder's progress clock ticking).  Spans
also record whenever the classic profiler is running
(``profiler.set_state('run')``), so existing profiler workflows keep
working unchanged.

This module is import-light on purpose (stdlib only; jax only touched
inside memory sampling) — every hot path in the framework imports it.
"""
from __future__ import annotations

import contextvars
import json
import logging
import os
import sys
import threading
import time
from collections import deque

from . import flight as _flight

__all__ = ["enabled", "set_enabled", "configure", "trace_active",
           "span", "now_us", "add_event", "clear_events",
           "Counter", "Gauge", "Histogram",
           "bump", "counter", "counters", "reset_counters",
           "set_gauge", "gauge", "observe", "histogram",
           "watch_jit", "compile_events", "retrace_report",
           "dump_chrome_trace", "chrome_trace_payload", "prometheus_text",
           "snapshot", "dump_snapshot", "reset", "sample_memory",
           "program_cost", "program_costs",
           "trace_context", "set_trace_context", "reset_trace_context",
           "new_trace_id", "new_span_id",
           "COUNTERS", "GAUGES", "HISTOGRAMS", "SPANS", "METRIC_NAMES"]

_LOG = logging.getLogger("mxnet_tpu.telemetry")

# --------------------------------------------------------------------------
# config / gating
# --------------------------------------------------------------------------

_TRUTHY = ("1", "true", "on", "yes")


def _env_enabled():
    return os.environ.get("MXNET_TELEMETRY", "0").strip().lower() in _TRUTHY


def _env_retrace_limit():
    try:
        return max(1, int(os.environ.get("MXNET_TELEMETRY_RETRACE_LIMIT", 5)))
    except ValueError:
        return 5


def _env_max_events():
    try:
        return max(1, int(os.environ.get("MXNET_TELEMETRY_MAX_EVENTS",
                                         200_000)))
    except ValueError:
        return 200_000


def _env_tracecheck():
    return os.environ.get("MXNET_TRACECHECK", "0").strip().lower() \
        in _TRUTHY


_ENABLED = _env_enabled()
_RETRACE_LIMIT = _env_retrace_limit()
_TRACECHECK = _env_tracecheck()
_PROF_RUNNING = False          # mirrored by profiler.set_state
# mirrored by telemetry.device (MXNET_DEVICE_TIME): the watched-jit hot
# path gates the sampled device-timing hook on this one module global
_DEVICE_TIME = False


def _set_device_time(flag):
    global _DEVICE_TIME
    _DEVICE_TIME = bool(flag)


def enabled():
    """Whether the telemetry layer (spans/histograms/watchdog) is on."""
    return _ENABLED


def set_enabled(value):
    global _ENABLED
    _ENABLED = bool(value)


def configure(enabled=None, retrace_limit=None, max_events=None):
    """Programmatic override of the MXNET_TELEMETRY* env configuration."""
    global _RETRACE_LIMIT, _events
    if enabled is not None:
        set_enabled(enabled)
    if retrace_limit is not None:
        _RETRACE_LIMIT = max(1, int(retrace_limit))
    if max_events is not None:
        cap = max(1, int(max_events))
        with _lock:
            _events = deque(list(_events)[-cap:], maxlen=cap)


def refresh_from_env():
    """Re-read MXNET_TELEMETRY / MXNET_TELEMETRY_RETRACE_LIMIT /
    MXNET_TRACECHECK / MXNET_DEVICE_TIME (and, when the cost module is
    loaded, its MXNET_PEAK_* overrides)."""
    global _ENABLED, _RETRACE_LIMIT, _TRACECHECK
    _ENABLED = _env_enabled()
    _RETRACE_LIMIT = _env_retrace_limit()
    _TRACECHECK = _env_tracecheck()
    _costs().refresh_from_env()
    dev = sys.modules.get("mxnet_tpu.telemetry.device")
    if dev is not None:
        dev.refresh_from_env()
    ts = sys.modules.get("mxnet_tpu.telemetry.timeseries")
    if ts is not None:
        ts.refresh_from_env()


def retrace_limit():
    return _RETRACE_LIMIT


def _set_profiler_running(running):
    """Called by profiler.set_state so spans honor the classic profiler."""
    global _PROF_RUNNING
    _PROF_RUNNING = bool(running)


def trace_active():
    """True when spans should record trace events."""
    return _ENABLED or _PROF_RUNNING


# --------------------------------------------------------------------------
# trace-event buffer (the Chrome traceEvents the profiler always produced)
# --------------------------------------------------------------------------

_lock = threading.Lock()
# ring buffer: always-on telemetry must not grow host RSS without bound
# over a week-long run — the newest MXNET_TELEMETRY_MAX_EVENTS spans win,
# and evictions are themselves counted (trace_events_dropped)
_events = deque(maxlen=_env_max_events())
_tid_cats = {}                     # tid -> set of categories seen on it
_t0 = time.perf_counter()

# track labels per span category: chrome://tracing / Perfetto show these as
# the thread-name of each tid's track.  One thread usually hosts several
# categories (its spans nest on one track — that containment is also what
# trace_report's self-time sweep relies on), so the label is chosen at
# dump time from the highest-priority category the tid hosted.
_CAT_TRACK = {"operator": "eager-dispatch", "program": "executor",
              "step": "train-step", "kvstore": "kvstore", "io": "data-io",
              "compile": "jit-compile", "serving": "serving",
              "rpc": "dist-rpc", "user": "user"}
_CAT_PRIORITY = ("step", "serving", "program", "kvstore", "io",
                 "operator", "rpc", "compile", "user")


def now_us():
    return (time.perf_counter() - _t0) * 1e6


# the flight ring timestamps with this module's clock so its entries line
# up with the Chrome trace events
_flight.set_clock(now_us)


def add_event(name, cat, start_us, dur_us, tid=None, args=None):
    """Append one complete ('X') event to the trace buffer.

    The append happens under the buffer lock: a concurrent
    ``dump_chrome_trace`` iterates the ring, and deque iteration raises
    if it races a mutation.  Events are only recorded while tracing is
    active, so the lock never touches the telemetry-off path.
    """
    if tid is None:
        tid = threading.get_ident() % 10000
    ev = {"name": name, "cat": cat, "ph": "X", "ts": start_us,
          "dur": dur_us, "pid": os.getpid(), "tid": tid}
    if args:
        ev["args"] = args
    with _lock:
        _tid_cats.setdefault(tid, set()).add(cat)
        dropped = len(_events) == _events.maxlen   # ring evicts the oldest
        _events.append(ev)
    if dropped:
        bump("trace_events_dropped")


def clear_events():
    with _lock:
        _events.clear()
        _tid_cats.clear()


# --------------------------------------------------------------------------
# hierarchical spans
# --------------------------------------------------------------------------

_SPAN_STACK = contextvars.ContextVar("mxnet_tpu_span_stack", default=())


def current_span():
    """Name of the innermost open span on this context (None outside)."""
    stack = _SPAN_STACK.get()
    return stack[-1] if stack else None


# --------------------------------------------------------------------------
# trace context (distributed tracing)
# --------------------------------------------------------------------------
#
# One trace id names one logical unit of work across processes: a
# training step (minted by its step span), a serving request (minted at
# submit), an RPC (minted per frame when nothing is active).  dist_ps
# propagates it on the wire; trace_report --fleet joins the per-rank
# traces back together on it.

_TRACE_CTX = contextvars.ContextVar("mxnet_tpu_trace_id", default=None)


def trace_context():
    """The active trace id on this context (None outside any trace)."""
    return _TRACE_CTX.get()


def set_trace_context(trace_id):
    """Adopt *trace_id* (e.g. one received over the wire); returns the
    reset token."""
    return _TRACE_CTX.set(trace_id)


def reset_trace_context(token):
    try:
        _TRACE_CTX.reset(token)
    except ValueError:        # token from another context: best effort
        pass


def new_trace_id():
    """16-hex-char process-unique trace id."""
    return os.urandom(8).hex()


def new_span_id():
    """8-hex-char span id (send/recv flow pairing)."""
    return os.urandom(4).hex()


class span:
    """Hierarchical timed span: ``with telemetry.span("trainer_step"): ...``

    Nesting is carried by a contextvar (so it survives thread-pool hops
    that copy context), and recorded two ways: structurally via
    ``args.parent``/``args.depth``, and visually via time containment on
    the owning thread's track.  Off path (telemetry off AND profiler
    stopped) is one bool check.

    *hist*: name of a registered histogram to observe with the span's
    duration (µs).  *memory*: sample host/device memory watermarks at span
    exit (step-boundary spans only; it costs a getrusage + device query).
    *args*: extra key/values for the trace event (e.g. bucket bytes).
    """

    __slots__ = ("_name", "_cat", "_hist", "_memory", "_args",
                 "_on", "_t0", "_tok", "_parent", "_trace_tok")

    def __init__(self, name, cat="user", hist=None, memory=False, args=None):
        self._name = name
        self._cat = cat
        self._hist = hist
        self._memory = memory
        self._args = args

    def __enter__(self):
        if not trace_active():
            self._on = False
            self._t0 = None
            if _DEVICE_TIME and self._cat == "step":
                # device-time attribution works with the trace buffer
                # off: the window still opens so sampled programs are
                # decomposed (the span itself records nothing)
                _open_step_window()
                self._t0 = now_us()
            return self
        self._on = True
        stack = _SPAN_STACK.get()
        self._parent = stack[-1] if stack else None
        self._tok = _SPAN_STACK.set(stack + (self._name,))
        self._trace_tok = None
        if self._cat == "step":
            # one trace id per step: RPCs issued inside (kvstore push/
            # pull over dist_ps) inherit it, so --fleet can join the
            # step's spans across ranks.  Steps are trace ROOTS — mint
            # unconditionally: an ambient id adopted from an earlier
            # RPC reply (recv sets the contextvar) must not glue every
            # step of the run into one giant trace
            self._trace_tok = _TRACE_CTX.set(new_trace_id())
            _open_step_window()
        self._t0 = now_us()
        return self

    def __exit__(self, *exc):
        if not self._on:
            # telemetry off: the flight recorder's progress clock still
            # ticks for coarse spans (step/program exits are what the
            # hang watchdog and /healthz reason about) — one string
            # compare, no timing, no lock
            # gate on the OPENED window (_t0), not the live flag:
            # disabling device timing mid-span must not leak the step
            # depth the matching open incremented
            if self._t0 is not None:
                _close_step_window(now_us() - self._t0)
            if self._cat in ("step", "program"):
                _flight.note_span(self._name, self._cat)
            return False
        dur = now_us() - self._t0
        _SPAN_STACK.reset(self._tok)
        args = {"parent": self._parent,
                "depth": len(_SPAN_STACK.get())}
        trace_id = _TRACE_CTX.get()
        if trace_id is not None:
            args["trace_id"] = trace_id
        if self._args:
            args.update(self._args)
        add_event(self._name, self._cat, self._t0, dur, args=args)
        _flight.note_span(self._name, self._cat, dur)
        if self._cat == "step":
            _close_step_window(dur)
            if self._trace_tok is not None:
                reset_trace_context(self._trace_tok)
        if self._hist is not None and _ENABLED:
            observe(self._hist, dur)
        if self._memory and _ENABLED:
            sample_memory()
        return False


# --------------------------------------------------------------------------
# metrics registry
# --------------------------------------------------------------------------
#
# Declarations first: every metric name the framework itself uses MUST be
# listed here — tests/test_telemetry.py statically scans mxnet_tpu/ for
# bump()/counter()/observe()/set_gauge() string literals and asserts
# membership, so a typo'd counter name fails CI instead of silently
# splitting a time series.

COUNTERS = {
    "xla_program_calls": "XLA programs launched (perf-contract currency)",
    "kvstore_push": "kvstore push operations (per key)",
    "kvstore_pull": "kvstore pull broadcast copies (per destination)",
    "kvstore_bucket_reduce": "bucketed gradient-reduce programs",
    "kvstore_reduce_scatter": "bucketed reduce-scatter rounds (ZeRO-1 "
                              "gradient leg: reduce + per-replica "
                              "row placement)",
    "trainer_zero_step": "fused Trainer steps run with the MXNET_ZERO "
                         "sharded weight update",
    "kvstore_push_bytes": "bytes entering kvstore reduction",
    "kvstore_pull_bytes": "bytes broadcast out of the kvstore",
    "kvstore_reduce_bytes": "payload bytes moved through bucket reduces",
    "optimizer_update": "eager per-slot optimizer updates",
    "trainer_fused_step": "fused whole-model Trainer steps",
    "module_train_step": "Module CachedTrainStep executions",
    "eager_invocations": "eager op dispatches through ndarray.invoke",
    "io_batches": "data batches produced by iterators",
    "jit_compiles": "watched-jit cache misses (traces+compiles)",
    "retrace_storms": "watched callables that crossed the retrace limit",
    "trace_events_dropped": "spans evicted from the bounded trace ring",
    "sanitizer_violations": "footguns caught at runtime by MXNET_SANITIZE "
                            "(tracer leaks, syncs-under-trace, engine "
                            "ordering)",
    "lockcheck_violations": "lock acquisition-order inversions witnessed "
                            "live by MXNET_LOCKCHECK (the runtime side "
                            "of the JG009 static cycle check)",
    "flight_dumps": "flight-recorder post-mortem files written (crash, "
                    "signal, hang, or manual)",
    "tracecheck_findings": "trace-tier (JX rule) findings booked by the "
                           "MXNET_TRACECHECK compile hook",
    "serving_requests": "predict requests accepted into a serving queue",
    "serving_batches": "coalesced batches dispatched by the serving "
                       "scheduler",
    "serving_overloads": "requests shed (503) by a full bounded serving "
                         "queue",
    "serving_errors": "predict requests that finished with an error",
    "serving_straight_through": "oversize requests run unpadded outside "
                                "the bucket table (the jit escape hatch)",
    "serving_padded_rows": "padding rows added to reach serving bucket "
                           "boundaries (throughput spent on waste)",
    "serving_warmup_compiles": "AOT bucket variants compiled at model "
                               "load/warmup",
    "checkpoint_saves": "checkpoints committed to disk (periodic async "
                        "or SIGTERM-final synchronous)",
    "checkpoint_restores": "successful CheckpointManager.restore() "
                           "loads",
    "checkpoint_write_retries": "transient checkpoint write failures "
                                "retried with backoff",
    "checkpoint_restore_fallbacks": "corrupt/partial checkpoints skipped "
                                    "in favor of an older complete one",
    "serving_deadline_drops": "queued predict requests dropped un-run "
                              "because their deadline passed before "
                              "dispatch",
    "serving_breaker_opens": "circuit-breaker open transitions after "
                             "consecutive serving batch failures",
    "serving_breaker_shed": "predict requests shed (503) by an open "
                            "serving circuit breaker",
    "chaos_faults": "faults injected by the MXNET_CHAOS chaos tier "
                    "(each also lands in the flight ring)",
    "ps_rpc_timeouts": "dist transport RPC recvs that hit the "
                       "MXNET_PS_RPC_TIMEOUT_S deadline",
    "ps_rpc_retries": "idempotent dist RPCs retried on a fresh "
                      "connection (backoff + jitter)",
    "ps_peer_lost": "structured PeerLost errors raised by the dist "
                    "transport (dead/silent peers, failed barriers)",
    "ps_reconnects": "dist server connections re-established after a "
                     "failure or refresh_servers recovery",
    "ps_heartbeats": "heartbeat frames sent to the dist scheduler",
    "guardian_checks": "trainer steps whose finite-health verdict the "
                       "guardian evaluated",
    "guardian_skipped_steps": "optimizer updates suppressed in-program "
                              "by a nonfinite gradient/loss verdict",
    "guardian_loss_spikes": "applied steps whose loss exceeded the EWMA "
                            "spike factor (blocks last-good pinning)",
    "guardian_rollbacks": "automatic restores to the last-good pinned "
                          "checkpoint after an exhausted skip budget",
    "guardian_scale_cuts": "dynamic loss-scale halvings on overflow",
    "guardian_scale_growths": "dynamic loss-scale doublings after a "
                              "clean growth interval",
    "metric_nonfinite_updates": "EvalMetric updates excluded from "
                                "running sums because their "
                                "contribution was NaN/Inf",
    "device_time_samples": "watched-jit calls block_until_ready-timed "
                           "by the MXNET_DEVICE_TIME sampler",
    "ps_fleet_syncs": "fleet_sync exchanges completed on the heartbeat "
                      "link (digest out, peer/fleet tables + scheduler "
                      "clock back)",
    "fleet_requests": "predict requests accepted by the serving fleet "
                      "router",
    "fleet_hedges": "hedged duplicate attempts fired after the "
                    "p99-derived hedge timeout (first reply wins)",
    "fleet_failovers": "predict attempts re-routed to another replica "
                       "after a replica failure or not-ready reply",
    "fleet_errors": "fleet predict requests that ultimately failed "
                    "(every failover/hedge exhausted or deadline hit)",
    "fleet_shed": "fleet predict requests refused with no routable "
                  "replica (all dead, not-ready, or breaker-open)",
    "fleet_replica_deaths": "replicas declared dead by the router "
                            "(heartbeat disconnect or staleness)",
    "fleet_registrations": "replica registrations accepted by the "
                           "router (including re-registrations into a "
                           "dead rank)",
    "fleet_reloads": "per-replica reload RPCs completed during rolling "
                     "rollouts",
    "replica_predicts": "predict RPCs served by this replica process",
    "overlap_bucket_dispatches": "gradient-bucket reduces dispatched as "
                                 "engine tasks under backward "
                                 "(comm/compute overlap)",
    "overlap_steps": "trainer steps that consumed an overlapped "
                     "bucket-reduce session at drain",
    "overlap_fallbacks": "armed overlap sessions discarded at drain "
                         "(changed slot set, re-written gradient, "
                         "flipped ZeRO plan) — the step fell back to "
                         "the synchronous round",
    "collective_chunk_programs": "chunk-sum programs launched by the "
                                 "chunked collective path (pipelined "
                                 "reduce, arXiv 2112.01075)",
    "collective_gather_home": "sharded arrays streamed home chunk by "
                              "chunk (the chunked all-gather leg)",
    "collective_redistribute": "arrays re-placed onto a new sharding "
                               "through the chunked redistribution "
                               "schedule",
    "model_stats_records": "model-health stats blocks fetched and "
                           "recorded (MXNET_MODEL_STATS due steps)",
    "timeseries_evictions": "points evicted from full time-series rings "
                            "(ring capacity: MXNET_TIMESERIES_STEPS)",
}

GAUGES = {
    "io_batch_wait_us": "time the training loop waited for the last batch "
                        "(data starvation when this rivals step time)",
    "host_rss_peak_bytes": "process peak resident set size",
    "device_bytes_in_use": "device allocator bytes in use, summed over "
                           "local devices (0 if the backend does not "
                           "report memory stats)",
    "device_bytes_in_use_peak": "high-water bytes in use on the most "
                                "loaded single local device",
    "engine_pending_tasks": "host-engine tasks queued or running "
                            "(sampled by the introspection sampler and "
                            "at step-span exits)",
    "step_rate_per_s": "training steps completed per second over the "
                       "sampler's last window",
    "step_model_flops": "model FLOPs executed by compiled programs "
                        "during the last step span (XLA cost_analysis)",
    "step_mfu": "model FLOP utilization of the last step against the "
                "device peak (0-1; MXNET_PEAK_FLOPS overrides)",
    "step_hbm_bw_util": "HBM bandwidth utilization of the last step "
                        "against the device peak (0-1; "
                        "MXNET_PEAK_HBM_BW overrides)",
    "serving_queue_depth": "requests waiting in serving queues, summed "
                           "over model slots",
    "serving_models_loaded": "model slots currently loaded in the "
                             "serving registry",
    "checkpoint_last_step": "training step of the last committed (or "
                            "restored) checkpoint",
    "checkpoint_write_seconds": "background-writer wall seconds for the "
                                "last committed checkpoint",
    "checkpoint_bytes": "total serialized bytes of the last committed "
                        "checkpoint (all shards + manifest'd files)",
    "ps_dead_peers": "peers the dist scheduler currently considers dead "
                     "(live on the scheduler; a worker's cached view "
                     "elsewhere)",
    "guardian_loss_scale": "current guardian loss scale (1.0 when "
                           "scaling is off)",
    "guardian_consecutive_skips": "steps skipped in a row by the "
                                  "guardian (rollback fires at "
                                  "MXNET_GUARDIAN_MAX_SKIPS)",
    "guardian_loss_ewma": "the guardian's EWMA loss baseline for spike "
                          "detection",
    "checkpoint_pinned_step": "the last-good checkpoint step pinned "
                              "against retention (guardian rollback "
                              "target)",
    "zero_shards": "replica count of the active MXNET_ZERO sharded "
                   "weight update (0/absent when replicated)",
    "zero_optimizer_bytes_per_device": "optimizer-state bytes resident "
                                       "per device under the active "
                                       "ZeRO-1 layout",
    "zero_optimizer_bytes_replicated": "optimizer-state bytes a fully "
                                       "replicated layout would hold "
                                       "per device (the ZeRO-1 "
                                       "denominator)",
    "step_data_wait_us": "data-wait segment of the last sampled step "
                         "timeline (io_batch_wait at window open)",
    "step_host_us": "host-gap segment of the last sampled step timeline "
                    "(wall minus device minus collective)",
    "step_device_us": "device-compute segment of the last sampled step "
                      "timeline (blocked compute-program time)",
    "step_collective_us": "collective-comm segment of the last sampled "
                          "step timeline (blocked kvstore-program time)",
    "overlap_ratio": "fraction of the last sampled step's collective "
                     "time hidden under compute (0-1; the ROADMAP "
                     "item-2 win condition)",
    "ps_clock_offset_us": "this rank's estimated trace-clock offset to "
                          "the dist scheduler (RTT-midpoint method)",
    "ps_clock_rtt_us": "round-trip time of the last scheduler clock "
                       "exchange (offset error is bounded by RTT/2)",
    "fleet_replicas_ready": "replicas the serving fleet router currently "
                            "routes traffic to",
    "fleet_replicas_total": "replicas registered with the serving fleet "
                            "router (any state, including dead)",
    "fleet_outstanding": "predict attempts in flight across all "
                         "replicas (the least-outstanding balancing "
                         "signal, summed)",
    "overlap_hidden_us": "collective wall time of the last drained "
                         "step that ran under backward (overlapped "
                         "bucket reduces completed before the drain)",
    "overlap_exposed_us": "collective wall time of the last drained "
                          "step paid inside the step (drain wait + "
                          "buckets that could not run off-thread)",
}

# fixed bucket edges (upper bounds; +Inf is implicit)
_US_BUCKETS = (50.0, 100.0, 250.0, 500.0, 1e3, 2.5e3, 5e3, 1e4, 2.5e4,
               5e4, 1e5, 2.5e5, 5e5, 1e6, 5e6)
_BYTE_BUCKETS = (1 << 10, 16 << 10, 256 << 10, 1 << 20, 4 << 20, 16 << 20,
                 64 << 20, 256 << 20)

_PCT_BUCKETS = (10.0, 25.0, 50.0, 75.0, 90.0, 100.0)

HISTOGRAMS = {
    "step_time_us": ("trainer/module step wall time", _US_BUCKETS),
    "eager_dispatch_us": ("eager op dispatch latency", _US_BUCKETS),
    "jit_compile_us": ("watched-jit trace+compile wall time", _US_BUCKETS),
    "bucket_bytes": ("kvstore bucket payload sizes", _BYTE_BUCKETS),
    "serving_latency_us": ("predict request latency, submit to result",
                           _US_BUCKETS),
    "serving_batch_occupancy": ("dispatched rows as a percent of bucket "
                                "capacity per serving batch",
                                _PCT_BUCKETS),
    "device_time_us": ("sampled per-program device execution time "
                       "(block-until-ready delta)", _US_BUCKETS),
    "serving_queue_wait_us": ("request queue wait, submit to batch "
                              "dispatch", _US_BUCKETS),
    "serving_execute_us": ("serving batch execute segment (dispatch "
                           "wall; true device time on sampled batches "
                           "under MXNET_DEVICE_TIME)", _US_BUCKETS),
    "fleet_request_us": ("fleet predict latency at the router, accept "
                         "to first winning reply (hedges and failovers "
                         "included)", _US_BUCKETS),
}

# Span names the framework itself emits (``span("...")`` literals).
# Declared for the same reason the metrics are: a typo'd span name
# silently splits trace_report's self-time series, so the static gate
# in tests/test_telemetry.py checks every literal against this table.
# (Dynamic span names — the executor's per-program labels — are booked
# through watch_jit names instead and are out of the literal gate's
# reach by construction.)
SPANS = {
    "trainer_step": "one Trainer.step (the step-timeline anchor)",
    "data_batch": "one data-iterator batch production (io tier)",
    "module_train_step": "one Module cached train step",
    "module_step_program": "the module step's fused program call",
    "kvstore_push_pull": "gradient reduce round inside a step",
    "kvstore_bucket_reduce": "one bucketed reduce program (also a "
                             "counter)",
    "optimizer_update": "eager per-slot optimizer update",
    "fused_optimizer_step": "the fused whole-model update program",
    "serving_run_batch": "one coalesced serving batch, dispatch to "
                         "futures resolved",
    "serving_pad": "pad + device_put segment of a serving batch",
    "serving_execute": "executable-call segment of a serving batch",
    "serving_slice": "result slice/host-transfer segment of a serving "
                     "batch",
    "fleet_route": "one fleet-routed predict request, router side "
                   "(accept to winning reply or final failure)",
}

METRIC_NAMES = frozenset(COUNTERS) | frozenset(GAUGES) \
    | frozenset(HISTOGRAMS) | frozenset(SPANS)


class Counter:
    """Monotonic counter view (the value lives in the registry dict so the
    bump fast path stays a plain int add under the registry lock)."""

    __slots__ = ("name", "help")

    def __init__(self, name, help=""):
        self.name, self.help = name, help

    def inc(self, n=1):
        bump(self.name, n)

    @property
    def value(self):
        return counter(self.name)


class Gauge:
    """Last-value gauge."""

    __slots__ = ("name", "help")

    def __init__(self, name, help=""):
        self.name, self.help = name, help

    def set(self, value):
        set_gauge(self.name, value)

    @property
    def value(self):
        return gauge(self.name)


class Histogram:
    """Fixed-bucket histogram: cumulative-style buckets + sum + count."""

    __slots__ = ("name", "help", "buckets", "counts", "total", "count")

    def __init__(self, name, help="", buckets=_US_BUCKETS):
        self.name, self.help = name, help
        self.buckets = tuple(sorted(buckets))
        self.counts = [0] * (len(self.buckets) + 1)   # last = +Inf
        self.total = 0.0
        self.count = 0

    def observe(self, value):
        with _mlock:
            self._observe(value)

    def _observe(self, value):
        i = 0
        for i, edge in enumerate(self.buckets):       # noqa: B007
            if value <= edge:
                break
        else:
            i = len(self.buckets)
        self.counts[i] += 1
        self.total += value
        self.count += 1

    def percentile(self, q):
        """Approximate percentile from bucket boundaries (upper edge of
        the bucket containing the q-quantile observation)."""
        if self.count == 0:
            return 0.0
        rank = q / 100.0 * self.count
        seen = 0
        for i, c in enumerate(self.counts):
            seen += c
            if seen >= rank and c:
                return self.buckets[i] if i < len(self.buckets) \
                    else float("inf")
        return float("inf")

    def to_dict(self):
        return {"buckets": list(self.buckets), "counts": list(self.counts),
                "sum": self.total, "count": self.count}


_mlock = threading.Lock()
_counters = {}                 # name -> int
_gauges = {}                   # name -> float
_hists = {}                    # name -> Histogram


def bump(name, n=1):
    """Increment a named monotonic counter.

    ALWAYS on (no gating on ``enabled()``): counters are how tests and
    benches prove call-count claims — e.g. the fused Trainer step's
    "one XLA program per step" contract gates on the
    ``xla_program_calls`` delta across a step.
    """
    with _mlock:
        _counters[name] = _counters.get(name, 0) + n


def counter(name):
    """Current value of one counter (0 if never bumped)."""
    return _counters.get(name, 0)


def counters():
    """Snapshot of all counters."""
    with _mlock:
        return dict(_counters)


def reset_counters():
    with _mlock:
        _counters.clear()


def set_gauge(name, value):
    _gauges[name] = float(value)


def gauge(name, default=0.0):
    return _gauges.get(name, default)


def histogram(name):
    """The named Histogram, creating it from the declaration table (or
    with default µs buckets for ad-hoc names)."""
    h = _hists.get(name)
    if h is None:
        with _mlock:
            h = _hists.get(name)
            if h is None:
                help_, buckets = HISTOGRAMS.get(name, ("", _US_BUCKETS))
                h = _hists[name] = Histogram(name, help_, buckets)
    return h


def observe(name, value):
    histogram(name).observe(value)


# --------------------------------------------------------------------------
# retrace watchdog
# --------------------------------------------------------------------------

_compile_lock = threading.Lock()
_compiles = {}                 # name -> {"count", "total_us", "last_size"}
_compile_log = []              # [{name, wall_us, cache_size, ts}]
_storm_warned = set()


class _WatchedJit:
    """Wrap a jitted callable; a call during which the jit cache grows is a
    trace+compile and gets recorded against *name*.

    The compiled-program cache key itself is jax-internal; the observable
    is the (name, cache-size) pair — enough to see WHAT keeps recompiling
    and how much wall time each recompile costs.  Attribute access
    (``_cache_size``, ``lower`` ...) proxies to the wrapped callable so
    cache-size contract tests keep working against the wrapper.
    """

    __slots__ = ("_fn", "_name", "_seen_lock", "_max_seen")

    def __init__(self, fn, name):
        self._fn = fn
        self._name = name
        self._seen_lock = threading.Lock()
        self._max_seen = 0

    def __call__(self, *args, **kwargs):
        # MXNET_TRACECHECK and MXNET_DEVICE_TIME ride the same wrapper
        # even with telemetry off (findings/samples are counter-booked,
        # and counters are always on)
        if not (_ENABLED or _TRACECHECK or _DEVICE_TIME):
            return self._fn(*args, **kwargs)
        size_fn = getattr(self._fn, "_cache_size", None)
        if size_fn is None:
            return self._fn(*args, **kwargs)
        before = size_fn()
        t0 = now_us()
        out = self._fn(*args, **kwargs)
        after = size_fn()
        if _DEVICE_TIME and after == before:
            # sampled device timing: block on the outputs so the wall
            # delta ≈ dispatch + device execution.  Fresh-compile calls
            # are excluded (trace+compile wall would pollute the
            # device-time series), and no extra XLA program ever runs —
            # block_until_ready only waits.
            _device().maybe_time(self._name, t0, out)
        if after > before:
            # dedupe concurrent observers of one compile: only the call
            # that advances the high-water cache size books it
            with self._seen_lock:
                fresh = after > self._max_seen
                if fresh:
                    self._max_seen = after
            if fresh:
                wall = now_us() - t0
                # cost capture pays an AOT lower+compile (partially
                # cache-absorbed, still real): cap it at the first few
                # variants per name so a retrace STORM — many compiles,
                # exactly when extra compile time hurts most — stops
                # paying after variant 3
                # (skipped entirely on the MXNET_TRACECHECK-only path:
                # the captured flops/bytes are only ever read by step
                # spans, which need telemetry on — don't pay a second
                # XLA compile for numbers nobody will consume)
                cost = None
                if _ENABLED and (after <= 3
                                 or self._name not in _PROGRAM_COSTS):
                    cost = _capture_cost(self._fn, self._name,
                                         args, kwargs)
                _record_compile(self._name, wall, after, cost)
                if _TRACECHECK:
                    _run_tracecheck(self._name, self._fn, args, kwargs)
        # cost window: a step span is open on this process — attribute
        # this program execution's FLOPs/bytes to it (dict .get + two
        # float adds; the window is None outside step spans)
        win = _STEP_WINDOW
        if win is not None:
            cost = _PROGRAM_COSTS.get(self._name)
            if cost is not None:
                win[0] += cost[0]
                win[1] += cost[1]
        return out

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, "_fn"), item)


def watch_jit(fn, name):
    """Register *fn* (a ``jax.jit`` product) with the retrace watchdog."""
    return _WatchedJit(fn, name)


def _run_tracecheck(name, fn, args, kwargs):
    """MXNET_TRACECHECK compile hook: hand the freshly compiled program
    to the lint trace tier (JX rules + the JX105 retrace explainer).
    Lazy import — the lint package must never load on the normal path —
    and exception-proof: analysis must never break a training step."""
    try:
        from ..lint import tracecheck as _tc
        _tc.on_compile(name, fn, args, kwargs)
    except Exception:
        pass


# --------------------------------------------------------------------------
# XLA cost accounting (per-program capture + per-step window)
# --------------------------------------------------------------------------
#
# _PROGRAM_COSTS holds the last-compiled (flops, bytes_accessed) per
# watched-jit name, written on compile events and read on every watched
# call while a step window is open.  The heavy lifting (ShapeDtypeStruct
# re-lower, cost_analysis parsing, peak tables) lives in ..costs, loaded
# lazily so the import-light contract of this module holds.

_PROGRAM_COSTS = {}            # name -> (flops, bytes_accessed)
_STEP_WINDOW = None            # [flops, bytes] while a step span is open
_STEP_DEPTH = 0
_costs_mod = None


def _costs():
    global _costs_mod
    if _costs_mod is None:
        from . import costs as _costs_mod_  # noqa: PLC0415
        _costs_mod = _costs_mod_
    return _costs_mod


_device_mod = None


def _device():
    global _device_mod
    if _device_mod is None:
        from . import device as _device_mod_  # noqa: PLC0415
        _device_mod = _device_mod_
    return _device_mod


def _capture_cost(fn, name, args, kwargs):
    """Ask XLA what the freshly compiled program costs; never raises."""
    try:
        cost = _costs().capture(fn, args, kwargs)
    except Exception:      # cost accounting must never break a step
        cost = None
    if cost is not None:
        _PROGRAM_COSTS[name] = cost
    return cost


def program_cost(name):
    """(flops, bytes_accessed) of *name*'s last-compiled program, or
    None before its first compile (or when capture failed)."""
    return _PROGRAM_COSTS.get(name)


def program_costs():
    """Snapshot of every captured program cost (JSON-shaped)."""
    return {name: {"flops": c[0], "bytes_accessed": c[1]}
            for name, c in sorted(_PROGRAM_COSTS.items())}


def _open_step_window():
    global _STEP_WINDOW, _STEP_DEPTH
    _STEP_DEPTH += 1
    if _STEP_DEPTH == 1:
        _STEP_WINDOW = [0.0, 0.0]
        if _DEVICE_TIME:
            _device().open_step_window()


def _close_step_window(dur_us):
    """Step-span exit: convert the window's FLOPs/bytes into the MFU and
    bandwidth-utilization gauges, and sample the engine backlog."""
    global _STEP_WINDOW, _STEP_DEPTH
    _STEP_DEPTH = max(0, _STEP_DEPTH - 1)
    if _STEP_DEPTH:
        return
    win, _STEP_WINDOW = _STEP_WINDOW, None
    if win is not None and win[0] > 0:
        try:
            _costs().finalize_step(win[0], win[1], dur_us)
        except Exception:
            pass
    if _DEVICE_TIME:
        _device().close_step_window(dur_us)
    _sample_engine_pending()
    # step time-series hook: the store keys every step-span exit's
    # gauges by step (sys.modules, not an import — core stays the
    # package's dependency root)
    ts = sys.modules.get("mxnet_tpu.telemetry.timeseries")
    if ts is not None:
        try:
            ts.note_step_exit(dur_us)
        except Exception:
            pass


def _sample_engine_pending():
    """engine_pending_tasks gauge — without importing (or creating!) the
    engine: only an already-live singleton is observed."""
    eng = sys.modules.get("mxnet_tpu.engine")
    if eng is None:
        return
    singleton = getattr(eng, "_SINGLETON", None)
    if singleton is None:
        return
    try:
        set_gauge("engine_pending_tasks", singleton.num_pending())
    except Exception:
        pass


def _record_compile(name, wall_us, cache_size, cost=None):
    with _compile_lock:
        rec = _compiles.setdefault(
            name, {"count": 0, "total_us": 0.0, "last_size": 0})
        rec["count"] += 1
        rec["total_us"] += wall_us
        rec["last_size"] = cache_size
        count = rec["count"]
        total_ms = rec["total_us"] / 1e3
        _compile_log.append({"name": name, "wall_us": wall_us,
                             "cache_size": cache_size, "ts": now_us()})
        storm = count > _RETRACE_LIMIT and name not in _storm_warned
        if storm:
            _storm_warned.add(name)
    bump("jit_compiles")
    observe("jit_compile_us", wall_us)
    _flight.record("compile", name, wall_us=round(wall_us, 1),
                   cache_size=cache_size, compiles=count)
    if trace_active():
        t_end = now_us()
        cargs = {"cache_size": cache_size, "compiles": count}
        if cost is not None:
            cargs["flops"] = cost[0]
            cargs["bytes_accessed"] = cost[1]
        add_event("compile:%s" % name, "compile", t_end - wall_us, wall_us,
                  args=cargs)
    if storm:
        bump("retrace_storms")
        _LOG.warning(
            "retrace-storm %s",
            json.dumps({"callable": name, "compiles": count,
                        "limit": _RETRACE_LIMIT,
                        "total_compile_ms": round(total_ms, 3),
                        "hint": "inputs keep changing shape/dtype/structure;"
                                " pad or bucket them so the compiled program"
                                " is reused"}, sort_keys=True))


def compile_events():
    """The raw compile log: [{name, wall_us, cache_size, ts}, ...]."""
    with _compile_lock:
        return [dict(e) for e in _compile_log]


def _acquire(lock, timeout):
    """Lock acquire with optional timeout — the crash/signal dump path
    must never deadlock on a lock the interrupted main thread holds."""
    if timeout is None:
        lock.acquire()
        return True
    return lock.acquire(timeout=timeout)


def retrace_report(lock_timeout=None):
    """Per-callable compile accounting for exporters / trace_report.

    *lock_timeout*: crash-dump callers pass a bound; on timeout the
    report is built from an unlocked best-effort copy (the holder is the
    very thread a signal interrupted — it will never release)."""
    locked = _acquire(_compile_lock, lock_timeout)
    try:
        items = list(_compiles.items())
        warned = set(_storm_warned)
    except RuntimeError:          # unlocked copy raced a resize
        return {}
    finally:
        if locked:
            _compile_lock.release()
    return {name: {"count": rec["count"],
                   "total_ms": rec["total_us"] / 1e3,
                   "cache_size": rec["last_size"],
                   "storm": name in warned}
            for name, rec in items}


# --------------------------------------------------------------------------
# memory watermarks
# --------------------------------------------------------------------------

def _device_memory(devices):
    """(total bytes_in_use, max single-device bytes_in_use) over
    *devices*; (None, None) when no device reports memory stats."""
    total, worst, reported = 0, 0, False
    for dev in devices:
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        used = int(stats.get("bytes_in_use", 0))
        total += used
        worst = max(worst, used)
        reported = True
    return (total, worst) if reported else (None, None)


def sample_memory():
    """Record host/device memory watermarks into the gauges (called at
    step-span boundaries and by the introspection sampler; safe on
    backends without memory_stats).

    Device usage is summed over ALL local devices — a multi-chip run
    reading one device would under-report HBM by 1/N — and the most
    loaded single device feeds a monotonic high-water gauge (the OOM
    question is always about the worst chip, not the average).
    """
    try:
        import resource
        rss = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        # linux reports KiB, macOS bytes; normalise to bytes
        set_gauge("host_rss_peak_bytes",
                  rss * 1024 if os.uname().sysname == "Linux" else rss)
    except Exception:
        pass
    try:
        import jax
        total, worst = _device_memory(jax.local_devices())
        if total is not None:
            set_gauge("device_bytes_in_use", total)
            set_gauge("device_bytes_in_use_peak",
                      max(worst, gauge("device_bytes_in_use_peak")))
    except Exception:
        pass


# --------------------------------------------------------------------------
# exporters
# --------------------------------------------------------------------------

def _metadata_events():
    """ph:'M' process/thread-name events so Perfetto / chrome://tracing
    label the tracks instead of showing bare numeric tids.  A track's name
    is its highest-priority hosted category (a train thread that also
    dispatches eager ops reads 'train-step', an io producer 'data-io').
    Caller holds ``_lock``."""
    pid = os.getpid()
    meta = [{"ph": "M", "name": "process_name", "pid": pid, "tid": 0,
             "args": {"name": "mxnet_tpu"}}]
    for tid, cats in sorted(_tid_cats.items()):
        label = next((_CAT_TRACK[c] for c in _CAT_PRIORITY if c in cats),
                     "thread-%d" % tid)
        meta.append({"ph": "M", "name": "thread_name", "pid": pid,
                     "tid": tid, "args": {"name": label}})
    return meta


def chrome_trace_payload():
    """The merged trace (spans + op events + compile events) with
    track-name metadata, as the Chrome trace JSON object."""
    with _lock:
        return {"traceEvents": _metadata_events() + list(_events),
                "displayTimeUnit": "ms"}


def dump_chrome_trace(filename):
    """Write :func:`chrome_trace_payload` to *filename*."""
    payload = chrome_trace_payload()
    with open(filename, "w") as f:
        json.dump(payload, f)
    return filename


def _escape_help(text):
    """Prometheus exposition-format HELP escaping: a raw newline in a
    HELP line terminates it mid-text and the next fragment becomes an
    unparseable sample line — the whole scrape fails."""
    return str(text).replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(value):
    """Label-value escaping per the exposition format (backslash first,
    then quote and newline)."""
    return str(value).replace("\\", "\\\\").replace('"', '\\"') \
        .replace("\n", "\\n")


def prometheus_text():
    """Prometheus text exposition of every live metric."""
    lines = []
    with _mlock:
        counter_items = sorted(_counters.items())
        gauge_items = sorted(_gauges.items())
        # copy each histogram's fields under the lock: a concurrent
        # observe() must not yield buckets disagreeing with _count/_sum
        hists = [(h.name, h.help, h.buckets, list(h.counts),
                  h.total, h.count) for h in _hists.values()]
    for name, val in counter_items:
        lines.append("# HELP %s %s"
                     % (name, _escape_help(COUNTERS.get(name, name))))
        lines.append("# TYPE %s counter" % name)
        lines.append("%s %d" % (name, val))
    for name, val in gauge_items:
        lines.append("# HELP %s %s"
                     % (name, _escape_help(GAUGES.get(name, name))))
        lines.append("# TYPE %s gauge" % name)
        lines.append("%s %.17g" % (name, val))
    for name, help_, buckets, counts, total, count in hists:
        lines.append("# HELP %s %s" % (name, _escape_help(help_ or name)))
        lines.append("# TYPE %s histogram" % name)
        cum = 0
        for edge, c in zip(buckets, counts):
            cum += c
            lines.append('%s_bucket{le="%s"} %d'
                         % (name, _escape_label("%.17g" % edge), cum))
        cum += counts[-1]
        lines.append('%s_bucket{le="+Inf"} %d' % (name, cum))
        lines.append("%s_sum %.17g" % (name, total))
        lines.append("%s_count %d" % (name, count))
    return "\n".join(lines) + "\n"


def snapshot(lock_timeout=None):
    """JSON-serialisable snapshot of the whole telemetry state.

    *lock_timeout*: bounds every lock acquire — the flight recorder's
    signal handler snapshots from the main thread, which may itself be
    mid-``bump()`` holding ``_mlock``; a plain blocking acquire there
    would turn SIGTERM into a hang.  On timeout the copies are taken
    unlocked (worst case: one torn histogram in a post-mortem)."""
    locked = _acquire(_mlock, lock_timeout)
    try:
        counters_ = dict(_counters)
        gauges_ = dict(_gauges)
        hists_ = {n: h.to_dict() for n, h in _hists.items()}
    except RuntimeError:          # unlocked copy raced a resize
        counters_, gauges_, hists_ = {}, {}, {}
    finally:
        if locked:
            _mlock.release()
    costs_ = {"programs": program_costs(),
              "peaks": _costs().peaks_if_resolved()}
    snap = {"enabled": _ENABLED,
            "retrace_limit": _RETRACE_LIMIT,
            "counters": counters_,
            "gauges": gauges_,
            "histograms": hists_,
            "retraces": retrace_report(lock_timeout),
            "costs": costs_}
    if _DEVICE_TIME:
        try:
            snap["device"] = _device().device_report()
        except Exception:     # a post-mortem snapshot must never fail
            pass
    return snap


def dump_snapshot(filename):
    with open(filename, "w") as f:
        json.dump(snapshot(), f, indent=1, sort_keys=True)
    return filename


def reset():
    """Clear events, metrics, and watchdog state (tests / new session)."""
    global _STEP_WINDOW, _STEP_DEPTH
    clear_events()
    reset_counters()
    with _mlock:
        _gauges.clear()
        _hists.clear()
    with _compile_lock:
        _compiles.clear()
        _compile_log.clear()
        _storm_warned.clear()
    _PROGRAM_COSTS.clear()
    _STEP_WINDOW = None
    _STEP_DEPTH = 0
    dev = sys.modules.get("mxnet_tpu.telemetry.device")
    if dev is not None:
        dev.reset()
    ts = sys.modules.get("mxnet_tpu.telemetry.timeseries")
    if ts is not None:
        ts.reset()
    _flight.reset()
