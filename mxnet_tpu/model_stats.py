"""In-program model-health statistics: the compiled Monitor substrate.

The reference framework's ``Monitor`` taps per-op outputs through an
executor callback — on this build that means abandoning the compiled
program for an eager node-by-node forward, the exact anti-pattern the
whole-program doctrine forbids.  This module restores the capability the
other way around: the statistics ride OUT of the one program that
already runs.

``MXNET_MODEL_STATS=1`` (or ``=<interval>`` to record every Nth step)
makes the fused trainer step — plain, ZeRO-1, and guarded alike — emit
one extra ``stack``-shaped f32 side-output computed inside the donated
program: per-slot

    grad_norm_sq     sum g², f32-accumulated (guardian/health.py rules:
                     cast BEFORE the reduction, never f64)
    weight_norm_sq   sum w_new² over the updated weight
    update_ratio     ||w_new - w_old|| / (||w_old|| + 1e-12)
    grad_absmax      max |g| (the overflow/underflow early-warning)

plus, when the step carries a recorded loss, one trailing ``loss`` row.
No host callback, no second XLA launch on the fused paths (graftcheck
specimens prove it on the ``fused_trainer_step*_stats`` programs); the
``MXNET_FUSED_TRAINER=0`` per-slot oracle computes the identical block
through :func:`stats_program` — ONE small watched jit, the
``guardian_verdict`` pattern — on due steps only.

The statistics math is isolated from the update clusters by
``jax.lax.optimization_barrier`` on its inputs, so stats-on training is
bitwise-identical to stats-off (tests/test_model_health.py pins it
across {fused, zero1, guardian-nan-retry}).  The host only *fetches*
the side-output on due-interval steps; the program itself is one fixed
signature either way, so flipping intervals never retraces.

Consumers: :class:`mxnet_tpu.monitor.Monitor`'s compiled mode drains
:func:`recorder`'s rows; ``telemetry/timeseries.py`` keys them by
optimizer step for export, the ``/timeseries`` endpoint, and
``tools/health_gate.py``'s drift envelopes (docs/OBSERVABILITY.md
§model-health).
"""
from __future__ import annotations

import os
import threading
from collections import deque

import jax
import jax.numpy as jnp

from . import telemetry as _tel

__all__ = ["STAT_NAMES", "enabled", "interval", "configure",
           "refresh_from_env", "stats_block", "stats_program",
           "recorder", "Recorder", "tracecheck_programs"]

# column order of the stacked side-output (and of every Recorder row)
STAT_NAMES = ("grad_norm_sq", "weight_norm_sq", "update_ratio",
              "grad_absmax")


def _parse_interval(raw):
    """MXNET_MODEL_STATS: unset/'0' = off; '1' = record every step; an
    integer N > 1 records every Nth step (the program computes stats on
    EVERY step either way — only the host fetch is rationed, so the
    interval never changes the compiled signature)."""
    if raw is None:
        return 0
    raw = raw.strip().lower()
    if raw in ("", "0", "false", "off", "no"):
        return 0
    if raw in ("1", "true", "on", "yes"):
        return 1
    try:
        return max(0, int(raw))
    except ValueError:
        return 0


_INTERVAL = _parse_interval(os.environ.get("MXNET_MODEL_STATS"))


def enabled():
    return _INTERVAL > 0


def interval():
    """Steps between recorded fetches (0 = off, 1 = every step)."""
    return _INTERVAL


def configure(interval=None):
    """Programmatic override of MXNET_MODEL_STATS (tests / notebooks)."""
    global _INTERVAL
    if interval is not None:
        _INTERVAL = max(0, int(interval))


def refresh_from_env():
    global _INTERVAL
    _INTERVAL = _parse_interval(os.environ.get("MXNET_MODEL_STATS"))


# --------------------------------------------------------------------------
# the in-program math
# --------------------------------------------------------------------------

def _slot_stats(w_old, g, w_new):
    """One slot's 4-stat row.  All accumulation in f32 (the cast happens
    BEFORE each reduction — guardian/health.py's rule: an f16 vdot
    saturates at 65504 and reports inf for finite half gradients; f64
    would trip JX102 and double HBM traffic)."""
    go = g.ravel().astype(jnp.float32)
    wo = w_old.ravel().astype(jnp.float32)
    wn = w_new.ravel().astype(jnp.float32)
    upd = wn - wo
    gsq = jnp.vdot(go, go)
    wsq = jnp.vdot(wn, wn)
    ratio = jnp.sqrt(jnp.vdot(upd, upd)) \
        / (jnp.sqrt(jnp.vdot(wo, wo)) + jnp.float32(1e-12))
    absmax = jnp.max(jnp.abs(go))
    return jnp.stack([gsq, wsq, ratio, absmax])


def stats_block(params_old, grads, params_new, loss=None):
    """The full side-output: ``(n_slots [+1], 4)`` f32.  With *loss*
    (any float array; scalarized by mean) a trailing ``[loss, 0, 0, 0]``
    row rides along, so loss, gradients, and update magnitudes share one
    device fetch.  Pure math — callers on the fused paths barrier the
    inputs first so these reductions cannot fuse into (and re-codegen)
    the update clusters."""
    rows = [_slot_stats(w, g, n)
            for w, g, n in zip(params_old, grads, params_new)]
    if loss is not None:
        loss32 = jnp.mean(jnp.asarray(loss).astype(jnp.float32))
        zero = jnp.float32(0.0)
        rows.append(jnp.stack([loss32, zero, zero, zero]))
    return jnp.stack(rows)


def _stats(params_old, grads, params_new, loss):
    return stats_block(params_old, grads, params_new, loss)


# one watched jit for the whole process: jax keys its own cache on the
# leaves' shapes/dtypes, so every model shares this single entry point
# (the guardian_verdict pattern)
_STATS_JIT = None


def stats_program():
    """The per-slot oracle's stats program (lazy, process-wide): the
    ``MXNET_FUSED_TRAINER=0`` loop calls this ONE extra watched program
    on due steps — the eager path's whole cost of model stats."""
    global _STATS_JIT
    if _STATS_JIT is None:
        _STATS_JIT = _tel.watch_jit(jax.jit(_stats), "model_stats")
    return _STATS_JIT


# --------------------------------------------------------------------------
# host-side recorder
# --------------------------------------------------------------------------

class Recorder:
    """Bounded host-side record of fetched stats blocks, keyed by
    optimizer step (its own monotonic count of stats-enabled trainer
    steps — guardian-skipped steps included: a skipped step's zero
    update_ratio and nonfinite grad stats are exactly the signal a
    drift table wants to show).

    Rows are ``(step, names, stats, loss)`` with *names* the per-slot
    parameter names and *stats* an ``(n_slots, 4)`` float ndarray in
    :data:`STAT_NAMES` column order.  ``drain()`` feeds the Monitor's
    compiled mode; every ``record`` also lands in
    ``telemetry.timeseries`` under ``model/<param>/<stat>`` keys.
    """

    def __init__(self, cap=256):
        self._lock = threading.Lock()
        self._rows = deque(maxlen=cap)
        self._step = 0

    def note_step(self):
        """Advance the optimizer-step count; True when this step's stats
        are due a host fetch under the current interval."""
        with self._lock:
            step = self._step
            self._step += 1
        return _INTERVAL > 0 and step % _INTERVAL == 0

    @property
    def step(self):
        with self._lock:
            return self._step

    def record(self, names, stats, loss=None):
        """Book one fetched block against the CURRENT step (the one
        ``note_step`` just counted)."""
        import numpy as np
        stats = np.asarray(stats, np.float32)
        with self._lock:
            step = self._step - 1
            self._rows.append((step, tuple(names), stats, loss))
        _tel.bump("model_stats_records")
        ts = _timeseries()
        if ts is not None:
            ts.record_model_stats(step, names, stats, loss)

    def record_block(self, names, block, has_loss):
        """Split one raw device side-output into (stats, loss) and book
        it: *block* is the ``(n_slots [+1], 4)`` program output, *has_loss*
        whether a loss row trails (static per program signature)."""
        import numpy as np
        arr = np.asarray(block, np.float32)
        loss = float(arr[-1, 0]) if has_loss else None
        self.record(names, arr[:len(names)], loss)

    def drain(self, start=None):
        """Rows with step >= *start* (None = everything buffered)."""
        with self._lock:
            rows = list(self._rows)
        if start is None:
            return rows
        return [r for r in rows if r[0] >= start]

    def latest(self):
        with self._lock:
            return self._rows[-1] if self._rows else None

    def reset(self):
        with self._lock:
            self._rows.clear()
            self._step = 0


def _timeseries():
    import sys
    return sys.modules.get("mxnet_tpu.telemetry.timeseries")


_RECORDER = Recorder()


def recorder():
    """The process-wide recorder (one trainer step stream per process,
    like the update-count bookkeeping it mirrors)."""
    return _RECORDER


def tracecheck_programs():
    """AOT specimens for graftcheck: the oracle-path stats program over
    the mixed two-slot layout ``Trainer._loop_step`` feeds it, with and
    without the trailing loss row."""
    import numpy as np
    params = [jnp.zeros((32, 16), jnp.float32),
              jnp.zeros((32,), jnp.float32)]
    loss = jnp.asarray(np.float32(0.0))
    return [("model_stats", stats_program(),
             (params, params, params, loss), {})]
