"""Data iterators.

API parity with the reference ``python/mxnet/io.py:42-932`` (DataDesc /
DataBatch / DataIter protocol, ResizeIter, PrefetchingIter, NDArrayIter)
plus the native-iterator equivalents CSVIter (src/io/iter_csv.cc:150) and
MNISTIter (src/io/iter_mnist.cc:259). Independent design: prefetching is
organised around per-source ``_Slot`` producer threads, and NDArrayIter's
cursor arithmetic lives in two small helpers.

TPU note: iterators build host batches; arrays land on device at ``forward``
time, one upload per batch.
"""
from __future__ import annotations

import os
import struct
import threading

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from . import random as _random
from . import telemetry as _tel
from .ndarray import NDArray


_io_suppress = threading.local()


def _timed_batch(produce):
    """Time one batch fetch through *produce*.

    Feeds the data-starvation telemetry: ``io_batch_wait_us`` is the time
    the CONSUMER just spent waiting for this batch — when it rivals the
    step time, the input pipeline (not the device) is the bottleneck.
    Exactly ONE timing per logical batch: nested fetches (ResizeIter /
    wrapper iterators delegating to an inner iterator on the same
    thread) are suppressed by a reentrancy flag, and prefetch PRODUCER
    threads are suppressed permanently — counting either would
    double-book batches or overwrite the gauge with the producer's full
    fetch time, inverting the starvation signal for a healthy prefetched
    pipeline.  Off path is two cached-bool checks.
    """
    if getattr(_io_suppress, "active", False) \
            or not (_tel.enabled() or _tel.trace_active()):
        return produce()
    t0 = _tel.now_us()
    _io_suppress.active = True
    try:
        batch = produce()
    finally:
        _io_suppress.active = False
    dur = _tel.now_us() - t0
    if _tel.enabled():
        _tel.bump("io_batches")
        _tel.set_gauge("io_batch_wait_us", dur)
    if _tel.trace_active():
        _tel.add_event("data_batch", "io", t0, dur)
    return batch

__all__ = ["DataDesc", "DataBatch", "DataIter", "ResizeIter",
           "PrefetchingIter", "NDArrayIter", "CSVIter", "MNISTIter",
           "LibSVMIter"]


class DataDesc:
    """name/shape/dtype/layout tuple-alike describing one input
    (ref io.py:42)."""

    def __init__(self, name, shape, dtype=np.float32, layout="NCHW"):
        self.name, self.shape = name, tuple(shape)
        self.dtype, self.layout = dtype, layout

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape,
                                          self.dtype, self.layout)

    # tuple compatibility: behaves as (name, shape) for legacy callers
    def __iter__(self):
        return iter((self.name, self.shape))

    def __getitem__(self, i):
        return (self.name, self.shape)[i]

    def __len__(self):
        return 2

    def __eq__(self, other):
        if isinstance(other, (tuple, list)):
            return (self.name, self.shape) == tuple(other)
        return (isinstance(other, DataDesc) and self.name == other.name
                and self.shape == other.shape)

    @staticmethod
    def get_batch_axis(layout):
        return 0 if layout is None else layout.find("N")

    @staticmethod
    def get_list(shapes, types=None):
        dtype_of = dict(types) if types is not None else {}
        return [DataDesc(name, shape, dtype_of.get(name, np.float32))
                for name, shape in shapes]


class DataBatch:
    """One minibatch of data+label arrays (ref io.py:115)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        def listify(x):
            return x if x is None or isinstance(x, (list, tuple)) else [x]
        self.data, self.label = listify(data), listify(label)
        self.pad, self.index = pad, index
        self.bucket_key = bucket_key
        self.provide_data, self.provide_label = provide_data, provide_label


class DataIter:
    """Iterator protocol base (ref io.py:176): subclasses implement
    iter_next/getdata/getlabel/getpad; next() assembles the DataBatch."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def __next__(self):
        return self.next()

    def reset(self):
        pass

    # -- checkpoint-state protocol (mxnet_tpu.checkpoint) ------------------
    # A resumable iterator returns a picklable cursor dict; the manager
    # stores it in the checkpoint and feeds it back on restore so the
    # post-resume batch sequence is bitwise-identical.  The base class
    # opts out (None = "not resumable": save records nothing, restore
    # skips) so wrapper/native iterators degrade gracefully.

    def get_checkpoint_state(self):
        return None

    def set_checkpoint_state(self, state):
        pass

    def skip_batches(self, n):
        """Advance the stream *n* batches (wrapping epochs like a
        training loop would) WITHOUT returning them — the guardian's
        quarantine primitive: after a rollback rewinds the cursor, the
        batch window that poisoned the run is skipped instead of
        replayed.  Returns the number of batches actually skipped (an
        exhausted, non-resetting stream stops early)."""
        skipped = 0
        for _ in range(int(n)):
            try:
                self.next()
            except StopIteration:
                self.reset()
                try:
                    self.next()
                except StopIteration:
                    break
            skipped += 1
        return skipped

    def next(self):
        return _timed_batch(self._produce_next)

    def _produce_next(self):
        if not self.iter_next():
            raise StopIteration
        return DataBatch(data=self.getdata(), label=self.getlabel(),
                         pad=self.getpad(), index=self.getindex())

    def iter_next(self):
        raise NotImplementedError()

    def getdata(self):
        raise NotImplementedError()

    def getlabel(self):
        raise NotImplementedError()

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError()


class _BatchView(DataIter):
    """Mixin for iterators that expose a held ``current_batch``."""

    current_batch = None

    def _held(self, field):
        return getattr(self.current_batch, field)

    def getdata(self):
        return self._held("data")

    def getlabel(self):
        return self._held("label")

    def getindex(self):
        return self._held("index")

    def getpad(self):
        return self._held("pad")


class ResizeIter(_BatchView):
    """Present an underlying iterator as exactly ``size`` batches,
    rewinding it on exhaustion (ref io.py:264)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__(data_iter.batch_size)
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur >= self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def get_checkpoint_state(self):
        inner = self.data_iter.get_checkpoint_state()
        if inner is None:
            return None
        return {"kind": "ResizeIter", "cur": int(self.cur), "inner": inner}

    def set_checkpoint_state(self, state):
        self.cur = int(state["cur"])
        self.data_iter.set_checkpoint_state(state["inner"])


class _Slot:
    """One producer thread double-buffering one source iterator.

    The thread fills ``batch`` whenever ``vacant`` is set, then flips
    ``ready``. StopIteration is represented by batch=None.
    """

    def __init__(self, source):
        self.source = source
        self.ready = threading.Event()
        self.vacant = threading.Event()
        self.vacant.set()
        self.batch = None
        self.live = True
        self.thread = threading.Thread(target=self._produce, daemon=True)
        self.thread.start()

    def _produce(self):
        _io_suppress.active = True       # producer fetches are never the
        while True:                      # consumer's wait
            self.vacant.wait()
            if not self.live:
                return
            try:
                self.batch = self.source.next()
            except StopIteration:
                self.batch = None
            self.vacant.clear()
            self.ready.set()

    def release(self):
        """Consume the held batch; producer refills in the background."""
        self.ready.clear()
        self.vacant.set()

    def reset(self):
        self.ready.wait()          # let any in-flight fill land
        self.source.reset()
        self.release()

    def shutdown(self):
        self.live = False
        self.vacant.set()


class PrefetchingIter(_BatchView):
    """Background-thread prefetcher over one or more iterators
    (ref io.py:343 / src/io/iter_prefetcher.h), merging their outputs
    into a single DataBatch per step."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        sources = iters if isinstance(iters, list) else [iters]
        if not sources:
            raise ValueError("need at least one source iterator")
        self.iters = sources
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self._slots = [_Slot(src) for src in sources]

    def __del__(self):
        for slot in self._slots:
            slot.shutdown()

    def _described(self, per_iter_descs, renames):
        if renames is None:
            return sum(per_iter_descs, [])
        renamed = []
        for mapping, descs in zip(renames, per_iter_descs):
            for d in descs:
                if isinstance(d, DataDesc):
                    renamed.append(DataDesc(mapping[d.name], d.shape, d.dtype))
                else:
                    renamed.append(DataDesc(mapping[d[0]], d[1]))
        return renamed

    @property
    def provide_data(self):
        return self._described([it.provide_data for it in self.iters],
                               self.rename_data)

    @property
    def provide_label(self):
        return self._described([it.provide_label for it in self.iters],
                               self.rename_label)

    def reset(self):
        for slot in self._slots:
            slot.reset()

    def iter_next(self):
        for slot in self._slots:
            slot.ready.wait()
        parts = [slot.batch for slot in self._slots]
        if parts[0] is None:
            return False
        self.current_batch = DataBatch(
            sum((b.data for b in parts), []),
            sum((b.label for b in parts), []),
            parts[0].pad, parts[0].index,
            provide_data=self.provide_data,
            provide_label=self.provide_label)
        for slot in self._slots:
            slot.release()
        return True

    def next(self):
        return _timed_batch(self._produce_next)

    def _produce_next(self):
        if not self.iter_next():
            raise StopIteration
        return self.current_batch


def _init_data(data, allow_empty, default_name):
    """Normalise array / list / dict input into [(name, NDArray), ...]."""
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not data and not allow_empty:
            raise ValueError("empty data")
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    pairs = []
    for name, arr in data.items():
        if not isinstance(arr, NDArray):
            raw = np.asarray(arr)
            if raw.dtype == np.float64:
                raw = raw.astype(np.float32)
            arr = nd.array(raw, dtype=raw.dtype)
        pairs.append((name, arr))
    return pairs


class NDArrayIter(DataIter):
    """Batched iteration over in-memory arrays (ref io.py:516).

    ``last_batch_handle``: 'pad' wraps the tail batch around and reports
    pad; 'discard' drops it; 'roll_over' carries it into the next epoch.
    """

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False,
                               default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.shuffle = shuffle
        self.last_batch_handle = last_batch_handle

        total = self.data[0][1].shape[0]
        self.idx = np.arange(total)
        if shuffle:
            _random.host_rng().shuffle(self.idx)
        if last_batch_handle == "discard":
            self.idx = self.idx[:total - total % batch_size]
        self.num_data = self.idx.shape[0]
        if self.num_data < batch_size:
            raise ValueError("batch_size needs to be smaller than data size.")
        self.data_list = [arr for _, arr in self.data + self.label]
        self.num_source = len(self.data_list)
        self.cursor = -batch_size
        # host-side staging copies so slicing doesn't round-trip the device
        self._np_cache = {name: arr.asnumpy()
                         for name, arr in self.data + self.label}

    @property
    def provide_data(self):
        return [DataDesc(name, (self.batch_size,) + arr.shape[1:], arr.dtype)
                for name, arr in self.data]

    @property
    def provide_label(self):
        return [DataDesc(name, (self.batch_size,) + arr.shape[1:], arr.dtype)
                for name, arr in self.label]

    def hard_reset(self):
        # data iterators are single-consumer by contract: the prefetch
        # tier hands the whole iterator to ONE worker thread, it is
        # never advanced and reset concurrently
        self.cursor = -self.batch_size    # graftlint: disable=JG011

    def reset(self):
        if self.shuffle:
            _random.host_rng().shuffle(self.idx)
        if self.last_batch_handle == "roll_over" \
                and self.cursor > self.num_data:
            overhang = (self.cursor % self.num_data) % self.batch_size
            self.cursor = overhang - self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        return _timed_batch(self._produce_next)

    def _produce_next(self):
        if not self.iter_next():
            raise StopIteration
        return DataBatch(data=self.getdata(), label=self.getlabel(),
                         pad=self.getpad(), index=None)

    def _window(self):
        """Index array for the current batch, wrapping the tail if short."""
        lo = self.cursor
        hi = lo + self.batch_size
        if hi <= self.num_data:
            return self.idx[lo:hi]
        wrap = hi - self.num_data
        return np.concatenate([self.idx[lo:], self.idx[:wrap]])

    def _slice(self, source):
        if self.cursor >= self.num_data:
            raise RuntimeError("DataIter needs reset.")
        sel = self._window()
        picked = []
        for name, _ in source:
            host = self._np_cache[name]
            picked.append(nd.array(host[sel], dtype=host.dtype))
        return picked

    def getdata(self):
        return self._slice(self.data)

    def getlabel(self):
        return self._slice(self.label)

    def getpad(self):
        overrun = self.cursor + self.batch_size - self.num_data
        if self.last_batch_handle == "pad" and overrun > 0:
            return overrun
        return 0

    def get_checkpoint_state(self):
        """Cursor + the epoch's shuffle permutation: restoring both (with
        the global host RNG snapshotted separately by the checkpoint
        manager) makes the remaining batch sequence of this epoch — and
        every reshuffle after it — bitwise-identical."""
        return {"kind": "NDArrayIter", "cursor": int(self.cursor),
                "idx": np.asarray(self.idx).copy()}

    def set_checkpoint_state(self, state):
        idx = np.asarray(state["idx"]).copy()
        if idx.shape[0] != self.idx.shape[0]:
            # dataset changed size between save and resume: raising here
            # routes into the checkpoint manager's non-fatal skip (the
            # stream restarts) instead of silently slicing garbage
            # batches from a stale permutation
            raise ValueError(
                "checkpoint cursor covers %d samples, iterator has %d"
                % (idx.shape[0], self.idx.shape[0]))
        self.idx = idx
        self.num_data = idx.shape[0]
        self.cursor = int(state["cursor"])


class _WrappedArrayIter(DataIter):
    """Shared shell for CSVIter/MNISTIter: parse files once, then delegate
    to an inner NDArrayIter."""

    def __init__(self, data, label, batch_size, **iter_kwargs):
        super().__init__(batch_size)
        self._inner = NDArrayIter(data, label, batch_size, **iter_kwargs)
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()

    def get_checkpoint_state(self):
        return self._inner.get_checkpoint_state()

    def set_checkpoint_state(self, state):
        self._inner.set_checkpoint_state(state)


class CSVIter(_WrappedArrayIter):
    """Comma-separated-file iterator (ref src/io/iter_csv.cc:150)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        table = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        table = table.reshape((-1,) + tuple(data_shape))
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = np.zeros((table.shape[0],), dtype=np.float32)
        super().__init__(table, label, batch_size,
                         last_batch_handle="roll_over" if round_batch
                         else "pad")


def _read_idx_file(path):
    """Parse an MNIST idx file: magic 2051 = images, 2049 = labels."""
    with open(path, "rb") as fh:
        magic, count = struct.unpack(">ii", fh.read(8))
        if magic == 2051:
            rows, cols = struct.unpack(">ii", fh.read(8))
            return np.frombuffer(fh.read(), dtype=np.uint8) \
                .reshape(count, rows, cols)
        if magic == 2049:
            return np.frombuffer(fh.read(), dtype=np.uint8).reshape(count)
        raise MXNetError("bad idx magic %d in %s" % (magic, path))


class MNISTIter(_WrappedArrayIter):
    """MNIST idx-format iterator (ref src/io/iter_mnist.cc:259).

    Requires the standard idx files on disk; tests fall back to
    test_utils.get_mnist_iterator's synthetic digits when absent.
    """

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128, shuffle=True,
                 flat=False, silent=False, seed=0, input_shape=None, **kwargs):
        if not os.path.exists(image):
            raise MXNetError("MNIST file %s not found" % image)
        pixels = _read_idx_file(image).astype(np.float32) / 255.0
        digits = _read_idx_file(label).astype(np.float32)
        if flat:
            pixels = pixels.reshape(pixels.shape[0], -1)
        else:
            pixels = pixels.reshape(pixels.shape[0], 1, 28, 28)
        super().__init__(pixels, digits, batch_size, shuffle=shuffle)


def _parse_libsvm(path, expect_dim=None):
    """Parse a libsvm file → (dense feature matrix, labels).

    Format per line: ``label idx:val idx:val ...`` (ref
    src/io/iter_libsvm.cc:200). Indices are 0-based like the reference's
    LibSVMIter contract.
    """
    labels, rows = [], []
    max_idx = -1
    with open(path) as fh:
        for line in fh:
            cells = line.split()
            if not cells:
                continue
            labels.append(float(cells[0]))
            row = {}
            for tok in cells[1:]:
                idx, _, val = tok.partition(":")
                idx = int(idx)
                row[idx] = float(val)
                max_idx = max(max_idx, idx)
            rows.append(row)
    dim = expect_dim if expect_dim is not None else max_idx + 1
    data = np.zeros((len(rows), dim), np.float32)
    for i, row in enumerate(rows):
        for idx, val in row.items():
            if idx < dim:
                data[i, idx] = val
    return data, np.asarray(labels, np.float32)


class LibSVMIter(_WrappedArrayIter):
    """Sparse-format text iterator (ref src/io/iter_libsvm.cc:200).

    Batches come out as CSRNDArray data (the framework's sparse handle);
    an optional separate label file supplies multi-dim labels.
    """

    def __init__(self, data_libsvm, data_shape, label_libsvm=None,
                 label_shape=None, batch_size=1, round_batch=True, **kwargs):
        dim = int(np.prod(data_shape))
        data, labels = _parse_libsvm(data_libsvm, expect_dim=dim)
        if label_libsvm is not None:
            lab_dim = int(np.prod(label_shape)) if label_shape else None
            lab_data, _ = _parse_libsvm(label_libsvm, expect_dim=lab_dim)
            labels = lab_data.reshape(
                (-1,) + tuple(label_shape)) if label_shape else lab_data
        super().__init__(data, labels, batch_size,
                         last_batch_handle="roll_over" if round_batch
                         else "pad")

    def next(self):
        batch = self._inner.next()
        from .ndarray import sparse as _sp
        batch.data = [_sp.csr_matrix(d) for d in batch.data]
        return batch
