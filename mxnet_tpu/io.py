"""Data iterators (parity: reference python/mxnet/io.py:42-932 + src/io/).

TPU-native notes: iterators produce host batches that land on device at
``forward`` time; ``PrefetchingIter`` double-buffers with a background
thread (the reference's prefetcher thread, ``src/io/iter_prefetcher.h``).
The heavyweight C++ decode pipeline (ImageRecordIter) lives in
``image.py``/``recordio.py``.
"""
from __future__ import annotations

import os
import struct
import threading

import numpy as np

from .base import MXNetError
from . import ndarray as nd
from .ndarray import NDArray

__all__ = ["DataDesc", "DataBatch", "DataIter", "ResizeIter",
           "PrefetchingIter", "NDArrayIter", "CSVIter", "MNISTIter"]


class DataDesc:
    """Name+shape+dtype+layout descriptor (reference io.py:42)."""

    def __init__(self, name, shape, dtype=np.float32, layout="NCHW"):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = dtype
        self.layout = layout

    def __repr__(self):
        return "DataDesc[%s,%s,%s,%s]" % (self.name, self.shape, self.dtype,
                                          self.layout)

    def __iter__(self):  # tuple-compat: (name, shape)
        return iter((self.name, self.shape))

    def __getitem__(self, i):
        return (self.name, self.shape)[i]

    def __len__(self):
        return 2

    def __eq__(self, other):
        if isinstance(other, (tuple, list)):
            return (self.name, self.shape) == tuple(other)
        return (isinstance(other, DataDesc) and self.name == other.name
                and self.shape == other.shape)

    @staticmethod
    def get_batch_axis(layout):
        if layout is None:
            return 0
        return layout.find("N")

    @staticmethod
    def get_list(shapes, types=None):
        if types is not None:
            type_dict = dict(types)
            return [DataDesc(x[0], x[1], type_dict[x[0]]) for x in shapes]
        return [DataDesc(x[0], x[1]) for x in shapes]


class DataBatch:
    """One minibatch (reference io.py:115)."""

    def __init__(self, data, label=None, pad=None, index=None,
                 bucket_key=None, provide_data=None, provide_label=None):
        if data is not None and not isinstance(data, (list, tuple)):
            data = [data]
        if label is not None and not isinstance(label, (list, tuple)):
            label = [label]
        self.data = data
        self.label = label
        self.pad = pad
        self.index = index
        self.bucket_key = bucket_key
        self.provide_data = provide_data
        self.provide_label = provide_label


class DataIter:
    """Base iterator (reference io.py:176)."""

    def __init__(self, batch_size=0):
        self.batch_size = batch_size

    def __iter__(self):
        return self

    def reset(self):
        pass

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=self.getindex())
        raise StopIteration

    def __next__(self):
        return self.next()

    def iter_next(self):
        raise NotImplementedError()

    def getdata(self):
        raise NotImplementedError()

    def getlabel(self):
        raise NotImplementedError()

    def getindex(self):
        return None

    def getpad(self):
        raise NotImplementedError()


class ResizeIter(DataIter):
    """Resize an iterator to a fixed number of batches (reference io.py:264)."""

    def __init__(self, data_iter, size, reset_internal=True):
        super().__init__()
        self.data_iter = data_iter
        self.size = size
        self.reset_internal = reset_internal
        self.cur = 0
        self.current_batch = None
        self.provide_data = data_iter.provide_data
        self.provide_label = data_iter.provide_label
        self.batch_size = data_iter.batch_size
        if hasattr(data_iter, "default_bucket_key"):
            self.default_bucket_key = data_iter.default_bucket_key

    def reset(self):
        self.cur = 0
        if self.reset_internal:
            self.data_iter.reset()

    def iter_next(self):
        if self.cur == self.size:
            return False
        try:
            self.current_batch = self.data_iter.next()
        except StopIteration:
            self.data_iter.reset()
            self.current_batch = self.data_iter.next()
        self.cur += 1
        return True

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


class PrefetchingIter(DataIter):
    """Thread-backed double-buffering prefetcher (reference io.py:343)."""

    def __init__(self, iters, rename_data=None, rename_label=None):
        super().__init__()
        if not isinstance(iters, list):
            iters = [iters]
        self.n_iter = len(iters)
        assert self.n_iter > 0
        self.iters = iters
        self.rename_data = rename_data
        self.rename_label = rename_label
        self.batch_size = self.provide_data[0][1][0]
        self.data_ready = [threading.Event() for _ in range(self.n_iter)]
        self.data_taken = [threading.Event() for _ in range(self.n_iter)]
        for e in self.data_taken:
            e.set()
        self.started = True
        self.current_batch = [None for _ in range(self.n_iter)]
        self.next_batch = [None for _ in range(self.n_iter)]

        def prefetch_func(self, i):
            while True:
                self.data_taken[i].wait()
                if not self.started:
                    break
                try:
                    self.next_batch[i] = self.iters[i].next()
                except StopIteration:
                    self.next_batch[i] = None
                self.data_taken[i].clear()
                self.data_ready[i].set()

        self.prefetch_threads = [
            threading.Thread(target=prefetch_func, args=[self, i], daemon=True)
            for i in range(self.n_iter)]
        for thread in self.prefetch_threads:
            thread.start()

    def __del__(self):
        self.started = False
        for e in self.data_taken:
            e.set()

    @property
    def provide_data(self):
        if self.rename_data is None:
            return sum([i.provide_data for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(r[x[0]], x[1])
                     for x in i.provide_data]
                    for r, i in zip(self.rename_data, self.iters)], [])

    @property
    def provide_label(self):
        if self.rename_label is None:
            return sum([i.provide_label for i in self.iters], [])
        return sum([[DataDesc(r[x.name], x.shape, x.dtype)
                     if isinstance(x, DataDesc) else DataDesc(r[x[0]], x[1])
                     for x in i.provide_label]
                    for r, i in zip(self.rename_label, self.iters)], [])

    def reset(self):
        for e in self.data_ready:
            e.wait()
        for i in self.iters:
            i.reset()
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()

    def iter_next(self):
        for e in self.data_ready:
            e.wait()
        if self.next_batch[0] is None:
            return False
        self.current_batch = DataBatch(
            sum([batch.data for batch in self.next_batch], []),
            sum([batch.label for batch in self.next_batch], []),
            self.next_batch[0].pad, self.next_batch[0].index,
            provide_data=self.provide_data, provide_label=self.provide_label)
        for e in self.data_ready:
            e.clear()
        for e in self.data_taken:
            e.set()
        return True

    def next(self):
        if self.iter_next():
            return self.current_batch
        raise StopIteration

    def getdata(self):
        return self.current_batch.data

    def getlabel(self):
        return self.current_batch.label

    def getindex(self):
        return self.current_batch.index

    def getpad(self):
        return self.current_batch.pad


def _init_data(data, allow_empty, default_name):
    if data is None:
        data = []
    if isinstance(data, (np.ndarray, NDArray)):
        data = [data]
    if isinstance(data, list):
        if not allow_empty:
            assert len(data) > 0
        if len(data) == 1:
            data = {default_name: data[0]}
        else:
            data = {"_%d_%s" % (i, default_name): d
                    for i, d in enumerate(data)}
    if not isinstance(data, dict):
        raise TypeError("Input must be NDArray, numpy.ndarray, a list of "
                        "them or dict with them as values")
    out = []
    for k, v in data.items():
        if not isinstance(v, NDArray):
            v = nd.array(np.asarray(v), dtype=np.asarray(v).dtype
                         if np.asarray(v).dtype != np.float64 else np.float32)
        out.append((k, v))
    return out


class NDArrayIter(DataIter):
    """Iterate over in-memory arrays (reference io.py:516)."""

    def __init__(self, data, label=None, batch_size=1, shuffle=False,
                 last_batch_handle="pad", data_name="data",
                 label_name="softmax_label"):
        super().__init__(batch_size)
        self.data = _init_data(data, allow_empty=False, default_name=data_name)
        self.label = _init_data(label, allow_empty=True,
                                default_name=label_name)
        self.idx = np.arange(self.data[0][1].shape[0])
        if shuffle:
            np.random.shuffle(self.idx)
        if last_batch_handle == "discard":
            new_n = self.data[0][1].shape[0] - self.data[0][1].shape[0] % batch_size
            self.idx = self.idx[:new_n]
        self.data_list = [x[1] for x in self.data] + [x[1] for x in self.label]
        self.num_source = len(self.data_list)
        self.num_data = self.idx.shape[0]
        assert self.num_data >= batch_size, \
            "batch_size needs to be smaller than data size."
        self.cursor = -batch_size
        self.batch_size = batch_size
        self.last_batch_handle = last_batch_handle
        self.shuffle = shuffle
        self._np_cache = {k: v.asnumpy() for k, v in self.data + self.label}

    @property
    def provide_data(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype) for k, v in self.data]

    @property
    def provide_label(self):
        return [DataDesc(k, tuple([self.batch_size] + list(v.shape[1:])),
                         v.dtype) for k, v in self.label]

    def hard_reset(self):
        self.cursor = -self.batch_size

    def reset(self):
        if self.shuffle:
            np.random.shuffle(self.idx)
        if (self.last_batch_handle == "roll_over"
                and self.cursor > self.num_data):
            self.cursor = -self.batch_size + (self.cursor % self.num_data) % \
                self.batch_size
        else:
            self.cursor = -self.batch_size

    def iter_next(self):
        self.cursor += self.batch_size
        return self.cursor < self.num_data

    def next(self):
        if self.iter_next():
            return DataBatch(data=self.getdata(), label=self.getlabel(),
                             pad=self.getpad(), index=None)
        raise StopIteration

    def _getdata(self, data_source):
        assert self.cursor < self.num_data, "DataIter needs reset."
        out = []
        for k, _ in data_source:
            npy = self._np_cache[k]
            if self.cursor + self.batch_size <= self.num_data:
                sel = self.idx[self.cursor:self.cursor + self.batch_size]
                out.append(nd.array(npy[sel], dtype=npy.dtype))
            else:
                pad = self.batch_size - self.num_data + self.cursor
                sel = np.concatenate([self.idx[self.cursor:],
                                      self.idx[:pad]])
                out.append(nd.array(npy[sel], dtype=npy.dtype))
        return out

    def getdata(self):
        return self._getdata(self.data)

    def getlabel(self):
        return self._getdata(self.label)

    def getpad(self):
        if (self.last_batch_handle == "pad"
                and self.cursor + self.batch_size > self.num_data):
            return self.cursor + self.batch_size - self.num_data
        return 0


class CSVIter(DataIter):
    """CSV file iterator (reference src/io/iter_csv.cc:150)."""

    def __init__(self, data_csv, data_shape, label_csv=None, label_shape=(1,),
                 batch_size=1, round_batch=True, **kwargs):
        super().__init__(batch_size)
        data = np.loadtxt(data_csv, delimiter=",", dtype=np.float32)
        data = data.reshape((-1,) + tuple(data_shape))
        label = None
        if label_csv is not None:
            label = np.loadtxt(label_csv, delimiter=",", dtype=np.float32)
            label = label.reshape((-1,) + tuple(label_shape))
            if label_shape == (1,):
                label = label.reshape(-1)
        else:
            label = np.zeros((data.shape[0],), dtype=np.float32)
        self._inner = NDArrayIter(data, label, batch_size,
                                  last_batch_handle="roll_over"
                                  if round_batch else "pad")
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()


def _read_idx_images(path):
    with open(path, "rb") as f:
        magic, n = struct.unpack(">ii", f.read(8))
        if magic == 2051:
            rows, cols = struct.unpack(">ii", f.read(8))
            return np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows, cols)
        if magic == 2049:
            return np.frombuffer(f.read(), dtype=np.uint8).reshape(n)
        raise MXNetError("bad idx magic %d in %s" % (magic, path))


class MNISTIter(DataIter):
    """MNIST idx-format iterator (reference src/io/iter_mnist.cc:259).

    Reads the standard idx files if present; raises otherwise (tests use
    test_utils.get_mnist_iterator which falls back to synthetic digits).
    """

    def __init__(self, image="train-images-idx3-ubyte",
                 label="train-labels-idx1-ubyte", batch_size=128, shuffle=True,
                 flat=False, silent=False, seed=0, input_shape=None, **kwargs):
        super().__init__(batch_size)
        if not os.path.exists(image):
            raise MXNetError("MNIST file %s not found" % image)
        imgs = _read_idx_images(image).astype(np.float32) / 255.0
        lbls = _read_idx_images(label).astype(np.float32)
        if flat:
            imgs = imgs.reshape(imgs.shape[0], -1)
        else:
            imgs = imgs.reshape(imgs.shape[0], 1, 28, 28)
        self._inner = NDArrayIter(imgs, lbls, batch_size, shuffle=shuffle)
        self.provide_data = self._inner.provide_data
        self.provide_label = self._inner.provide_label

    def reset(self):
        self._inner.reset()

    def next(self):
        return self._inner.next()
