"""Define-by-run autograd: record/pause scopes + tape backward.

Parity surface: reference ``python/mxnet/autograd.py`` (record/pause/
train_mode/predict_mode at :121-194, mark_variables :196, backward :247,
grad :274) over ``src/imperative/imperative.cc`` (RecordOp :182, Backward
:357).

TPU-native redesign: instead of re-building an NNVM gradient graph, every
recorded op captures a ``jax.vjp`` closure at execution time (the forward
runs *once*, inside vjp tracing, so there is no double compute); backward is
a reverse sweep over the tape accumulating cotangents.  Ops whose reference
gradient is semantic rather than mathematical (SoftmaxOutput & friends)
registered a ``custom_vjp`` and bypass jax.vjp.
"""
from __future__ import annotations

import threading

import jax.numpy as jnp

from .base import MXNetError

__all__ = ["record", "pause", "train_mode", "predict_mode", "is_recording",
           "is_training", "mark_variables", "backward", "grad", "get_symbol",
           "set_recording", "set_training", "set_grad_ready_hook"]


# -- grad-ready notification (comm/compute overlap) -------------------------
#
# The tape sweep finalizes each marked variable's gradient the moment its
# LAST consumer has been processed (not in one batch at the end), and
# fires this hook with the finalized variable.  mxnet_tpu.gluon.overlap
# installs the hook to dispatch a gradient bucket's reduce as an engine
# task while backward is still computing earlier layers — DDP-style
# comm/compute overlap.  One module-global read when no hook is set.

_GRAD_READY_HOOK = None


def set_grad_ready_hook(hook):
    """Install (or with None, remove) the grad-ready listener; returns
    the previous hook.  The hook receives the marked *data* NDArray
    whose ``_grad`` buffer has just been finalized by backward."""
    global _GRAD_READY_HOOK
    prev, _GRAD_READY_HOOK = _GRAD_READY_HOOK, hook
    return prev


class _State(threading.local):
    def __init__(self):
        self.recording = False
        self.training = False
        self.tape = []


_STATE = _State()


def is_recording():
    return _STATE.recording


def is_training():
    return _STATE.training


def set_recording(flag):
    prev = _STATE.recording
    _STATE.recording = bool(flag)
    return prev


def set_training(flag):
    prev = _STATE.training
    _STATE.training = bool(flag)
    return prev


class _RecordingScope:
    def __init__(self, recording, training):
        self._rec, self._train = recording, training

    def __enter__(self):
        self._prev_rec = _STATE.recording
        self._prev_train = _STATE.training
        if self._rec is not None:
            if self._rec and not _STATE.recording:
                _clear_tape()  # fresh outermost recording session
            _STATE.recording = self._rec
        if self._train is not None:
            _STATE.training = self._train
        return self

    def __exit__(self, *exc):
        _STATE.recording = self._prev_rec
        _STATE.training = self._prev_train

    def __call__(self, fn):
        def wrapped(*a, **kw):
            with self.__class__(self._rec, self._train):
                return fn(*a, **kw)
        return wrapped


def record(train_mode=True):  # noqa: A002 - reference name
    return _RecordingScope(True, train_mode)


def pause(train_mode=False):
    return _RecordingScope(False, train_mode)


def train_mode():
    return _RecordingScope(None, True)


def predict_mode():
    return _RecordingScope(None, False)


class TapeNode:
    """One recorded op invocation."""
    __slots__ = ("op", "attrs", "inputs", "outputs", "diff_idx", "vjp_fn",
                 "custom_bwd", "in_vals", "out_vals")

    def __init__(self, op, attrs, inputs, outputs, diff_idx, vjp_fn=None,
                 custom_bwd=None, in_vals=None, out_vals=None):
        self.op, self.attrs = op, attrs
        self.inputs, self.outputs = inputs, outputs
        self.diff_idx = diff_idx
        self.vjp_fn = vjp_fn
        self.custom_bwd = custom_bwd
        self.in_vals, self.out_vals = in_vals, out_vals


def _clear_tape():
    for node in _STATE.tape:
        for o in node.outputs:
            o._tape_node = None
    _STATE.tape = []


def _finalize_marked(v, g):
    """Write one marked variable's accumulated gradient into its
    attached buffer (identical semantics to the reference end-of-sweep
    batch write) and fire the grad-ready hook.  ``g is None`` — the
    variable received no contribution this backward — writes nothing
    and stays stale, exactly like before."""
    if v._grad is None or g is None:
        return
    if v._grad_req == "add":
        v._grad._set_data(v._grad._data + g)
    elif v._grad_req != "null":
        v._grad._set_data(jnp.broadcast_to(g, v._grad.shape).astype(
            v._grad.dtype) if g.shape != tuple(v._grad.shape)
            else g.astype(v._grad.dtype))
    if v._grad_req != "null":
        v._fresh_grad = True  # Trainer.step stale-grad tracking
        hook = _GRAD_READY_HOOK
        if hook is not None:
            hook(v)


def append_node(node):
    _STATE.tape.append(node)


def mark_variables(variables, gradients, grad_reqs="write"):
    """Attach gradient buffers (reference imperative.cc:112)."""
    if isinstance(grad_reqs, str):
        grad_reqs = [grad_reqs] * len(variables)
    for v, g, req in zip(variables, gradients, grad_reqs):
        v._grad = g
        v._grad_req = req
        v._marked = True


def _backward_impl(heads, head_grads=None, retain_graph=False,
                   train_mode=True, variables=None):
    from .ndarray import NDArray, _wrap
    heads = heads if isinstance(heads, (list, tuple)) else [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]

    grad_map = {}
    keepalive = {}
    for h, hg in zip(heads, head_grads):
        if getattr(h, "_tape_node", None) is None and not getattr(h, "_marked", False):
            raise MXNetError(
                "cannot differentiate a head that is not in a recorded "
                "computational graph (did you run inside autograd.record()?)")
        g = jnp.ones_like(h._data) if hg is None else hg._data
        grad_map[id(h)] = grad_map.get(id(h), 0) + g
        keepalive[id(h)] = h

    # Incremental finalization (comm/compute overlap): a marked
    # variable's accumulated gradient can no longer change once the
    # node at its SMALLEST consumer index has been processed — the
    # reverse sweep visits indices in decreasing order, so that node is
    # its last contributor.  Writing the buffer right there (instead of
    # one batch at the end) lets the grad-ready hook start a gradient
    # bucket's reduce while the sweep is still computing earlier
    # layers' gradients.  Heads are excluded: a marked head's seed
    # gradient is outside the consumer bookkeeping.
    tape = _STATE.tape
    head_ids = {id(h) for h in heads}
    final_at = {}            # tape index -> [marked vars final there]
    claimed = set()
    for idx, node in enumerate(tape):
        for pos in node.diff_idx:
            inp = node.inputs[pos]
            key = id(inp)
            if key in claimed or key in head_ids \
                    or not getattr(inp, "_marked", False):
                continue
            claimed.add(key)
            final_at.setdefault(idx, []).append(inp)

    late = {}                # finalized after the sweep (heads, leftovers)
    for idx in range(len(tape) - 1, -1, -1):
        node = tape[idx]
        if any(id(o) in grad_map for o in node.outputs):
            out_grads = tuple(
                grad_map.get(id(o), jnp.zeros_like(o._data))
                for o in node.outputs)
            if node.custom_bwd is not None:
                all_in_grads = node.custom_bwd(out_grads, node.in_vals,
                                               node.out_vals, node.attrs)
                in_grads = [all_in_grads[i] for i in node.diff_idx]
            else:
                in_grads = node.vjp_fn(out_grads)
            for pos, g in zip(node.diff_idx, in_grads):
                inp = node.inputs[pos]
                key = id(inp)
                keepalive[key] = inp
                if key in grad_map:
                    grad_map[key] = grad_map[key] + g
                else:
                    grad_map[key] = g
        for v in final_at.get(idx, ()):
            _finalize_marked(v, grad_map.get(id(v)))

    for h in heads:
        if getattr(h, "_marked", False):
            late[id(h)] = h
    for key, v in late.items():
        _finalize_marked(v, grad_map.get(key))

    result = None
    if variables is not None:
        result = []
        for v in variables:
            if id(v) not in grad_map:
                raise MXNetError("one of the requested variables is not part "
                                 "of the recorded graph")
            result.append(_wrap(grad_map[id(v)], v.context))
    if not retain_graph:
        _clear_tape()
    return result


def backward(heads, head_grads=None, retain_graph=False, train_mode=True):
    return _backward_impl(heads, head_grads, retain_graph, train_mode)


class _GradOp:
    """Sentinel op for tape nodes produced by grad(create_graph=True)."""
    name = "_grad"


def _pure_replay(variables, heads, train_mode):
    """Rebuild the recorded computation as a pure jax function
    ``f(var_vals tuple) -> head_vals tuple`` by replaying the tape.

    Stochastic ops replay with fresh RNG keys — second-order grads through
    dropout-style ops use a new mask, like re-running the forward would.
    """
    from . import random as _random
    tape = list(_STATE.tape)

    def f(var_vals):
        env = {id(v): val for v, val in zip(variables, var_vals)}
        for node in tape:
            ins = [env.get(id(i), i._data) for i in node.inputs]
            rng = _random.next_key() if getattr(node.op, "needs_rng", False) \
                else None
            outs = node.op.traceable(node.attrs, train_mode=train_mode,
                                     rng=rng)(*ins)
            for o, val in zip(node.outputs, outs):
                env[id(o)] = val
        return tuple(env.get(id(h), h._data) for h in heads)

    return f


def _grad_create_graph(heads, variables, head_grads, train_mode):
    """Higher-order path: gradients come out as recorded tape outputs, so
    they can be differentiated again (ref autograd.py:274 create_graph)."""
    import jax
    from .ndarray import _wrap

    heads = heads if isinstance(heads, (list, tuple)) else [heads]
    if head_grads is None:
        head_grads = [None] * len(heads)
    elif not isinstance(head_grads, (list, tuple)):
        head_grads = [head_grads]
    cotangents = tuple(
        jnp.ones_like(h._data) if hg is None else hg._data
        for h, hg in zip(heads, head_grads))

    forward = _pure_replay(variables, heads, train_mode)

    def grad_of_vars(var_vals):
        _, pullback = jax.vjp(forward, var_vals)
        return pullback(cotangents)[0]

    var_vals = tuple(v._data for v in variables)
    grad_vals, pullback2 = jax.vjp(grad_of_vars, var_vals)
    outputs = [_wrap(g, v.context) for g, v in zip(grad_vals, variables)]

    if is_recording():
        node = TapeNode(_GradOp(), {}, list(variables), outputs,
                        list(range(len(variables))),
                        vjp_fn=lambda gouts: pullback2(tuple(gouts))[0])
        for o in outputs:
            o._tape_node = node
        append_node(node)
    return outputs


def grad(heads, variables, head_grads=None, retain_graph=None,
         create_graph=False, train_mode=True):
    """Compute grads of heads w.r.t. variables (reference autograd.py:274).

    ``create_graph=True`` returns gradients that are themselves recorded,
    so a further ``backward()``/``grad()`` differentiates through them.
    """
    variables = variables if isinstance(variables, (list, tuple)) else [variables]
    if retain_graph is None:
        retain_graph = create_graph
    if create_graph:
        return _grad_create_graph(heads, variables, head_grads, train_mode)
    return _backward_impl(heads, head_grads, retain_graph, train_mode,
                          variables=variables)


def get_symbol(x):
    """Trace-back symbol extraction (reference autograd.py:351).

    Returns a Symbol describing the recorded computation that produced x.
    """
    from .symbol import Symbol
    node = getattr(x, "_tape_node", None)
    if node is None:
        raise MXNetError("array is not an output of a recorded computation")
    from . import symbol as _sym

    memo = {}

    def build(arr):
        key = id(arr)
        if key in memo:
            return memo[key]
        n = getattr(arr, "_tape_node", None)
        if n is None:
            s = _sym.var(getattr(arr, "name", None) or "var%d" % len(memo))
        else:
            ins = [build(i) for i in n.inputs]
            attrs = {k: v for k, v in n.attrs.items()}
            s = Symbol._from_op(n.op.name, ins, attrs)
            idx = n.outputs.index(arr) if arr in n.outputs else 0
            s = s[idx] if len(n.outputs) > 1 else s
        memo[key] = s
        return s

    return build(x)
