"""Base utilities: errors, dtype mapping, registries.

Reimagines the roles of the reference's ``python/mxnet/base.py`` (532 LoC ctypes
bridge, ``include/mxnet/base.h``) for a JAX/XLA-backed framework: there is no C
ABI to bridge, so this module only carries the pieces with user-visible
semantics — error type, dtype codes (``mshadow/base.h`` type enum, used by the
NDArray serialization format), and the string-keyed registries that back
operator/optimizer/initializer/metric lookup (``dmlc::Registry``).
"""
from __future__ import annotations

import numpy as np

__all__ = [
    "MXNetError", "NotSupportedForSparseNDArray", "mx_real_t", "mx_uint",
    "string_types", "numeric_types", "integer_types",
    "DTYPE_TO_CODE", "CODE_TO_DTYPE", "dtype_np", "dtype_code", "dtype_name",
    "Registry",
]


class MXNetError(Exception):
    """Error raised by the framework (parity with ``mxnet.base.MXNetError``)."""


class NotSupportedForSparseNDArray(MXNetError):
    def __init__(self, function, alias, *args):
        super().__init__(
            "Function {}{} is not supported for sparse NDArray".format(
                function.__name__, " (alias %s)" % alias if alias else ""))


mx_real_t = np.float32
mx_uint = np.uint32
string_types = (str,)
numeric_types = (float, int, np.generic)
integer_types = (int, np.integer)

# mshadow type codes (reference mshadow/base.h TypeFlag) — load-bearing for the
# binary .params / NDArray save format (src/ndarray/ndarray.cc:821).
DTYPE_TO_CODE = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.float16): 2,
    np.dtype(np.uint8): 3,
    np.dtype(np.int32): 4,
    np.dtype(np.int8): 5,
    np.dtype(np.int64): 6,
    # TPU-native extension: bfloat16 gets a code outside the reference range.
    np.dtype("bfloat16") if hasattr(np, "bfloat16") else "bfloat16": 7,
}


def _bfloat16_dtype():
    import ml_dtypes
    return np.dtype(ml_dtypes.bfloat16)


try:
    _BF16 = _bfloat16_dtype()
    DTYPE_TO_CODE = {
        np.dtype(np.float32): 0, np.dtype(np.float64): 1,
        np.dtype(np.float16): 2, np.dtype(np.uint8): 3,
        np.dtype(np.int32): 4, np.dtype(np.int8): 5,
        np.dtype(np.int64): 6, _BF16: 7,
    }
except Exception:  # pragma: no cover
    _BF16 = None

CODE_TO_DTYPE = {v: k for k, v in DTYPE_TO_CODE.items()}


def dtype_np(dtype):
    """Normalize a user dtype spec (str/np.dtype/type) to np.dtype."""
    if dtype is None:
        return np.dtype(np.float32)
    if isinstance(dtype, str) and dtype == "bfloat16" and _BF16 is not None:
        return _BF16
    return np.dtype(dtype)


def dtype_code(dtype):
    return DTYPE_TO_CODE[dtype_np(dtype)]


def dtype_name(dtype):
    d = dtype_np(dtype)
    if _BF16 is not None and d == _BF16:
        return "bfloat16"
    return d.name


class Registry:
    """String-keyed object registry with alias support.

    Plays the role of ``dmlc::Registry`` / the Python-side ``mx.registry``
    (reference ``python/mxnet/registry.py``): optimizers, initializers,
    metrics, and operators all register here.
    """

    def __init__(self, kind):
        self.kind = kind
        self._store = {}

    def register(self, obj, name=None, aliases=()):
        key = (name or getattr(obj, "__name__", None) or str(obj)).lower()
        self._store[key] = obj
        for a in aliases:
            self._store[a.lower()] = obj
        return obj

    def get(self, name):
        key = name.lower()
        if key not in self._store:
            raise MXNetError(
                "Cannot find %s '%s'. Registered: %s"
                % (self.kind, name, sorted(self._store)))
        return self._store[key]

    def find(self, name):
        return self._store.get(name.lower())

    def names(self):
        return sorted(self._store)

    def create(self, spec, *args, **kwargs):
        """Create an instance from a name / (name, kwargs) / instance spec."""
        if isinstance(spec, str):
            return self.get(spec)(*args, **kwargs)
        return spec


def env_flag(name, default=False):
    """Read a boolean MXNET_* environment flag (ref dmlc::GetEnv use-sites;
    canonical list in docs/faq/env_var.md)."""
    import os
    raw = os.environ.get(name)
    if raw is None:
        return default
    return raw.strip().lower() not in ("", "0", "false", "off", "no")


def mirror_enabled():
    """MXNET_BACKWARD_DO_MIRROR: trade compute for memory by
    rematerialising forward activations during backward
    (ref src/executor/graph_executor.cc:281-304 mirror pass; here it maps
    to jax.checkpoint around the block's pure function)."""
    return env_flag("MXNET_BACKWARD_DO_MIRROR")
