"""Operator-level profiler with Chrome trace-event output.

Parity surface: reference ``python/mxnet/profiler.py:27-55`` +
``src/engine/profiler.{h,cc}`` (SURVEY §5.1): engine workers stamp each op
with ``OprExecStat{opr_name, start/end µs, thread_id, dev}`` and
``Profiler::DumpProfile`` emits Chrome trace-event JSON.

TPU-native redesign: there is no engine worker to instrument — eager ops
dispatch through ``ndarray.invoke`` and compiled graphs execute as one XLA
program.  So the profiler has two layers:

1. **Op events**: when running, the eager dispatch path and the Executor
   forward/backward record wall-clock spans per op / per program, dumped as
   Chrome ``traceEvents`` JSON — same file format the reference produces,
   loadable in chrome://tracing or Perfetto.
2. **Device profile**: ``start()/stop()`` also drive ``jax.profiler``
   (XPlane/TensorBoard) when a trace dir is configured, which is where
   real per-kernel TPU timing lives (XLA fuses ops, so per-op host spans
   are the honest analogue of the reference's engine stats).

The buffers themselves live in :mod:`mxnet_tpu.telemetry` — the runtime
telemetry plane (hierarchical spans, metrics registry, retrace watchdog)
shares one merged trace with this module, and ``bump()``/``counter()``
here are compatibility shims over its typed metrics registry.

Env autostart: ``MXNET_PROFILER_AUTOSTART=1`` (reference env_var.md:101).
"""
from __future__ import annotations

import os
import threading

from . import telemetry as _telemetry
from .telemetry import (bump, counter, counters, reset_counters,  # noqa: F401
                        now_us as _now_us)

__all__ = ["profiler_set_config", "set_config", "set_state", "dump_profile",
           "dump", "pause", "resume", "clear", "Marker",
           "bump", "counter", "counters", "reset_counters"]

_lock = threading.Lock()
# serializes the jax device-trace transition (flag + jax.profiler call as
# one unit) — held only on run/stop, never on the hot path
_jax_trace_lock = threading.Lock()
_state = {
    "mode": "symbolic",
    "filename": "profile.json",
    "running": False,
    "jax_trace_dir": None,
    "jax_tracing": False,
}


def profiler_set_config(mode="symbolic", filename="profile.json",
                        **kwargs):
    """Configure profiler (reference profiler.py:27).

    mode: 'symbolic' records Executor program spans only; 'all' also
    records eager op dispatches.  ``jax_trace_dir`` additionally captures
    an XLA device trace viewable in TensorBoard.
    """
    with _lock:
        _state["mode"] = mode
        _state["filename"] = filename
        _state["jax_trace_dir"] = kwargs.get("jax_trace_dir")
    _telemetry.clear_events()  # new config = new profiling session


set_config = profiler_set_config


def set_state(state="stop"):
    """'run' | 'stop' (reference profiler.py:40).

    Events accumulate across run/stop cycles (so ``pause``/``resume``
    exclude a window without losing prior spans); ``set_config`` or
    ``clear`` starts a fresh buffer.
    """
    run = state == "run"
    with _lock:
        _state["running"] = run
        tdir = _state["jax_trace_dir"]
        # mirror into telemetry under the same lock: concurrent run/stop
        # must not leave is_running() and trace_active() disagreeing
        _telemetry._set_profiler_running(run)
    # the jax_tracing flag and the jax.profiler side effect transition as
    # ONE unit under a dedicated lock: concurrent run/stop calls can
    # neither double-start the device trace nor stop it before the
    # in-flight start has actually run.  `running` is RE-READ inside the
    # lock — acting on this call's stale snapshot could start a device
    # trace after a later stop already won.
    with _jax_trace_lock:
        now_running = _state["running"]
        if now_running and tdir and not _state["jax_tracing"]:
            import jax
            jax.profiler.start_trace(tdir)
            _state["jax_tracing"] = True
        elif not now_running and _state["jax_tracing"]:
            import jax
            jax.profiler.stop_trace()
            _state["jax_tracing"] = False


def clear():
    """Drop all accumulated events."""
    _telemetry.clear_events()


def pause():
    set_state("stop")


def resume():
    set_state("run")


def is_running():
    return _state["running"]


def record_op(name, start_us, dur_us):
    """Called from the eager dispatch path (mode='all')."""
    if _state["running"] and _state["mode"] == "all":
        _telemetry.add_event(name, "operator", start_us, dur_us)


def record_program(name, start_us, dur_us):
    """Called from Executor forward/backward (any mode)."""
    if _state["running"]:
        _telemetry.add_event(name, "program", start_us, dur_us)


class Marker(_telemetry.span):
    """User annotation span: ``with profiler.Marker("data-load"): ...``

    Markers are telemetry spans: nested Markers record parent/depth and
    render as nested tracks, and they obey either gate (profiler running
    OR ``MXNET_TELEMETRY=1``).
    """

    def __init__(self, name, cat="user"):
        super().__init__(name, cat=cat)


def dump_profile(filename=None):
    """Write accumulated events as Chrome trace JSON
    (reference Profiler::DumpProfile, profiler.cc:127-192), including
    ``ph:"M"`` process/thread-name metadata so Perfetto labels tracks."""
    fname = filename or _state["filename"]
    return _telemetry.dump_chrome_trace(fname)


dump = dump_profile


if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    profiler_set_config(mode=os.environ.get("MXNET_PROFILER_MODE",
                                            "symbolic"))
    set_state("run")
