"""Operator-level profiler with Chrome trace-event output.

Parity surface: reference ``python/mxnet/profiler.py:27-55`` +
``src/engine/profiler.{h,cc}`` (SURVEY §5.1): engine workers stamp each op
with ``OprExecStat{opr_name, start/end µs, thread_id, dev}`` and
``Profiler::DumpProfile`` emits Chrome trace-event JSON.

TPU-native redesign: there is no engine worker to instrument — eager ops
dispatch through ``ndarray.invoke`` and compiled graphs execute as one XLA
program.  So the profiler has two layers:

1. **Op events** (this module): when running, the eager dispatch path and
   the Executor forward/backward record wall-clock spans per op / per
   program, dumped as Chrome ``traceEvents`` JSON — same file format the
   reference produces, loadable in chrome://tracing or Perfetto.
2. **Device profile**: ``start()/stop()`` also drive ``jax.profiler``
   (XPlane/TensorBoard) when a trace dir is configured, which is where
   real per-kernel TPU timing lives (XLA fuses ops, so per-op host spans
   are the honest analogue of the reference's engine stats).

Env autostart: ``MXNET_PROFILER_AUTOSTART=1`` (reference env_var.md:101).
"""
from __future__ import annotations

import json
import os
import threading
import time

__all__ = ["profiler_set_config", "set_config", "set_state", "dump_profile",
           "dump", "pause", "resume", "clear", "Marker",
           "bump", "counter", "counters", "reset_counters"]

_lock = threading.Lock()
_state = {
    "mode": "symbolic",
    "filename": "profile.json",
    "running": False,
    "jax_trace_dir": None,
    "jax_tracing": False,
}
_events = []          # finished spans: dicts in Chrome trace format
_counters = {}        # name -> monotonic int (program-call accounting)
_t0 = time.perf_counter()


def _now_us():
    return (time.perf_counter() - _t0) * 1e6


def profiler_set_config(mode="symbolic", filename="profile.json",
                        **kwargs):
    """Configure profiler (reference profiler.py:27).

    mode: 'symbolic' records Executor program spans only; 'all' also
    records eager op dispatches.  ``jax_trace_dir`` additionally captures
    an XLA device trace viewable in TensorBoard.
    """
    with _lock:
        _state["mode"] = mode
        _state["filename"] = filename
        _state["jax_trace_dir"] = kwargs.get("jax_trace_dir")
        _events.clear()  # new config = new profiling session


set_config = profiler_set_config


def set_state(state="stop"):
    """'run' | 'stop' (reference profiler.py:40).

    Events accumulate across run/stop cycles (so ``pause``/``resume``
    exclude a window without losing prior spans); ``set_config`` or
    ``clear`` starts a fresh buffer.
    """
    with _lock:
        run = state == "run"
        already_tracing = _state["jax_tracing"]
        _state["running"] = run
        tdir = _state["jax_trace_dir"]
    if run and tdir and not already_tracing:
        import jax
        jax.profiler.start_trace(tdir)
        _state["jax_tracing"] = True
    elif not run and already_tracing:
        import jax
        jax.profiler.stop_trace()
        _state["jax_tracing"] = False


def clear():
    """Drop all accumulated events."""
    with _lock:
        _events.clear()


def pause():
    set_state("stop")


def resume():
    set_state("run")


def is_running():
    return _state["running"]


def _record(name, cat, start_us, dur_us, tid=0):
    _events.append({"name": name, "cat": cat, "ph": "X",
                    "ts": start_us, "dur": dur_us,
                    "pid": os.getpid(), "tid": tid})


def record_op(name, start_us, dur_us):
    """Called from the eager dispatch path (mode='all')."""
    if _state["running"] and _state["mode"] == "all":
        _record(name, "operator", start_us, dur_us,
                tid=threading.get_ident() % 10000)


def record_program(name, start_us, dur_us):
    """Called from Executor forward/backward (any mode)."""
    if _state["running"]:
        _record(name, "program", start_us, dur_us,
                tid=threading.get_ident() % 10000)


def bump(name, n=1):
    """Increment a named monotonic counter.

    Counters are always on (an int add, no gating on ``set_state``):
    they are how tests and benches *prove* call-count claims — e.g. the
    fused Gluon Trainer step's "one XLA program per step" contract is
    gated on the ``xla_program_calls`` delta across a step.
    """
    with _lock:
        _counters[name] = _counters.get(name, 0) + n


def counter(name):
    """Current value of one counter (0 if never bumped)."""
    return _counters.get(name, 0)


def counters():
    """Snapshot of all counters."""
    with _lock:
        return dict(_counters)


def reset_counters():
    with _lock:
        _counters.clear()


class Marker:
    """User annotation span: ``with profiler.Marker("data-load"): ...``"""

    def __init__(self, name, cat="user"):
        self._name = name
        self._cat = cat

    def __enter__(self):
        self._start = _now_us()
        return self

    def __exit__(self, *exc):
        if _state["running"]:
            _record(self._name, self._cat, self._start,
                    _now_us() - self._start)


def dump_profile(filename=None):
    """Write accumulated events as Chrome trace JSON
    (reference Profiler::DumpProfile, profiler.cc:127-192)."""
    fname = filename or _state["filename"]
    with _lock:
        payload = {"traceEvents": list(_events),
                   "displayTimeUnit": "ms"}
    with open(fname, "w") as f:
        json.dump(payload, f)
    return fname


dump = dump_profile


if os.environ.get("MXNET_PROFILER_AUTOSTART", "0") == "1":
    profiler_set_config(mode=os.environ.get("MXNET_PROFILER_MODE",
                                            "symbolic"))
    set_state("run")
