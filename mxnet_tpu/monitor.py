"""Monitor: sample intermediate op outputs during Executor forward.

API parity with the reference ``python/mxnet/monitor.py:33`` + the executor
monitor callback (``GraphExecutor::SetMonitorCallback`` graph_executor.cc:120,
``ExecuteMonCallback`` :1380). On the TPU build an installed, *active*
monitor flips the Executor onto its eager node-by-node path for that batch —
a compiled XLA program has no per-op boundaries to tap — and off-interval
batches keep the fast compiled program.
"""
from __future__ import annotations

import re

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["Monitor"]


def _default_stat(x):
    """mean(|x|) — the reference's default statistic."""
    return x.abs().mean() if hasattr(x, "abs") else x


def _render(value):
    """Format one stat NDArray (or list thereof) as a tab-joined string."""
    items = value if isinstance(value, list) else [value]
    parts = []
    for v in items:
        if not isinstance(v, NDArray):
            raise MXNetError("the argument must be NDArray")
        if v.shape in ((), (1,)):
            parts.append(str(v.asnumpy().reshape(-1)[0]))
        else:
            parts.append(str(v.asnumpy()))
    return "\t".join(parts) + "\t"


class Monitor(object):
    """Collect per-op output statistics every ``interval`` batches.

    ``stat_func`` maps an output NDArray to its statistic; ``pattern``
    filters by output name; ``sort`` orders ``toc()`` results by name.
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval, self.sort = interval, sort
        self.stat_func = stat_func or _default_stat
        self.re_prog = re.compile(pattern)
        self.activated, self.queue = False, []
        self.step, self.exes = 0, []

        mon = self

        def stat_helper(name, arr):
            if mon.activated and mon.re_prog.match(name):
                mon.queue.append((mon.step, name, mon.stat_func(arr)))
        # The Executor polls is_active to decide whether this forward must
        # run node-by-node; keeping it a callable avoids a stale snapshot.
        stat_helper.is_active = lambda: mon.activated
        self.stat_helper = stat_helper

    def install(self, exe):
        """Attach this monitor's tap to an Executor (ref monitor.py:install)."""
        exe.set_monitor_callback(self.stat_helper)
        self.exes += [exe]

    def tic(self):
        """Arm collection if this step is on the interval; call pre-forward."""
        due = self.step % self.interval == 0
        self.step += 1
        if due:
            self.queue, self.activated = [], True

    def toc(self):
        """Disarm and drain: returns [(step, name, stat_string), ...]."""
        was_armed, self.activated = self.activated, False
        if not was_armed:
            return []
        drained = [(step, name, _render(val))
                   for step, name, val in self.queue]
        self.queue = []
        if self.sort:
            drained.sort(key=lambda row: row[1])
        return drained

    def toc_print(self):
        """Drain and pretty-print (ref monitor.py:toc_print)."""
        rows = self.toc()
        for step, name, stat in rows:
            print("Batch: {:7d} {:30s} {:s}".format(step, name, stat))
        return rows
