"""Monitor: tap every op output during Executor forward for debugging.

Parity surface: reference ``python/mxnet/monitor.py:33`` + executor monitor
callback (``GraphExecutor::SetMonitorCallback``, graph_executor.cc:120,
ExecuteMonCallback :1380).  On the TPU build, installing a monitor switches
the Executor to its eager node-by-node path so every intermediate value is
observable (the compiled XLA program has no per-op boundaries to tap).
"""
from __future__ import annotations

import re

from .base import MXNetError
from .ndarray import NDArray

__all__ = ["Monitor"]


class Monitor(object):
    """Collect per-op output statistics every ``interval`` batches.

    Parameters mirror the reference: ``stat_func`` maps NDArray -> NDArray
    stat (default: mean of |x|), ``pattern`` filters output names,
    ``sort`` orders results by name in ``toc()``.
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        if stat_func is None:
            def stat_func(x):
                return x.abs().mean() if hasattr(x, "abs") else x
        self.stat_func = stat_func
        self.interval = interval
        self.activated = False
        self.queue = []
        self.step = 0
        self.exes = []
        self.re_prog = re.compile(pattern)
        self.sort = sort

        def stat_helper(name, arr):
            if not self.activated or not self.re_prog.match(name):
                return
            self.queue.append((self.step, name, self.stat_func(arr)))
        # Executor consults is_active to decide whether THIS forward must
        # take the slow eager per-node path; off-interval batches stay on
        # the compiled program instead of paying eager speed for nothing.
        stat_helper.is_active = lambda: self.activated
        self.stat_helper = stat_helper

    def install(self, exe):
        """Attach to an Executor (reference monitor.py:install)."""
        exe.set_monitor_callback(self.stat_helper)
        self.exes.append(exe)

    def tic(self):
        """Start collecting if due this step (call before forward)."""
        if self.step % self.interval == 0:
            self.queue = []
            self.activated = True
        self.step += 1

    def toc(self):
        """Stop collecting; return [(step, name, stat_str), ...]."""
        if not self.activated:
            return []
        self.activated = False
        res = []
        for n, k, v_list in self.queue:
            if isinstance(v_list, NDArray):
                v_list = [v_list]
            assert isinstance(v_list, list)
            s = ""
            for v in v_list:
                if not isinstance(v, NDArray):
                    raise MXNetError("the argument must be NDArray")
                if v.shape == () or v.shape == (1,):
                    s += str(v.asnumpy().reshape(-1)[0]) + "\t"
                else:
                    s += str(v.asnumpy()) + "\t"
            res.append((n, k, s))
        if self.sort:
            res = sorted(res, key=lambda x: x[1])
        self.queue = []
        return res

    def toc_print(self):
        """Collect and print (reference monitor.py:toc_print)."""
        res = self.toc()
        for n, k, v in res:
            print("Batch: {:7d} {:30s} {:s}".format(n, k, v))
        return res
