"""Monitor: sample model statistics during training/forward.

API parity with the reference ``python/mxnet/monitor.py:33`` + the executor
monitor callback (``GraphExecutor::SetMonitorCallback`` graph_executor.cc:120,
``ExecuteMonCallback`` :1380).  Two modes on the TPU build:

* **Compiled mode** (``MXNET_MODEL_STATS`` set): the monitor reads the
  per-parameter statistics the fused trainer step already emits as an
  in-program side-output (``mxnet_tpu.model_stats``) — grad-norm²,
  weight-norm², update/weight ratio, grad absmax, and the loss — so the
  Executor/CachedOp stays on its one compiled program.  ``toc()`` rows
  are named ``<param>:<stat>`` (plus ``loss``) and still honor
  ``pattern=``/``sort=``.  ``stat_func`` does not apply (the statistics
  are fixed, computed on device).
* **Eager mode** (the default, and the only way to tap per-ACTIVATION
  outputs with ``pattern=``): an installed, *active* monitor flips the
  Executor onto its eager node-by-node path for that batch — THE SLOW
  PATH: a compiled XLA program has no per-op boundaries, so every
  monitored batch abandons whole-program compilation.  Off-interval
  batches keep the fast compiled program.

docs/OBSERVABILITY.md §model-health documents the stat definitions and
when to reach for which mode.
"""
from __future__ import annotations

import re

from . import model_stats as _mstats
from .base import MXNetError
from .lint import sanitizer as _sanitizer
from .ndarray import NDArray

__all__ = ["Monitor"]


def _default_stat(x):
    """mean(|x|) — the reference's default statistic."""
    return x.abs().mean() if hasattr(x, "abs") else x


def _render(value):
    """Format one stat NDArray (or list thereof) as a tab-joined string.

    The ``asnumpy`` reads are deliberate, observation-only host syncs:
    under MXNET_SANITIZE an active monitor formatting its own stats must
    not read as a sync-under-trace violation (``allow_host_sync``) — a
    genuine tracer leak still raises.
    """
    items = value if isinstance(value, list) else [value]
    parts = []
    with _sanitizer.allow_host_sync():
        for v in items:
            if not isinstance(v, NDArray):
                raise MXNetError("the argument must be NDArray")
            if v.shape in ((), (1,)):
                parts.append(str(v.asnumpy().reshape(-1)[0]))
            else:
                parts.append(str(v.asnumpy()))
    return "\t".join(parts) + "\t"


class Monitor(object):
    """Collect model statistics every ``interval`` batches.

    ``stat_func`` maps an output NDArray to its statistic (eager mode
    only); ``pattern`` filters by output/parameter name; ``sort`` orders
    ``toc()`` results by name.
    """

    def __init__(self, interval, stat_func=None, pattern=".*", sort=False):
        self.interval, self.sort = interval, sort
        self.stat_func = stat_func or _default_stat
        self.re_prog = re.compile(pattern)
        self.activated, self.queue = False, []
        self.step, self.exes = 0, []
        self._mark = 0          # compiled mode: recorder step at tic()

        mon = self

        def stat_helper(name, arr):
            if mon.activated and mon.re_prog.match(name):
                mon.queue.append((mon.step, name, mon.stat_func(arr)))
        # The Executor polls is_active to decide whether this forward must
        # run node-by-node; keeping it a callable avoids a stale snapshot.
        # Compiled mode never flips the executor eager: the statistics
        # come out of the training program itself.
        stat_helper.is_active = \
            lambda: mon.activated and not _mstats.enabled()
        self.stat_helper = stat_helper

    def install(self, exe):
        """Attach this monitor's tap to an Executor (ref monitor.py:install).
        A no-op source in compiled mode (is_active stays False there), but
        installing is still valid — flipping MXNET_MODEL_STATS off mid-run
        reactivates the eager taps on the next armed batch."""
        exe.set_monitor_callback(self.stat_helper)
        self.exes += [exe]

    def tic(self):
        """Arm collection if this step is on the interval; call pre-forward."""
        due = self.step % self.interval == 0
        self.step += 1
        if due:
            self.queue, self.activated = [], True
            if _mstats.enabled():
                # compiled mode: remember where the stats stream is now;
                # toc() drains whatever the trainer records past this
                self._mark = _mstats.recorder().step

    def toc(self):
        """Disarm and drain: returns [(step, name, stat_string), ...]."""
        was_armed, self.activated = self.activated, False
        if not was_armed:
            return []
        if _mstats.enabled():
            drained = self._drain_compiled()
        else:
            drained = [(step, name, _render(val))
                       for step, name, val in self.queue]
        self.queue = []
        if self.sort:
            drained.sort(key=lambda row: row[1])
        return drained

    def _drain_compiled(self):
        """Compiled-mode drain: the model_stats recorder rows booked
        since tic(), flattened to ``<param>:<stat>`` (+ ``loss``) and
        filtered by ``pattern=`` like any eager tap."""
        drained = []
        for _, names, stats, loss in _mstats.recorder().drain(self._mark):
            for row, pname in enumerate(names):
                for col, sname in enumerate(_mstats.STAT_NAMES):
                    name = "%s:%s" % (pname, sname)
                    if self.re_prog.match(name):
                        drained.append((self.step, name,
                                        "%s\t" % stats[row][col]))
            if loss is not None and self.re_prog.match("loss"):
                drained.append((self.step, "loss", "%s\t" % loss))
        return drained

    def toc_print(self):
        """Drain and pretty-print (ref monitor.py:toc_print)."""
        rows = self.toc()
        for step, name, stat in rows:
            print("Batch: {:7d} {:30s} {:s}".format(step, name, stat))
        return rows
