"""User-defined operators: CustomOp / CustomOpProp / register.

API parity with the reference ``python/mxnet/operator.py:1-880`` (the
CustomOp protocol behind the ``Custom`` graph op,
``src/operator/custom/custom.cc:49-250``). The TPU execution story differs
by design — see ``mxnet_tpu/ops/custom.py``: the numpy callbacks run on
host behind ``jax.pure_callback`` so Custom ops compose with jit/grad,
while performance-critical user kernels should register pure-jax or
Pallas functions with ``mxnet_tpu.ops.register`` instead (that path runs
on-chip and fuses; ``ops/pallas_kernels.py`` shows the recipe).

Usage (identical to the reference)::

    import mxnet_tpu as mx

    class Sigmoid(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            y = 1.0 / (1.0 + mx.nd.exp(-in_data[0]))
            self.assign(out_data[0], req[0], y)

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            y = out_data[0]
            self.assign(in_grad[0], req[0], out_grad[0] * y * (1 - y))

    @mx.operator.register("sigmoid")
    class SigmoidProp(mx.operator.CustomOpProp):
        def __init__(self):
            super().__init__(need_top_grad=True)

        def create_operator(self, ctx, shapes, dtypes):
            return Sigmoid()

    out = mx.nd.Custom(x, op_type="sigmoid")
"""
from __future__ import annotations

import numpy as np

from .ops.custom import CUSTOM_PROP_REGISTRY, register_prop

__all__ = ["CustomOp", "CustomOpProp", "register",
           "get_all_registered_operators"]


class CustomOp(object):
    """Base class for the per-instance forward/backward callbacks
    (ref operator.py CustomOp)."""

    def forward(self, is_train, req, in_data, out_data, aux):
        """Compute outputs from ``in_data`` into ``out_data``."""
        raise NotImplementedError()

    def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
        """Compute input gradients into ``in_grad``."""
        raise NotImplementedError()

    def assign(self, dst, req, src):
        """Store *src* into *dst* honouring the write request."""
        if req in ("null",):
            return
        if req in ("write", "inplace"):
            dst[:] = src
        elif req == "add":
            dst[:] = dst + src
        else:
            raise ValueError("unknown req %r" % req)


class CustomOpProp(object):
    """Declares a custom op's interface: names, shapes, dtypes, and the
    operator factory (ref operator.py CustomOpProp).

    ``need_top_grad`` records whether backward consumes the output
    gradient (loss-layer ops set it False); kept for API parity — the
    TPU build always supplies out_grad.
    """

    def __init__(self, need_top_grad=True):
        self.need_top_grad_ = need_top_grad

    def infer_shape(self, in_shape):
        """Default: all outputs/aux shaped like the first input."""
        n_out = len(self.list_outputs())
        n_aux = len(self.list_auxiliary_states())
        return (in_shape, [in_shape[0]] * n_out, [in_shape[0]] * n_aux)

    def infer_type(self, in_type):
        """Default: everything takes the first input's dtype."""
        lead = in_type[0]
        return (in_type, [lead] * len(self.list_outputs()),
                [lead] * len(self.list_auxiliary_states()))

    def list_arguments(self):
        return ["data"]

    def list_outputs(self):
        return ["output"]

    def list_auxiliary_states(self):
        return []

    def declare_backward_dependency(self, out_grad, in_data, out_data):
        """Which arrays backward reads (ref operator.py:
        used for dependency pruning; informational here)."""
        deps = []
        if self.need_top_grad_:
            deps.extend(out_grad)
        deps.extend(in_data)
        deps.extend(out_data)
        return deps

    def create_operator(self, ctx, in_shapes, in_dtypes):
        return CustomOp()

    def need_top_grad(self):
        return self.need_top_grad_


def register(reg_name):
    """Class decorator registering a CustomOpProp under *reg_name*
    (ref operator.py:register); afterwards
    ``nd.Custom(..., op_type=reg_name)`` / ``sym.Custom(...)`` work."""

    def do_register(prop_cls):
        if not issubclass(prop_cls, CustomOpProp):
            raise TypeError("register() expects a CustomOpProp subclass")
        register_prop(reg_name, prop_cls)
        return prop_cls

    return do_register


def get_all_registered_operators():
    return sorted(CUSTOM_PROP_REGISTRY)
