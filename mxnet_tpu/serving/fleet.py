"""The serving fleet router: health-gated, hedging, failover balancing.

ROADMAP item 5's "millions of users" step: one router process spreads
predict traffic over N :mod:`~.replica` processes (each the PR-6
slot-table server) on the PR-8 hardened transport, and its headline
property is that the fleet *keeps serving* through replica death and
model rollout:

* **Least-outstanding balancing** — each request routes to the ready
  replica with the fewest attempts in flight (ties break to the least
  served, so an idle fleet round-robins); readiness is the replica's own
  reported state, so a compiling/warming/draining replica takes no
  traffic.
* **Hedged retries** — predict is idempotent, so after a p99-derived
  hedge timeout (``MXNET_FLEET_HEDGE_MS=0`` derives ``2 × p99`` from the
  router's own attempt latencies; an explicit value pins it) a duplicate
  fires to a *different* replica and the first reply wins.  Tail latency
  from one slow replica stops being the fleet's tail.
* **Failover** — a failed attempt (dead connection, RPC timeout,
  replica-side executor fault, ``busy`` backpressure) immediately
  re-routes to an untried replica, up to ``MXNET_FLEET_MAX_ATTEMPTS``,
  all bounded by the request deadline
  (``MXNET_FLEET_REQUEST_TIMEOUT_MS``): an accepted request completes —
  hedged or failed over — within its deadline, or fails structurally,
  never hangs.
* **Per-replica circuit breakers** (reusing
  :class:`~.slots.CircuitBreaker`) — a replica that fails repeatedly is
  shed from routing until its half-open probe succeeds.
* **Health-gated membership** — replicas heartbeat on dedicated
  connections (``MXNET_FLEET_HEARTBEAT_S``); a kill -9'd replica is
  detected by disconnect instantly and by staleness within
  ``MXNET_FLEET_DEAD_AFTER_S`` (default 2x the interval), then shed
  while its in-flight requests fail over.  A restarted replica
  re-registers into its dead rank, warms from the checkpoint tier, and
  takes traffic only once it reports ``ready``.
* **Zero-downtime rollout** — :meth:`FleetRouter.rolling_reload` (the
  router's ``POST /v1/models/<m>/reload``) walks replicas one at a
  time: hold traffic, drain in-flight, compile-then-swap via the slot
  ``reload``, resume on ``ready``.  Survivors carry the load, so a full
  fleet rollout completes with zero failed requests.

The :mod:`mxnet_tpu.chaos` ``fleet.route`` seam fires once per accepted
request, in routing order, before a replica is picked — so router-side
faults replay deterministically from a seeded spec.  Trace ids ride the
wire for free (the :class:`~mxnet_tpu.dist_ps.Conn` trace context), so a
request's router span, RPC events, and replica-side batch spans share
one id end-to-end in ``trace_report --fleet`` merges.
"""
from __future__ import annotations

import os
import threading
import time

from .. import chaos as _chaos
from .. import dist_ps as _ps
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..lint import lockwitness as _lockwitness
from .slots import CircuitBreaker
from .batcher import Overloaded

__all__ = ["FleetRouter", "current_router", "refresh_from_env",
           "heartbeat_s", "dead_after_s",
           "DEFAULT_HEARTBEAT_S", "DEFAULT_REQUEST_TIMEOUT_MS",
           "DEFAULT_MAX_ATTEMPTS"]

DEFAULT_HEARTBEAT_S = 0.5
DEFAULT_REQUEST_TIMEOUT_MS = 10000.0
DEFAULT_MAX_ATTEMPTS = 3
DEFAULT_RELOAD_TIMEOUT_S = 600.0

_ROUTABLE_STATES = ("ready",)
_KNOWN_STATES = ("starting", "warming", "ready", "reloading", "draining",
                 "stopped", "dead")


# env parsing shared with the transport the fleet rides on (one
# implementation to fix when a knob needs smarter parsing)
_env_float = _ps._env_float
_env_int = _ps._env_int


def _read_env():
    hb = _env_float("MXNET_FLEET_HEARTBEAT_S", DEFAULT_HEARTBEAT_S,
                    minimum=0.05)
    return {
        "heartbeat": hb,
        # the acceptance contract: a silent replica is shed within 2x
        # the heartbeat interval (disconnects are instant regardless)
        "dead_after": _env_float("MXNET_FLEET_DEAD_AFTER_S", 2.0 * hb,
                                 minimum=0.1),
        "hedge_ms": _env_float("MXNET_FLEET_HEDGE_MS", 0.0),
        "request_timeout_ms": _env_float("MXNET_FLEET_REQUEST_TIMEOUT_MS",
                                         DEFAULT_REQUEST_TIMEOUT_MS,
                                         minimum=1.0),
        "max_attempts": _env_int("MXNET_FLEET_MAX_ATTEMPTS",
                                 DEFAULT_MAX_ATTEMPTS),
        "reload_timeout": _env_float("MXNET_FLEET_RELOAD_TIMEOUT_S",
                                     DEFAULT_RELOAD_TIMEOUT_S,
                                     minimum=1.0),
    }


# cached at import (JG006 cached-value pattern; predict is the hot path)
_ENV = _read_env()


def refresh_from_env():
    """Re-read every MXNET_FLEET_* knob (tests / live reconfig)."""
    global _ENV
    _ENV = _read_env()


def heartbeat_s():
    return _ENV["heartbeat"]


def dead_after_s():
    return _ENV["dead_after"]


class _ReplicaHandle:
    """Router-side view of one replica: address, reported state, the
    balancing/breaker accounting, and a small idle-connection pool."""

    _POOL_CAP = 4

    def __init__(self, rank, addr, models):
        self.rank = rank
        self.addr = tuple(addr)
        self.models = list(models or ())
        self.state = "warming"
        self.admin_hold = False        # router-held (rolling reload)
        self.generation = 0            # bumped per (re-)registration
        self.last_hb = time.monotonic()
        self.breaker = CircuitBreaker()
        self.outstanding = 0
        self.served = 0
        self.reported_outstanding = 0
        self._lock = _lockwitness.make_lock("_ReplicaHandle._lock")
        self._pool = []

    # -- connection pool ---------------------------------------------------

    def get_conn(self):
        with self._lock:
            if self._pool:
                return self._pool.pop()
        return _ps.Conn.connect(self.addr, retries=2, delay=0.05)

    def put_conn(self, conn):
        with self._lock:
            if len(self._pool) < self._POOL_CAP:
                self._pool.append(conn)
                return
        conn.close()

    def close_conns(self):
        with self._lock:
            pool, self._pool = self._pool, []
        for conn in pool:
            conn.close()

    # -- accounting --------------------------------------------------------

    def inc_outstanding(self, delta):
        with self._lock:
            self.outstanding += delta

    def routable(self, model=None):
        return (self.state in _ROUTABLE_STATES
                and not self.admin_hold
                and (model is None or model in self.models))

    def view(self):
        with self._lock:
            outstanding, served = self.outstanding, self.served
        return {"addr": "%s:%s" % self.addr,
                "state": "held" if self.admin_hold and
                self.state == "ready" else self.state,
                "models": list(self.models),
                "outstanding": outstanding,
                "served": served,
                "reported_outstanding": self.reported_outstanding,
                "breaker": self.breaker.state(),
                "last_hb_age_s": round(time.monotonic() - self.last_hb,
                                       3)}


class _PredictBox:
    """Shared completion state between a request's attempt threads."""

    def __init__(self):
        self.cond = _lockwitness.make_condition(name="_PredictBox.cond")
        self.outs = None           # (names, arrays, replica_rank, kind)
        self.app_error = None
        self.fails = []            # [(kind, exception)]
        self.finished = 0


class FleetRouter:
    """The replica registry + request router (one per router process)."""

    def __init__(self, port=0, host="127.0.0.1"):
        self._replicas = {}            # rank -> _ReplicaHandle
        self._lock = _lockwitness.make_lock("FleetRouter._lock")
        self._stop = threading.Event()
        self._reload_lock = _lockwitness.make_lock(
            "FleetRouter._reload_lock")
        # p99 source for the derived hedge timeout: an unregistered
        # Histogram (per-router series, not the flat global registry)
        self._attempt_latency = _telemetry.Histogram("attempt_us")
        self._listener = _ps.RpcListener(self._serve_conn, port=port,
                                         host=host, name="fleet-router")
        self.addr = self._listener.addr
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="mxnet-fleet-monitor",
            daemon=True)

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        global _CURRENT
        self._listener.start()
        self._monitor.start()
        _CURRENT = self
        _telemetry.flight.record("fleet_router_start",
                                 "%s:%s" % self.addr)
        return self

    def stop(self):
        global _CURRENT
        self._stop.set()
        self._listener.stop()
        with self._lock:
            handles = list(self._replicas.values())
        for handle in handles:
            handle.close_conns()
        if _CURRENT is self:
            _CURRENT = None

    def shutdown_replicas(self):
        """Ask every live replica to exit (tests / orchestration)."""
        with self._lock:
            handles = list(self._replicas.values())
        for handle in handles:
            if handle.state == "dead":
                continue
            try:
                conn = handle.get_conn()
                conn.send(("shutdown",))
                conn.recv(timeout=5.0)
                conn.close()
            except (OSError, ConnectionError):
                pass

    # -- registration + heartbeats (the listener side) ---------------------

    def _serve_conn(self, conn):
        try:
            msg = conn.recv(timeout=max(dead_after_s() * 5, 15.0))
        except (OSError, ConnectionError):
            return
        if not (isinstance(msg, tuple) and msg):
            return
        if msg[0] == "reg_replica":
            _, addr, rank_hint, models = msg
            rank = self._register(tuple(addr), rank_hint, models)
            conn.send(("ranked", rank))
            return
        if msg[0] == "hb_replica":
            self._serve_heartbeats(conn, int(msg[1]))

    def _register(self, addr, rank_hint, models):
        """Assign a rank: the same address re-registers in place (a
        replica whose heartbeat link blipped must not appear twice),
        else the hint wins when its slot is free or dead (a restarted
        replica takes over its old rank), else the lowest dead rank,
        else a fresh one."""
        with self._lock:
            rank = None
            for r, h in self._replicas.items():
                if h.addr == tuple(addr):
                    rank = r
                    break
            if rank is None and isinstance(rank_hint, int) \
                    and rank_hint >= 0:
                cur = self._replicas.get(rank_hint)
                if cur is None or cur.state == "dead":
                    rank = rank_hint
            if rank is None:
                dead = sorted(r for r, h in self._replicas.items()
                              if h.state == "dead")
                rank = dead[0] if dead \
                    else (max(self._replicas) + 1 if self._replicas else 0)
            old = self._replicas.get(rank)
            handle = _ReplicaHandle(rank, addr, models)
            handle.generation = (old.generation + 1) if old else 0
            self._replicas[rank] = handle
        if old is not None:
            old.close_conns()
        _telemetry.bump("fleet_registrations")
        _telemetry.flight.record(
            "fleet_register", str(rank), addr="%s:%s" % tuple(addr),
            rejoin=old is not None)
        self.refresh_gauges()
        return rank

    def _handle_for(self, rank):
        with self._lock:
            return self._replicas.get(rank)

    def _serve_heartbeats(self, conn, rank):
        """Per-replica heartbeat loop: stamp arrivals, adopt the
        replica's reported state, declare death on disconnect (instant)
        or staleness.  *generation* guards the kill-then-restart race:
        a dead connection from a superseded registration must not bury
        the replica that just took the rank over."""
        handle = self._handle_for(rank)
        if handle is None:
            return
        generation = handle.generation
        while not self._stop.is_set():
            try:
                msg = conn.recv(timeout=max(dead_after_s(), 0.05))
            except _ps.RPCTimeout:
                handle = self._handle_for(rank)
                if handle is not None \
                        and handle.generation == generation:
                    self._mark_dead(handle, "heartbeat-stale")
                continue
            except (OSError, ConnectionError):
                handle = self._handle_for(rank)
                if handle is not None \
                        and handle.generation == generation:
                    self._mark_dead(handle, "heartbeat-disconnect")
                return
            handle = self._handle_for(rank)
            if handle is None or handle.generation != generation:
                return                 # superseded registration
            handle.last_hb = time.monotonic()
            if isinstance(msg, tuple) and msg and msg[0] == "hb":
                state = str(msg[1])
                if state in _KNOWN_STATES:
                    if handle.state == "dead" and state != "dead":
                        _telemetry.flight.record("fleet_revive",
                                                 str(rank), state=state)
                    handle.state = state
                if len(msg) > 2:
                    handle.reported_outstanding = int(msg[2])
                if len(msg) > 3 and msg[3]:
                    handle.models = list(msg[3])

    def _mark_dead(self, handle, reason):
        if handle.state == "dead":
            return
        handle.state = "dead"
        handle.close_conns()
        _telemetry.bump("fleet_replica_deaths")
        _telemetry.flight.record("fleet_replica_dead", str(handle.rank),
                                 reason=reason)
        self.refresh_gauges()

    def _monitor_loop(self):
        """Staleness tripwire: disconnects shed a dead replica
        instantly; this sweep catches the truly-silent-on-a-live-socket
        case within the 2x-heartbeat contract."""
        while not self._stop.wait(max(heartbeat_s() / 2.0, 0.025)):
            now = time.monotonic()
            with self._lock:
                handles = list(self._replicas.values())
            for handle in handles:
                if handle.state != "dead" \
                        and now - handle.last_hb > dead_after_s():
                    self._mark_dead(handle, "heartbeat-stale")
            self.refresh_gauges()

    # -- routing -----------------------------------------------------------

    def _pick(self, model, tried):
        """Least-outstanding ready replica not yet tried (ties: least
        served, then rank — an idle fleet round-robins).  The breaker is
        consulted in preference order and only until one admits:
        ``allow()`` on a half-open breaker CLAIMS its single probe
        lease, so asking every candidate up front would burn the leases
        of replicas this request never dispatches to and wedge fleet
        recovery."""
        with self._lock:
            candidates = [h for h in self._replicas.values()
                          if h.rank not in tried and h.routable(model)]
            candidates.sort(
                key=lambda h: (h.outstanding, h.served, h.rank))
            for handle in candidates:
                if handle.breaker.allow():
                    return handle
            return None

    def _launch(self, handle, model, inputs, deadline, box, kind):
        handle.inc_outstanding(1)
        threading.Thread(
            target=self._attempt,
            args=(handle, model, inputs, deadline, box, kind),
            name="mxnet-fleet-attempt-%d" % handle.rank,
            daemon=True).start()

    def _attempt(self, handle, model, inputs, deadline, box, kind):
        """One replica RPC; posts its outcome into the request's box.
        Every wait is bounded by the request deadline."""
        t0 = time.perf_counter()
        reply = err = None
        conn = None
        try:
            remaining = deadline - time.perf_counter()
            if remaining <= 0:
                raise _ps.RPCTimeout("request deadline passed before "
                                     "the attempt dispatched")
            conn = handle.get_conn()
            conn.send(("predict", model, inputs,
                       round(remaining * 1e3, 1)))
            reply = conn.recv(
                timeout=max(0.01, deadline - time.perf_counter()))
        except Exception as exc:  # noqa: BLE001 — the box MUST resolve:
            # PeerLost/RPCTimeout/ProtocolError or any unexpected bug;
            # a dead attempt thread would otherwise leave the request
            # waiting out its full deadline.  The conn is suspect —
            # never recycle it.
            handle.breaker.record(ok=False)
            err = exc
            if conn is not None:
                conn.close()
                conn = None
        else:
            handle.put_conn(conn)
        handle.inc_outstanding(-1)
        with box.cond:
            box.finished += 1
            if err is not None:
                box.fails.append((kind, err))
            elif (isinstance(reply, tuple) and reply
                  and reply[0] == "outs"):
                handle.breaker.record(ok=True)
                with handle._lock:
                    handle.served += 1
                self._attempt_latency.observe(
                    (time.perf_counter() - t0) * 1e6)
                if box.outs is None:
                    box.outs = (list(reply[1]), list(reply[2]),
                                reply[3], kind)
            elif (isinstance(reply, tuple) and reply
                  and reply[0] in ("busy", "not_ready")):
                # backpressure, not a fault: route around, no breaker hit
                box.fails.append((kind, Overloaded(
                    "replica %d is %s: %s"
                    % (handle.rank, reply[0], reply[1]))))
            elif (isinstance(reply, tuple) and reply
                  and reply[0] == "fail"):
                handle.breaker.record(ok=False)
                box.fails.append((kind, MXNetError(str(reply[1]))))
            elif (isinstance(reply, tuple) and reply
                  and reply[0] == "err"):
                # the request's own fault: any replica would answer the
                # same, so propagate instead of burning failovers
                handle.breaker.record(ok=True)
                if box.app_error is None:
                    box.app_error = MXNetError(str(reply[1]))
            else:
                handle.breaker.record(ok=False)
                box.fails.append((kind, MXNetError(
                    "replica %d sent malformed reply %r"
                    % (handle.rank, reply))))
            box.cond.notify_all()

    def _hedge_timeout_s(self):
        """The p99-derived hedge delay: 2x the router's own attempt p99,
        clamped to [25ms, 1s]; ``MXNET_FLEET_HEDGE_MS`` pins it; before
        enough samples exist the conservative 250ms floor applies."""
        if _ENV["hedge_ms"] > 0:
            return _ENV["hedge_ms"] / 1e3
        hist = self._attempt_latency
        if hist.count >= 20:
            return min(max(2.0 * hist.percentile(99) / 1e6, 0.025), 1.0)
        return 0.25

    def predict(self, model, inputs, timeout_s=None):
        """Route one predict; returns the output arrays (first winning
        reply).  See :meth:`predict_detail` for the attempt metadata."""
        return self.predict_detail(model, inputs, timeout_s=timeout_s)[0]

    def predict_detail(self, model, inputs, timeout_s=None):
        """Route one predict with hedging + failover; returns
        ``(outputs, meta)`` where meta carries the serving replica rank,
        output names, attempt count, and whether the hedge won."""
        if _chaos.active():
            act = _chaos.decide("fleet.route")
            if act is not None:
                _chaos.apply_inline(act)
        _telemetry.bump("fleet_requests")
        t0 = time.perf_counter()
        deadline = t0 + (timeout_s if timeout_s
                         else _ENV["request_timeout_ms"] / 1e3)
        max_attempts = _ENV["max_attempts"]
        box = _PredictBox()
        tried = set()
        with _telemetry.span("fleet_route", cat="serving",
                             args={"model": model}):
            first = self._pick(model, tried)
            if first is None:
                self._refuse(model)            # raises 404 or shed/503
            tried.add(first.rank)
            self._launch(first, model, inputs, deadline, box, "primary")
            launched, consumed, hedged = 1, 0, False
            hedge_at = t0 + self._hedge_timeout_s()
            last_err = None
            while True:
                with box.cond:
                    if (box.outs is None and box.app_error is None
                            and len(box.fails) == consumed):
                        horizon = deadline if hedged else \
                            min(deadline, hedge_at)
                        wait_s = horizon - time.perf_counter()
                        box.cond.wait(min(max(wait_s, 0.0), 0.05)
                                      + 0.001)
                    outs = box.outs
                    app_error = box.app_error
                    new_fails = box.fails[consumed:]
                    finished = box.finished
                if outs is not None:
                    return self._finish(model, outs, t0,
                                        attempts=launched)
                if app_error is not None:
                    _telemetry.bump("fleet_errors")
                    raise app_error
                now = time.perf_counter()
                for kind, exc in new_fails:
                    consumed += 1
                    last_err = exc
                    if now < deadline and launched < max_attempts:
                        nxt = self._pick(model, tried)
                        if nxt is not None:
                            tried.add(nxt.rank)
                            self._launch(nxt, model, inputs, deadline,
                                         box, "failover")
                            launched += 1
                            _telemetry.bump("fleet_failovers")
                if not hedged and now >= hedge_at:
                    if (now < deadline and launched < max_attempts
                            and finished < launched):
                        nxt = self._pick(model, tried)
                        if nxt is not None:
                            tried.add(nxt.rank)
                            self._launch(nxt, model, inputs, deadline,
                                         box, "hedge")
                            launched += 1
                            _telemetry.bump("fleet_hedges")
                    # the hedge window resolves exactly once — placed,
                    # or given up (attempts exhausted / no untried
                    # replica).  Leaving it open would re-poll _pick
                    # under the router lock at ~1 kHz until the
                    # deadline because the wait horizon stays in the
                    # past.
                    hedged = True
                with box.cond:
                    finished = box.finished
                    settled = (box.outs is not None
                               or box.app_error is not None
                               or len(box.fails) > consumed)
                if settled:
                    continue           # resolve it on the next pass
                if finished >= launched:
                    _telemetry.bump("fleet_errors")
                    raise Overloaded(
                        "fleet predict for %r failed on every routable "
                        "replica (%d attempt(s)); last error: %r"
                        % (model, launched, last_err))
                if now >= deadline:
                    _telemetry.bump("fleet_errors")
                    raise MXNetError(
                        "fleet predict for %r timed out after %.1fs "
                        "(%d attempt(s) in flight)"
                        % (model, time.perf_counter() - t0, launched))

    def _finish(self, model, outs, t0, attempts):
        names, arrays, rank, kind = outs
        latency_us = (time.perf_counter() - t0) * 1e6
        _telemetry.observe("fleet_request_us", latency_us)
        meta = {"replica": rank, "output_names": names,
                "attempts": attempts, "hedged_win": kind == "hedge",
                "latency_us": latency_us}
        return arrays, meta

    def _refuse(self, model):
        """No routable replica: 404 when the model is unknown fleetwide,
        503 (shed) when replicas exist but none can take traffic."""
        with self._lock:
            handles = list(self._replicas.values())
        known_anywhere = any(model in h.models for h in handles)
        routable_any = any(h.routable() for h in handles)
        if routable_any and not known_anywhere:
            raise MXNetError(
                "model %r is not loaded on any replica (fleet of %d)"
                % (model, len(handles)))
        _telemetry.bump("fleet_shed")
        raise Overloaded(
            "no routable replica for %r (%d registered: dead, warming, "
            "breaker-open, or held); retry later"
            % (model, len(handles)))

    # -- rollout -----------------------------------------------------------

    def rolling_reload(self, model, prefix=None, epoch=None,
                       drain_timeout_s=10.0):
        """Zero-downtime rollout: walk ready replicas one at a time —
        hold new traffic, drain in-flight, compile-then-swap via the
        replica's slot ``reload``, resume.  Stops at the first failure
        (survivors keep the old weights — a canary abort, not a
        half-broken fleet).  Returns {rank: "ok" | "error: ..."}."""
        if not self._reload_lock.acquire(blocking=False):
            raise MXNetError("a rolling reload is already in progress")
        try:
            with self._lock:
                targets = sorted(
                    (h for h in self._replicas.values()
                     if h.routable(model)), key=lambda h: h.rank)
            if not targets:
                raise MXNetError(
                    "model %r is not loaded on any ready replica"
                    % model)
            spec = {"prefix": prefix, "epoch": epoch}
            results = {}
            for handle in targets:
                handle.admin_hold = True
                try:
                    t_end = time.monotonic() + drain_timeout_s
                    while handle.outstanding > 0 \
                            and time.monotonic() < t_end:
                        time.sleep(0.01)
                    conn = handle.get_conn()
                    try:
                        conn.send(("reload", model, spec))
                        reply = conn.recv(timeout=_ENV["reload_timeout"])
                    except (OSError, ConnectionError) as exc:
                        conn.close()
                        results[handle.rank] = "error: %r" % (exc,)
                        break
                    handle.put_conn(conn)
                    if isinstance(reply, tuple) and reply \
                            and reply[0] == "ok":
                        results[handle.rank] = "ok"
                        _telemetry.bump("fleet_reloads")
                    else:
                        results[handle.rank] = "error: %s" % (
                            reply[1] if isinstance(reply, tuple)
                            and len(reply) > 1 else reply,)
                        break
                finally:
                    handle.admin_hold = False
            _telemetry.flight.record(
                "fleet_rolling_reload", model,
                ok=all(v == "ok" for v in results.values()),
                replicas=len(results))
            return results
        finally:
            self._reload_lock.release()

    # -- views -------------------------------------------------------------

    def ready_count(self):
        with self._lock:
            return sum(1 for h in self._replicas.values()
                       if h.routable())

    def total_count(self):
        with self._lock:
            return len(self._replicas)

    def models(self):
        """Every model some routable replica advertises."""
        with self._lock:
            names = set()
            for h in self._replicas.values():
                if h.routable():
                    names.update(h.models)
        return sorted(names)

    def wait_ready(self, n, timeout=60.0):
        """Poll until *n* replicas are routable; False on timeout."""
        t_end = time.monotonic() + timeout
        while time.monotonic() < t_end:
            if self.ready_count() >= n:
                return True
            time.sleep(0.02)
        return self.ready_count() >= n

    def http_view(self):
        """The /fleet serving view: replica table + routing counters."""
        with self._lock:
            replicas = {str(r): h.view()
                        for r, h in sorted(self._replicas.items())}
        return {"addr": "%s:%s" % self.addr,
                "replicas": replicas,
                "replicas_ready": self.ready_count(),
                "replicas_total": len(replicas),
                "models": self.models(),
                "hedge_timeout_ms": round(
                    self._hedge_timeout_s() * 1e3, 1),
                "counters": {name: _telemetry.counter(name) for name in
                             ("fleet_requests", "fleet_hedges",
                              "fleet_failovers", "fleet_errors",
                              "fleet_shed", "fleet_replica_deaths",
                              "fleet_registrations", "fleet_reloads")}}

    def refresh_gauges(self):
        with self._lock:
            handles = list(self._replicas.values())
        _telemetry.set_gauge("fleet_replicas_ready",
                             sum(1 for h in handles if h.routable()))
        _telemetry.set_gauge("fleet_replicas_total", len(handles))
        _telemetry.set_gauge("fleet_outstanding",
                             sum(h.outstanding for h in handles))


_CURRENT = None


def current_router():
    """The process's started FleetRouter, or None (the /v1 + /fleet
    delegation hook — observe-only callers never construct one)."""
    return _CURRENT
