"""AOT predict programs: bucket-padded batch variants of one Predictor.

The deployment unit of the reference framework is an ahead-of-time
artifact (`c_predict_api` over the amalgamation build — PAPER layer 9);
TVM (arxiv 1802.04799) and the Julia-to-TPU pipeline (arxiv 1810.09868)
make the same argument for XLA: serve *compiled programs*, not graphs.
This module is that unit for the TPU build:

* At model load, the predictor's eval program (the executor's
  ``executor_eval`` jit — already watched, cost-accounted, and
  graftcheck-covered) is lowered **ahead of time** from
  ``ShapeDtypeStruct`` specimens at every bucket batch size and compiled
  into a table of XLA executables.  No data is touched and nothing runs
  at load beyond the compiles themselves.
* At request time a batch of n rows is padded up to the smallest bucket
  ``b >= n`` and dispatched straight to the bucket's executable.  There
  is **no jit dispatch on the request path**, so a retrace is
  structurally impossible — the property the PR-2 retrace watchdog can
  only report after the fact, made unrepresentable.
* A request larger than the biggest bucket takes the *straight-through*
  path: one unpadded call through the watched jit (which may compile a
  new variant, booked by the watchdog like any other compile).  That is
  the explicit escape hatch, not the normal path.

Bucket policy: a power-of-two ladder ``1, 2, 4, ... max_batch``
(``MXNET_SERVE_MAX_BATCH``, default 32), or an explicit
``MXNET_SERVE_BUCKETS=1,4,16`` list.  Padding waste is bounded by 2x on
the ladder; latency cost of the waste is what ``serving_padded_rows``
and the occupancy histogram make visible.

Batch-dependent *non-input* args (the zero-bound ``*_label`` loss heads
a checkpoint carries) are re-inferred per bucket and zero-filled once at
compile time; parameters are captured as live device buffers — swap the
whole program (``ModelSlot.reload``) to pick up new weights.
"""
from __future__ import annotations

import logging
import os
import threading

import numpy as np

from .. import random as _random
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..lint import lockwitness as _lockwitness

__all__ = ["PredictProgram", "bucket_sizes", "refresh_from_env",
           "DEFAULT_MAX_BATCH", "tracecheck_programs"]

_LOG = logging.getLogger("mxnet_tpu.serving")

DEFAULT_MAX_BATCH = 32


def _env_max_batch():
    try:
        return max(1, int(os.environ.get("MXNET_SERVE_MAX_BATCH",
                                         DEFAULT_MAX_BATCH)))
    except ValueError:
        return DEFAULT_MAX_BATCH


def _env_buckets():
    raw = os.environ.get("MXNET_SERVE_BUCKETS", "").strip()
    if not raw:
        return None
    try:
        sizes = tuple(sorted({int(tok) for tok in raw.split(",") if tok}))
    except ValueError:
        return None
    return sizes if sizes and all(s > 0 for s in sizes) else None


# cached at import (JG006 cached-value pattern); serving.refresh_from_env()
# re-reads for tests / long-lived operators
_MAX_BATCH = _env_max_batch()
_BUCKETS = _env_buckets()


def refresh_from_env():
    global _MAX_BATCH, _BUCKETS
    _MAX_BATCH = _env_max_batch()
    _BUCKETS = _env_buckets()


def bucket_sizes(max_batch=None, buckets=None):
    """The bucket ladder: explicit *buckets* win, else powers of two up
    to (and always including) *max_batch*."""
    if buckets is None:
        buckets = _BUCKETS
    if buckets is not None:
        return tuple(sorted({int(b) for b in buckets}))
    if max_batch is None:
        max_batch = _MAX_BATCH
    sizes, b = [], 1
    while b < max_batch:
        sizes.append(b)
        b *= 2
    sizes.append(max_batch)
    return tuple(sorted(set(sizes)))


def _pad_rows(arr, b):
    """Zero-pad axis 0 of *arr* up to *b* rows (no-op when full)."""
    n = arr.shape[0]
    if n == b:
        return arr
    pad = np.zeros((b - n,) + arr.shape[1:], arr.dtype)
    return np.concatenate([arr, pad], axis=0)


class PredictProgram:
    """The bucket table of AOT-compiled eval executables for one model.

    Build (and :meth:`warmup`) once per checkpoint load; ``run`` is then
    pad → executable → slice, with zero tracing.  Thread-safe for
    concurrent ``run`` calls (executables are immutable; XLA execution
    is reentrant) — write-serialization per model is the batcher's job.
    """

    def __init__(self, predictor, buckets=None, max_batch=None,
                 name="model", warmup=True):
        ex = predictor._exe
        self.name = name
        self._ex = ex
        self._symbol = predictor._symbol
        self._input_shapes = dict(predictor._input_shapes)
        self._input_names = list(predictor._input_names)
        self._arg_pos = {n: i for i, n in enumerate(ex.arg_names)}
        self._dev = ex._ctx.jax_device
        # one fixed key for the whole program lifetime: eval-mode graphs
        # are deterministic (dropout is identity), and a per-call key
        # would make identical requests non-reproducible
        self._key = _random.next_key()
        self._aux_vals = [ex.aux_dict[n]._data for n in ex.aux_names]
        self.buckets = bucket_sizes(max_batch=max_batch, buckets=buckets)
        self.max_batch = self.buckets[-1]
        self._variants = {}          # b -> (executable, fixed_args, cost)
        self._lock = _lockwitness.make_lock("PredictProgram._lock")
        if warmup:
            self.warmup()

    # -- AOT build ---------------------------------------------------------

    def _arg_shapes_for(self, b):
        """Inferred shape of every executor arg at input batch *b*."""
        shapes = {n: (b,) + self._input_shapes[n][1:]
                  for n in self._input_names}
        arg_shapes, _, _ = self._symbol.infer_shape(**shapes)
        return dict(zip(self._ex.arg_names, arg_shapes))

    def _specs_for(self, b):
        """ShapeDtypeStruct specimens of the eval program at bucket *b*
        — what the AOT lower (and the graftcheck provider) traces."""
        import jax
        shapes = self._arg_shapes_for(b)
        arg_specs = [jax.ShapeDtypeStruct(tuple(shapes[n]),
                                          self._ex.arg_dict[n].dtype)
                     for n in self._ex.arg_names]
        aux_specs = [jax.ShapeDtypeStruct(v.shape, v.dtype)
                     for v in self._aux_vals]
        key_spec = jax.ShapeDtypeStruct(self._key.shape, self._key.dtype)
        return arg_specs, aux_specs, key_spec

    def _build_variant(self, b):
        """Lower + compile the bucket-*b* executable and its fixed
        (non-input) argument values."""
        import jax
        import jax.numpy as jnp
        ex = self._ex
        shapes = self._arg_shapes_for(b)
        arg_specs, aux_specs, key_spec = self._specs_for(b)
        fixed = []
        for n in ex.arg_names:
            cur = ex.arg_dict[n]
            if n in self._input_shapes:
                fixed.append(None)                 # filled per call
            elif tuple(shapes[n]) == tuple(cur.shape):
                fixed.append(cur._data)            # parameter buffer
            else:
                # batch-dependent non-input: a zero-bound loss label —
                # rebuilt at the bucket's batch size, once
                fixed.append(jax.device_put(
                    jnp.zeros(tuple(shapes[n]), cur.dtype), self._dev))
        compiled = ex._eval_jit.lower(arg_specs, aux_specs,
                                      key_spec).compile()
        from ..telemetry import costs as _costs
        return compiled, fixed, _costs.analyze_compiled(compiled)

    def warmup(self):
        """Compile every bucket variant AOT (idempotent).  This is the
        load-time cost that buys a retrace-free request path."""
        import time
        for b in self.buckets:
            with self._lock:
                if b in self._variants:
                    continue
            t0 = time.perf_counter()
            variant = self._build_variant(b)
            with self._lock:
                self._variants[b] = variant
            _telemetry.bump("serving_warmup_compiles")
            _telemetry.flight.record(
                "serving_warmup", self.name, bucket=b,
                wall_ms=round((time.perf_counter() - t0) * 1e3, 1))
        return self

    # -- request path ------------------------------------------------------

    def bucket_for(self, n):
        """Smallest bucket >= n, or None (straight-through territory)."""
        for b in self.buckets:
            if b >= n:
                return b
        return None

    def check_rows(self, inputs):
        """Row count of a request's input dict, fully shape-validated —
        run BEFORE the request occupies queue capacity, so one malformed
        request fails at submit instead of poisoning every innocent
        request coalesced into its batch."""
        rows = None
        for name in self._input_names:
            if name not in inputs:
                raise MXNetError("missing input %r (need %s)"
                                 % (name, self._input_names))
            shape = getattr(inputs[name], "shape", None)
            if shape is None or len(shape) == 0:
                raise MXNetError("input %r must be a batched array" % name)
            want = self._input_shapes[name][1:]
            if len(shape) != len(want) + 1 or tuple(shape[1:]) != want:
                raise MXNetError(
                    "input %r has shape %s; expected (batch,)+%s"
                    % (name, tuple(shape), want))
            if rows is None:
                rows = int(shape[0])
            elif int(shape[0]) != rows:
                raise MXNetError(
                    "ragged batch: %r has %d rows, expected %d"
                    % (name, shape[0], rows))
        unknown = set(inputs) - set(self._input_names)
        if unknown:
            raise MXNetError("unknown inputs %s (have %s)"
                             % (sorted(unknown), self._input_names))
        if rows is None or rows <= 0:
            raise MXNetError("empty batch")
        return rows

    def _gather_inputs(self, inputs, n):
        """Canonicalize the per-input host arrays and re-validate via
        :meth:`check_rows` (one validator, two call sites: submit-time
        rejection and dispatch-time defense)."""
        arrs = {}
        for key, val in inputs.items():
            if key in self._input_shapes:
                arrs[key] = np.ascontiguousarray(
                    np.asarray(val, self._ex.arg_dict[key].dtype))
            else:
                arrs[key] = val          # unknown key: check_rows names it
        rows = self.check_rows(arrs)
        if rows != n:
            raise MXNetError("batch has %d rows, expected %d" % (rows, n))
        return {name: arrs[name] for name in self._input_names}

    def run(self, inputs, n, timings=None):
        """Pad a batch of *n* rows to its bucket and execute the AOT
        executable.  Returns ``(outputs, bucket, cost)`` with outputs a
        list of per-output numpy arrays sliced back to *n* rows.  No
        tracing happens here, ever.

        *timings* (optional dict) is filled with the request-span
        decomposition: ``pad_us`` (host pad + device_put),
        ``execute_us`` (the executable call — dispatch wall normally;
        true device time when the MXNET_DEVICE_TIME sampler blocks this
        batch, flagged ``device_blocked``), ``slice_us`` (result
        host-transfer + per-request slicing)."""
        import jax
        b = self.bucket_for(n)
        if b is None:
            raise MXNetError(
                "batch of %d exceeds max bucket %d; use run_straight"
                % (n, self.max_batch))
        with self._lock:
            variant = self._variants.get(b)
        if variant is None:                     # lazy warmup (load raced)
            variant = self._build_variant(b)
            with self._lock:
                self._variants.setdefault(b, variant)
            _telemetry.bump("serving_warmup_compiles")
        compiled, fixed, cost = variant
        t0 = _telemetry.now_us()
        vals = self._gather_inputs(inputs, n)
        arg_vals = list(fixed)
        for name in self._input_names:
            arg_vals[self._arg_pos[name]] = jax.device_put(
                _pad_rows(vals[name], b), self._dev)
        t1 = _telemetry.now_us()
        outs, _new_aux = compiled(arg_vals, self._aux_vals, self._key)
        blocked = _telemetry.device.take_serving_sample()
        if blocked:
            # sampled batch: wait for the device so execute_us is true
            # execution time (and book it in the device-time table, the
            # serving twin of the watched-jit sampler)
            jax.block_until_ready(outs)
        t2 = _telemetry.now_us()
        sliced = [np.asarray(o)[:n] for o in outs]
        t3 = _telemetry.now_us()
        if blocked:
            _telemetry.device.record_program(
                "serving:%s:b%d" % (self.name, b), t2 - t1,
                collective=False)
        if timings is not None:
            timings["pad_us"] = t1 - t0
            timings["execute_us"] = t2 - t1
            timings["slice_us"] = t3 - t2
            timings["device_blocked"] = blocked
        return sliced, b, cost

    def run_straight(self, inputs, n):
        """Oversize escape hatch: run *n* rows unpadded through the
        watched jit.  May trace+compile a fresh variant — visible to the
        retrace watchdog as an ``executor_eval`` compile event."""
        import jax
        import jax.numpy as jnp
        ex = self._ex
        shapes = self._arg_shapes_for(n)
        vals = self._gather_inputs(inputs, n)
        arg_vals = []
        for name in ex.arg_names:
            cur = ex.arg_dict[name]
            if name in self._input_shapes:
                arg_vals.append(jax.device_put(vals[name], self._dev))
            elif tuple(shapes[name]) == tuple(cur.shape):
                arg_vals.append(cur._data)
            else:
                arg_vals.append(jax.device_put(
                    jnp.zeros(tuple(shapes[name]), cur.dtype), self._dev))
        _telemetry.bump("serving_straight_through")
        outs, _new_aux = ex._eval_jit(arg_vals, self._aux_vals, self._key)
        return [np.asarray(o) for o in outs], n, None

    @property
    def output_names(self):
        return list(self._ex.output_names)

    def costs(self):
        """{bucket: {"flops", "bytes_accessed"}} for the compiled table."""
        with self._lock:
            return {b: ({"flops": c[0], "bytes_accessed": c[1]}
                        if c else None)
                    for b, (_e, _f, c) in sorted(self._variants.items())}


def tracecheck_programs():
    """graftcheck provider: the serving-shaped eval program — the
    specimen predictor's forward lowered at a bucket batch size, exactly
    what every warmed serving variant is.  Covers the serving tier with
    the JX rules automatically (params stay arguments: JX101 proves no
    weight matrix is baked into the deployable)."""
    from ..predict import _tracecheck_predictor
    pred = _tracecheck_predictor()
    program = PredictProgram(pred, buckets=(4,), name="tracecheck",
                             warmup=False)
    arg_specs, aux_specs, key_spec = program._specs_for(4)
    return [("serving_predict", program._ex._eval_jit,
             (arg_specs, aux_specs, key_spec), {})]
