"""mxnet_tpu.serving: AOT predict programs + continuous batching.

The production serving tier over :class:`mxnet_tpu.predict.Predictor`
(ROADMAP open item 1 — the "millions of users" gap; reference analogue:
the dedicated ``c_predict_api`` deployment ABI, PAPER layer 9):

* :mod:`.program` — per-model **AOT compilation** of the predictor's
  eval program into bucket-padded batch-shape variants from
  ``ShapeDtypeStruct`` specimens.  The request path calls compiled XLA
  executables directly: no jit dispatch, no tracing, retraces
  structurally impossible.  graftcheck covers every serving program
  through the same ``tracecheck_programs()`` provider machinery as the
  training entry points.
* :mod:`.batcher` — a bounded request queue + per-model scheduler with
  **continuous/dynamic batching**: requests coalesce up to the next
  bucket boundary or ``MXNET_SERVE_BATCH_TIMEOUT_MS``, dispatch as
  host-engine tasks serialized on the slot's engine variable, and split
  back per request.  A full queue sheds load (HTTP 503) instead of
  buffering unbounded latency.
* :mod:`.slots` — **multi-tenant model slots**: named load / unload /
  reload of checkpoints with per-model latency percentiles, batch
  occupancy, and MFU accounting.
* :mod:`.http` — the ``/v1`` **ops surface**, served by the PR-4
  introspection server (``MXNET_TELEMETRY_HTTP``): model listing +
  stats, predict, and management actions.
* :mod:`.fleet` + :mod:`.replica` — the **multi-replica serving
  fleet** (ISSUE 13): a router spreading predict over N replica
  processes with least-outstanding balancing, hedged retries, breaker-
  and health-gated failover, and zero-downtime rolling rollout; see
  docs/SERVING.md §fleet.  Imported lazily — single-process serving
  pays nothing for them.

Quick start::

    import mxnet_tpu.serving as serving
    serving.load("mlp", prefix="ckpt/mlp", epoch=3,
                 input_shapes={"data": (1, 784)})
    probs = serving.predict("mlp", {"data": batch})[0]

Env knobs (docs/env_var.md): ``MXNET_SERVE_MAX_BATCH``,
``MXNET_SERVE_BUCKETS``, ``MXNET_SERVE_BATCH_TIMEOUT_MS``,
``MXNET_SERVE_QUEUE_CAP``.  docs/SERVING.md is the guide.
"""
from __future__ import annotations

from . import batcher, http, program, slots                # noqa: F401
from .batcher import ContinuousBatcher, Overloaded         # noqa: F401
from .program import PredictProgram, bucket_sizes          # noqa: F401
from .slots import (ModelRegistry, ModelSlot,              # noqa: F401
                    get_registry, reset_registry)

__all__ = ["PredictProgram", "ContinuousBatcher", "Overloaded",
           "ModelRegistry", "ModelSlot", "bucket_sizes",
           "get_registry", "reset_registry",
           "load", "unload", "reload_model", "predict", "submit",
           "stats", "handle_http", "readiness", "refresh_gauges",
           "refresh_from_env"]


def load(name, **kwargs):
    """Load a checkpoint into the process registry (see
    :meth:`.slots.ModelRegistry.load`)."""
    return get_registry().load(name, **kwargs)


def unload(name, drain=True):
    return get_registry().unload(name, drain=drain)


def reload_model(name, **kwargs):
    return get_registry().reload(name, **kwargs)


def predict(name, inputs, timeout=60.0):
    """Sync predict against a loaded slot: returns the output list."""
    return get_registry().predict(name, inputs, timeout=timeout)


def submit(name, inputs):
    """Async predict: returns the request future."""
    return get_registry().submit(name, inputs)


def stats():
    return get_registry().stats()


def handle_http(method, path, body=None):
    """Entry point the introspection server delegates /v1 paths to."""
    return http.handle(method, path, body)


def readiness():
    """(ok, detail) for the ``/readyz`` endpoint: readiness — distinct
    from ``/healthz`` liveness — is "safe to route NEW traffic here".
    Not ready while any slot is compiling/reloading/draining, while this
    process's replica is warming/reloading/draining, or when this
    process is a fleet router with zero routable replicas.  Observe-only
    (``sys.modules`` lookups; constructs nothing)."""
    import sys
    ok, detail = True, {}
    registry = slots._registry
    if registry is not None:
        slots_ok, slots_detail = registry.readiness()
        detail["slots"] = slots_detail
        ok = ok and slots_ok
    rep_mod = sys.modules.get("mxnet_tpu.serving.replica")
    if rep_mod is not None:
        rep = rep_mod.current_replica()
        if rep is not None:
            detail["replica"] = {"rank": rep.rank, "state": rep.state}
            ok = ok and rep.state == "ready"
    fleet_mod = sys.modules.get("mxnet_tpu.serving.fleet")
    if fleet_mod is not None:
        router = fleet_mod.current_router()
        if router is not None:
            ready = router.ready_count()
            detail["fleet"] = {"replicas_ready": ready,
                               "replicas_total": router.total_count()}
            ok = ok and ready > 0
    return ok, detail


def refresh_gauges():
    """Refresh the aggregate serving gauges (called by the introspection
    sampler through ``sys.modules`` — observe-only, creates nothing)."""
    import sys
    registry = slots._registry
    if registry is not None:
        registry.refresh_gauges()
    fleet_mod = sys.modules.get("mxnet_tpu.serving.fleet")
    if fleet_mod is not None:
        router = fleet_mod.current_router()
        if router is not None:
            router.refresh_gauges()


def refresh_from_env():
    """Re-read every MXNET_SERVE_* / MXNET_FLEET_* knob (tests / live
    reconfig)."""
    import sys
    program.refresh_from_env()
    batcher.refresh_from_env()
    fleet_mod = sys.modules.get("mxnet_tpu.serving.fleet")
    if fleet_mod is not None:
        fleet_mod.refresh_from_env()
