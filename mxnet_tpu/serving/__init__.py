"""mxnet_tpu.serving: AOT predict programs + continuous batching.

The production serving tier over :class:`mxnet_tpu.predict.Predictor`
(ROADMAP open item 1 — the "millions of users" gap; reference analogue:
the dedicated ``c_predict_api`` deployment ABI, PAPER layer 9):

* :mod:`.program` — per-model **AOT compilation** of the predictor's
  eval program into bucket-padded batch-shape variants from
  ``ShapeDtypeStruct`` specimens.  The request path calls compiled XLA
  executables directly: no jit dispatch, no tracing, retraces
  structurally impossible.  graftcheck covers every serving program
  through the same ``tracecheck_programs()`` provider machinery as the
  training entry points.
* :mod:`.batcher` — a bounded request queue + per-model scheduler with
  **continuous/dynamic batching**: requests coalesce up to the next
  bucket boundary or ``MXNET_SERVE_BATCH_TIMEOUT_MS``, dispatch as
  host-engine tasks serialized on the slot's engine variable, and split
  back per request.  A full queue sheds load (HTTP 503) instead of
  buffering unbounded latency.
* :mod:`.slots` — **multi-tenant model slots**: named load / unload /
  reload of checkpoints with per-model latency percentiles, batch
  occupancy, and MFU accounting.
* :mod:`.http` — the ``/v1`` **ops surface**, served by the PR-4
  introspection server (``MXNET_TELEMETRY_HTTP``): model listing +
  stats, predict, and management actions.

Quick start::

    import mxnet_tpu.serving as serving
    serving.load("mlp", prefix="ckpt/mlp", epoch=3,
                 input_shapes={"data": (1, 784)})
    probs = serving.predict("mlp", {"data": batch})[0]

Env knobs (docs/env_var.md): ``MXNET_SERVE_MAX_BATCH``,
``MXNET_SERVE_BUCKETS``, ``MXNET_SERVE_BATCH_TIMEOUT_MS``,
``MXNET_SERVE_QUEUE_CAP``.  docs/SERVING.md is the guide.
"""
from __future__ import annotations

from . import batcher, http, program, slots                # noqa: F401
from .batcher import ContinuousBatcher, Overloaded         # noqa: F401
from .program import PredictProgram, bucket_sizes          # noqa: F401
from .slots import (ModelRegistry, ModelSlot,              # noqa: F401
                    get_registry, reset_registry)

__all__ = ["PredictProgram", "ContinuousBatcher", "Overloaded",
           "ModelRegistry", "ModelSlot", "bucket_sizes",
           "get_registry", "reset_registry",
           "load", "unload", "reload_model", "predict", "submit",
           "stats", "handle_http", "refresh_gauges", "refresh_from_env"]


def load(name, **kwargs):
    """Load a checkpoint into the process registry (see
    :meth:`.slots.ModelRegistry.load`)."""
    return get_registry().load(name, **kwargs)


def unload(name, drain=True):
    return get_registry().unload(name, drain=drain)


def reload_model(name, **kwargs):
    return get_registry().reload(name, **kwargs)


def predict(name, inputs, timeout=60.0):
    """Sync predict against a loaded slot: returns the output list."""
    return get_registry().predict(name, inputs, timeout=timeout)


def submit(name, inputs):
    """Async predict: returns the request future."""
    return get_registry().submit(name, inputs)


def stats():
    return get_registry().stats()


def handle_http(method, path, body=None):
    """Entry point the introspection server delegates /v1 paths to."""
    return http.handle(method, path, body)


def refresh_gauges():
    """Refresh the aggregate serving gauges (called by the introspection
    sampler through ``sys.modules`` — observe-only, creates nothing)."""
    registry = slots._registry
    if registry is not None:
        registry.refresh_gauges()


def refresh_from_env():
    """Re-read every MXNET_SERVE_* knob (tests / live reconfig)."""
    program.refresh_from_env()
    batcher.refresh_from_env()
