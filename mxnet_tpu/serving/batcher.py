"""Continuous batching: a bounded request queue + per-model scheduler.

The throughput unit of a TPU is a well-filled batch; the latency unit of
a service is one request.  The scheduler here converts between them the
way production inference stacks do (and the way the reference's
multi-threaded `c_predict_api` deployments were driven):

* ``submit`` enqueues a request into a **bounded** queue and returns a
  future.  A full queue rejects immediately (:class:`Overloaded` — the
  HTTP tier turns it into a 503) instead of buffering unbounded latency:
  backpressure is the contract, not a failure mode.
* A scheduler thread coalesces whatever is in flight **up to the next
  bucket boundary or a deadline**: it dispatches as soon as the pending
  rows fill the largest bucket (``MXNET_SERVE_MAX_BATCH``), or when the
  oldest pending request has waited ``MXNET_SERVE_BATCH_TIMEOUT_MS``
  (the empty-queue flush).  Requests are never split across batches;
  a request bigger than the largest bucket dispatches alone through the
  program's straight-through path.
* The assembled batch executes as a **host-engine task** serialized on
  the slot's engine variable (write-dependency), so batch k+1 is being
  assembled — and its inputs padded — while batch k still runs: the
  continuous half of continuous batching.  Without the native engine the
  task degrades to inline execution on the scheduler thread, same
  semantics, no pipelining.
* **Failure containment** (docs/FAULT_TOLERANCE.md): each request
  carries a queue **deadline** (``MXNET_SERVE_REQUEST_TIMEOUT_MS``) —
  one the scheduler enforces before dispatch, so a stalled executor
  sheds its backlog as timeouts instead of serving stale work — and a
  **circuit breaker** opens after ``MXNET_SERVE_BREAKER_THRESHOLD``
  consecutive batch failures: while open, ``submit`` sheds immediately
  (:class:`Overloaded` → HTTP 503 + Retry-After) instead of queueing
  doomed work; after ``MXNET_SERVE_BREAKER_COOLDOWN_S`` the next batch
  is the half-open probe.  The :mod:`mxnet_tpu.chaos` ``serving.batch``
  seam injects executor failures to prove both.

Every request/batch is booked into the telemetry registry (counters,
``serving_latency_us`` and ``serving_batch_occupancy`` histograms) and,
per-model, into the slot metrics the ``/v1/models`` endpoint reports.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque

from .. import chaos as _chaos
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..lint import lockwitness as _lockwitness

__all__ = ["Overloaded", "ContinuousBatcher", "CircuitBreaker",
           "refresh_from_env", "DEFAULT_BATCH_TIMEOUT_MS",
           "DEFAULT_QUEUE_CAP", "DEFAULT_BREAKER_THRESHOLD",
           "DEFAULT_BREAKER_COOLDOWN_S"]

DEFAULT_BATCH_TIMEOUT_MS = 5.0
DEFAULT_QUEUE_CAP = 256
DEFAULT_BREAKER_THRESHOLD = 5
DEFAULT_BREAKER_COOLDOWN_S = 5.0


class Overloaded(MXNetError):
    """Bounded queue full or circuit open: shed the request now (HTTP
    503), don't buffer unbounded latency or queue doomed work."""


def _env_timeout_ms():
    try:
        return max(0.0, float(os.environ.get("MXNET_SERVE_BATCH_TIMEOUT_MS",
                                             DEFAULT_BATCH_TIMEOUT_MS)))
    except ValueError:
        return DEFAULT_BATCH_TIMEOUT_MS


def _env_queue_cap():
    try:
        return max(1, int(os.environ.get("MXNET_SERVE_QUEUE_CAP",
                                         DEFAULT_QUEUE_CAP)))
    except ValueError:
        return DEFAULT_QUEUE_CAP


def _env_request_timeout_ms():
    try:
        return max(0.0, float(os.environ.get(
            "MXNET_SERVE_REQUEST_TIMEOUT_MS", 0.0)))
    except ValueError:
        return 0.0


def _env_breaker_threshold():
    try:
        return max(0, int(os.environ.get("MXNET_SERVE_BREAKER_THRESHOLD",
                                         DEFAULT_BREAKER_THRESHOLD)))
    except ValueError:
        return DEFAULT_BREAKER_THRESHOLD


def _env_breaker_cooldown_s():
    try:
        return max(0.0, float(os.environ.get(
            "MXNET_SERVE_BREAKER_COOLDOWN_S", DEFAULT_BREAKER_COOLDOWN_S)))
    except ValueError:
        return DEFAULT_BREAKER_COOLDOWN_S


# cached at import (JG006 cached-value pattern)
_TIMEOUT_MS = _env_timeout_ms()
_QUEUE_CAP = _env_queue_cap()
_REQUEST_TIMEOUT_MS = _env_request_timeout_ms()
_BREAKER_THRESHOLD = _env_breaker_threshold()
_BREAKER_COOLDOWN_S = _env_breaker_cooldown_s()


def refresh_from_env():
    global _TIMEOUT_MS, _QUEUE_CAP, _REQUEST_TIMEOUT_MS
    global _BREAKER_THRESHOLD, _BREAKER_COOLDOWN_S
    _TIMEOUT_MS = _env_timeout_ms()
    _QUEUE_CAP = _env_queue_cap()
    _REQUEST_TIMEOUT_MS = _env_request_timeout_ms()
    _BREAKER_THRESHOLD = _env_breaker_threshold()
    _BREAKER_COOLDOWN_S = _env_breaker_cooldown_s()


class CircuitBreaker:
    """Consecutive-failure breaker: *threshold* straight batch failures
    open it for *cooldown_s*; while open, submissions shed (503).  After
    the cooldown the next batch is the half-open probe — success closes
    the breaker, failure re-opens (and re-arms the cooldown).  A
    threshold of 0 disables the breaker entirely."""

    def __init__(self, threshold=None, cooldown_s=None):
        self.threshold = _BREAKER_THRESHOLD if threshold is None \
            else max(0, int(threshold))
        self.cooldown_s = _BREAKER_COOLDOWN_S if cooldown_s is None \
            else max(0.0, float(cooldown_s))
        self._failures = 0
        self._opened_at = None
        self._probing = False
        self._probe_started = 0.0
        self._lock = _lockwitness.make_lock("CircuitBreaker._lock")

    def allow(self):
        if not self.threshold:
            return True
        now = time.monotonic()
        with self._lock:
            if self._opened_at is None:
                return True
            if self._probing:
                # ONE probe in flight decides; the rest stay shed until
                # record() resolves it.  The probe holds a bounded lease
                # so a probe that dies un-run (queue deadline drop)
                # cannot wedge the breaker open forever.
                if now - self._probe_started \
                        < max(self.cooldown_s, 1.0):
                    return False
            if now - self._opened_at >= self.cooldown_s:
                self._probing = True
                self._probe_started = now
                return True
            return False

    def record(self, ok):
        if not self.threshold:
            return
        with self._lock:
            self._probing = False
            if ok:
                self._failures = 0
                self._opened_at = None
                return
            self._failures += 1
            if self._failures >= self.threshold:
                if self._opened_at is None:
                    _telemetry.bump("serving_breaker_opens")
                self._opened_at = time.monotonic()   # re-arm cooldown

    def state(self):
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if self._probing \
                    or time.monotonic() - self._opened_at >= self.cooldown_s:
                return "half-open"
            return "open"

    def retry_after_s(self):
        with self._lock:
            if self._opened_at is None:
                return 0.0
            return max(0.0, self.cooldown_s
                       - (time.monotonic() - self._opened_at))


class _Request:
    """One in-flight predict request: host inputs + a completion event.
    *deadline* (perf_counter seconds, None = none) bounds its QUEUE
    time: the scheduler drops it un-run once passed.

    Every request carries a *trace_id* (surfaced in the HTTP response)
    and, once dispatched, its span decomposition in *segments*:
    ``queue_wait_us`` (submit → batch dispatch, per request) plus the
    shared batch segments ``pad_us`` / ``execute_us`` / ``slice_us`` —
    what lets serve_bench attribute a p99 to queueing vs execution."""

    __slots__ = ("inputs", "n", "t_submit", "t_done", "outputs", "error",
                 "deadline", "trace_id", "segments", "_done")

    def __init__(self, inputs, n, timeout_s=None):
        self.inputs = inputs
        self.n = n
        self.t_submit = time.perf_counter()
        self.t_done = None
        self.outputs = None
        self.error = None
        self.deadline = None if not timeout_s \
            else self.t_submit + timeout_s
        # adopt the ambient trace id when one exists (a fleet-routed
        # predict: the router's id rode the wire and the replica's
        # handler thread adopted it) so router span, rpc events, and
        # this request's batch spans merge end-to-end; otherwise mint
        self.trace_id = _telemetry.trace_context() \
            or _telemetry.new_trace_id()
        self.segments = {}
        self._done = threading.Event()

    def wait(self, timeout=None):
        """Block for the result; raises the request's error if it failed."""
        if not self._done.wait(timeout):
            raise MXNetError("predict request timed out after %ss"
                             % timeout)
        if self.error is not None:
            raise self.error
        return self.outputs

    def done(self):
        return self._done.is_set()

    @property
    def latency_us(self):
        """Submit-to-completion latency (in-flight: elapsed so far)."""
        end = self.t_done if self.t_done is not None else time.perf_counter()
        return (end - self.t_submit) * 1e6

    def _finish(self, outputs=None, error=None):
        if self.t_done is None:      # dispatcher may have stamped it
            self.t_done = time.perf_counter()
        self.outputs = outputs
        self.error = error
        self._done.set()


class ContinuousBatcher:
    """The per-model queue + scheduler thread (owned by a ModelSlot)."""

    def __init__(self, program, name, metrics=None, queue_cap=None,
                 timeout_ms=None, use_engine=True,
                 request_timeout_ms=None, breaker=None):
        self._program = program
        self._name = name
        self._metrics = metrics
        self._cap = _QUEUE_CAP if queue_cap is None else max(1, queue_cap)
        timeout_ms = _TIMEOUT_MS if timeout_ms is None else timeout_ms
        self._timeout_s = max(0.0, timeout_ms) / 1e3
        request_timeout_ms = _REQUEST_TIMEOUT_MS \
            if request_timeout_ms is None else max(0.0, request_timeout_ms)
        self._request_timeout_s = request_timeout_ms / 1e3
        self._breaker = CircuitBreaker() if breaker is None else breaker
        self._queue = deque()
        self._cond = _lockwitness.make_condition(
            name="ContinuousBatcher._cond")
        self._stopping = False
        self._use_engine = use_engine
        self._eng = None
        self._var = None
        self._thread = threading.Thread(
            target=self._loop, name="mxnet-serve-%s" % name, daemon=True)

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._use_engine:
            try:
                from .. import engine as _engine
                self._eng = _engine.engine()
                self._var = self._eng.new_variable()
            except Exception:        # engine unavailable: inline dispatch
                self._eng = None
                self._var = None
        self._thread.start()
        return self

    def stop(self, drain=True, timeout=30.0):
        """Stop the scheduler.  *drain* processes what is queued first;
        otherwise pending requests fail with an unload error."""
        with self._cond:
            self._stopping = True
            if not drain:
                dropped, self._queue = list(self._queue), deque()
            else:
                dropped = []
            self._cond.notify_all()
        for req in dropped:
            req._finish(error=MXNetError(
                "model %r unloaded before the request ran" % self._name))
        if self._thread.is_alive():
            self._thread.join(timeout)
        if self._eng is not None and self._var is not None:
            try:
                self._eng.wait_for_var(self._var)
                self._eng.delete_variable(self._var)
            except Exception:
                pass
            self._var = None

    def set_program(self, program):
        """Hot-swap the compiled program table (ModelSlot.reload): takes
        effect at the next batch boundary."""
        with self._cond:
            self._program = program

    # -- client side -------------------------------------------------------

    def queue_depth(self):
        with self._cond:
            return len(self._queue)

    def breaker_state(self):
        """'closed' / 'open' / 'half-open' (the /v1 stats surface)."""
        return self._breaker.state()

    def submit(self, inputs, n, timeout_ms=None):
        """Enqueue *n* rows; returns the request future.  Raises
        :class:`Overloaded` when the bounded queue is full or the
        circuit breaker is open.  *timeout_ms* overrides the request's
        queue deadline (default ``MXNET_SERVE_REQUEST_TIMEOUT_MS``;
        0 = no deadline)."""
        if not self._breaker.allow():
            if self._metrics is not None:
                self._metrics.count("breaker_shed")
            _telemetry.bump("serving_breaker_shed")
            raise Overloaded(
                "circuit breaker open for %r after repeated executor "
                "failures; retry in %.1fs"
                % (self._name, self._breaker.retry_after_s()))
        timeout_s = self._request_timeout_s if timeout_ms is None \
            else max(0.0, timeout_ms) / 1e3
        req = _Request(inputs, n, timeout_s=timeout_s)
        with self._cond:
            if self._stopping:
                raise MXNetError("model %r is unloading" % self._name)
            if len(self._queue) >= self._cap:
                if self._metrics is not None:
                    self._metrics.count("overloads")
                _telemetry.bump("serving_overloads")
                raise Overloaded(
                    "serving queue for %r is full (%d requests); "
                    "retry later" % (self._name, self._cap))
            self._queue.append(req)
            self._cond.notify_all()
        if self._metrics is not None:
            self._metrics.count("requests")
        _telemetry.bump("serving_requests")
        return req

    # -- scheduler ---------------------------------------------------------

    def _packable_rows(self):
        """Rows the head of the queue can contribute to ONE batch (whole
        requests only, capped at max_batch; an oversize head saturates)."""
        max_b = self._program.max_batch
        total = 0
        for req in self._queue:
            if req.n > max_b:
                return max_b if total == 0 else total
            if total + req.n > max_b:
                return total
            total += req.n
        return total

    def _take_batch(self):
        """Pop the requests forming the next batch (caller holds _cond)."""
        max_b = self._program.max_batch
        batch, total = [], 0
        while self._queue:
            req = self._queue[0]
            if req.n > max_b:
                if batch:
                    break                     # oversize goes alone, next
                batch.append(self._queue.popleft())
                total = req.n
                break
            if total + req.n > max_b:
                break
            batch.append(self._queue.popleft())
            total += req.n
        return batch, total

    def _drop_expired(self):
        """Purge requests whose queue deadline passed (caller holds
        _cond); returns them for out-of-lock completion.  Dropping
        BEFORE dispatch is the point: a recovering executor must chew
        through live work, not a backlog nobody is waiting on."""
        now = time.perf_counter()
        if not any(r.deadline is not None and now > r.deadline
                   for r in self._queue):
            return []
        kept, dropped = deque(), []
        for req in self._queue:
            if req.deadline is not None and now > req.deadline:
                dropped.append(req)
            else:
                kept.append(req)
        self._queue = kept
        return dropped

    def _fail_expired(self, dropped):
        for req in dropped:
            _telemetry.bump("serving_deadline_drops")
            if self._metrics is not None:
                self._metrics.count("deadline_drops")
            req._finish(error=MXNetError(
                "request timed out in the %r queue after %.0f ms "
                "(deadline exceeded before dispatch)"
                % (self._name, req.latency_us / 1e3)))

    def _loop(self):
        while True:
            with self._cond:
                while not self._queue and not self._stopping:
                    self._cond.wait()
                if not self._queue and self._stopping:
                    return
                # coalesce: dispatch when the pending rows fill the top
                # bucket, or when the oldest request's deadline lapses
                # (the empty-queue timeout flush)
                deadline = self._queue[0].t_submit + self._timeout_s
                while (not self._stopping
                       and self._packable_rows() < self._program.max_batch):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                expired = self._drop_expired()
                batch, total = self._take_batch()
                program = self._program
            if expired:
                self._fail_expired(expired)
            if batch:
                self._dispatch(program, batch, total)

    def _dispatch(self, program, batch, total):
        """Hand one assembled batch to the host engine (serialized on the
        slot variable) or run it inline when no engine is available."""
        task = lambda: self._run_batch(program, batch, total)  # noqa: E731
        if self._eng is not None and self._var is not None:
            try:
                self._eng.push(task, mutable_vars=(self._var,),
                               tag="serving:%s" % self._name)
                return
            except Exception:      # engine shutting down: degrade inline
                pass
        task()

    def _run_batch(self, program, batch, total):
        """Execute one coalesced batch and split results per request.
        Never raises: failures land in the request futures."""
        # per-request queue-wait resolves at dispatch, before any work:
        # the decomposition must hold even when the batch then fails
        t_dispatch = time.perf_counter()
        for req in batch:
            wait_us = (t_dispatch - req.t_submit) * 1e6
            req.segments["queue_wait_us"] = wait_us
            _telemetry.observe("serving_queue_wait_us", wait_us)
            if self._metrics is not None:
                self._metrics.queue_wait(wait_us)
        timings = {}
        trace_ids = [req.trace_id for req in batch]
        try:
            with _telemetry.span("serving_run_batch", cat="serving",
                                 args={"rows": total,
                                       "requests": len(batch),
                                       "trace_ids": trace_ids}):
                if _chaos.active():
                    act = _chaos.decide("serving.batch")
                    if act is not None:
                        _chaos.apply_inline(act)
                if len(batch) == 1:
                    inputs = batch[0].inputs
                else:
                    import numpy as np
                    names = list(batch[0].inputs)
                    inputs = {name: np.concatenate(
                        [req.inputs[name] for req in batch], axis=0)
                        for name in names}
                if total > program.max_batch:
                    outs, bucket, cost = program.run_straight(
                        inputs, total)
                else:
                    outs, bucket, cost = program.run(inputs, total,
                                                     timings=timings)
        except BaseException as exc:  # noqa: BLE001 — futures carry it
            self._breaker.record(ok=False)
            if self._metrics is not None:
                self._metrics.count("errors", len(batch))
            _telemetry.bump("serving_errors", len(batch))
            err = exc if isinstance(exc, MXNetError) else MXNetError(
                "predict batch failed: %r" % (exc,))
            for req in batch:
                req._finish(error=err)
            return
        self._breaker.record(ok=True)
        self._book_segments(batch, timings, trace_ids)
        # book ALL accounting BEFORE waking any waiter: a client reading
        # counters/stats the instant predict() returns must see this
        # batch (the futures' latency stamp is taken here, so the booked
        # number is the one the waiter observes)
        offset, slices = 0, []
        for req in batch:
            slices.append([o[offset:offset + req.n] for o in outs])
            offset += req.n
            req.t_done = time.perf_counter()
            latency = req.latency_us
            _telemetry.observe("serving_latency_us", latency)
            if self._metrics is not None:
                self._metrics.latency(latency)
        occupancy = 100.0 * total / max(bucket, total)
        _telemetry.bump("serving_batches")
        _telemetry.observe("serving_batch_occupancy", occupancy)
        if self._metrics is not None:
            self._metrics.batch(rows=total, bucket=bucket,
                                padded=max(0, bucket - total),
                                cost=cost, n_requests=len(batch))
        for req, outputs in zip(batch, slices):
            req._finish(outputs=outputs)

    def _book_segments(self, batch, timings, trace_ids):
        """Attach the batch's pad/execute/slice segments to every rider
        and land them as child trace events under serving_run_batch."""
        if not timings:
            return                    # straight-through path: no pads
        execute_us = timings.get("execute_us", 0.0)
        _telemetry.observe("serving_execute_us", execute_us)
        for req in batch:
            req.segments.update(timings)
        if self._metrics is not None:
            self._metrics.execute(execute_us)
        if not _telemetry.trace_active():
            return
        # reconstruct the child spans from the measured segment walls:
        # they tile the tail of the batch span ending now
        end = _telemetry.now_us()
        args = {"trace_ids": trace_ids}
        t_slice = end - timings.get("slice_us", 0.0)
        t_exec = t_slice - execute_us
        t_pad = t_exec - timings.get("pad_us", 0.0)
        _telemetry.add_event("serving_pad", "serving", t_pad,
                             timings.get("pad_us", 0.0), args=args)
        _telemetry.add_event("serving_execute", "serving", t_exec,
                             execute_us,
                             args=dict(args, device_blocked=timings.get(
                                 "device_blocked", False)))
        _telemetry.add_event("serving_slice", "serving", t_slice,
                             timings.get("slice_us", 0.0), args=args)
