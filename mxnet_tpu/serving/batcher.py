"""Continuous batching: a bounded request queue + per-model scheduler.

The throughput unit of a TPU is a well-filled batch; the latency unit of
a service is one request.  The scheduler here converts between them the
way production inference stacks do (and the way the reference's
multi-threaded `c_predict_api` deployments were driven):

* ``submit`` enqueues a request into a **bounded** queue and returns a
  future.  A full queue rejects immediately (:class:`Overloaded` — the
  HTTP tier turns it into a 503) instead of buffering unbounded latency:
  backpressure is the contract, not a failure mode.
* A scheduler thread coalesces whatever is in flight **up to the next
  bucket boundary or a deadline**: it dispatches as soon as the pending
  rows fill the largest bucket (``MXNET_SERVE_MAX_BATCH``), or when the
  oldest pending request has waited ``MXNET_SERVE_BATCH_TIMEOUT_MS``
  (the empty-queue flush).  Requests are never split across batches;
  a request bigger than the largest bucket dispatches alone through the
  program's straight-through path.
* The assembled batch executes as a **host-engine task** serialized on
  the slot's engine variable (write-dependency), so batch k+1 is being
  assembled — and its inputs padded — while batch k still runs: the
  continuous half of continuous batching.  Without the native engine the
  task degrades to inline execution on the scheduler thread, same
  semantics, no pipelining.

Every request/batch is booked into the telemetry registry (counters,
``serving_latency_us`` and ``serving_batch_occupancy`` histograms) and,
per-model, into the slot metrics the ``/v1/models`` endpoint reports.
"""
from __future__ import annotations

import os
import threading
import time
from collections import deque

from .. import telemetry as _telemetry
from ..base import MXNetError

__all__ = ["Overloaded", "ContinuousBatcher", "refresh_from_env",
           "DEFAULT_BATCH_TIMEOUT_MS", "DEFAULT_QUEUE_CAP"]

DEFAULT_BATCH_TIMEOUT_MS = 5.0
DEFAULT_QUEUE_CAP = 256


class Overloaded(MXNetError):
    """Bounded queue full: shed the request now (HTTP 503), don't buffer
    unbounded latency."""


def _env_timeout_ms():
    try:
        return max(0.0, float(os.environ.get("MXNET_SERVE_BATCH_TIMEOUT_MS",
                                             DEFAULT_BATCH_TIMEOUT_MS)))
    except ValueError:
        return DEFAULT_BATCH_TIMEOUT_MS


def _env_queue_cap():
    try:
        return max(1, int(os.environ.get("MXNET_SERVE_QUEUE_CAP",
                                         DEFAULT_QUEUE_CAP)))
    except ValueError:
        return DEFAULT_QUEUE_CAP


# cached at import (JG006 cached-value pattern)
_TIMEOUT_MS = _env_timeout_ms()
_QUEUE_CAP = _env_queue_cap()


def refresh_from_env():
    global _TIMEOUT_MS, _QUEUE_CAP
    _TIMEOUT_MS = _env_timeout_ms()
    _QUEUE_CAP = _env_queue_cap()


class _Request:
    """One in-flight predict request: host inputs + a completion event."""

    __slots__ = ("inputs", "n", "t_submit", "t_done", "outputs", "error",
                 "_done")

    def __init__(self, inputs, n):
        self.inputs = inputs
        self.n = n
        self.t_submit = time.perf_counter()
        self.t_done = None
        self.outputs = None
        self.error = None
        self._done = threading.Event()

    def wait(self, timeout=None):
        """Block for the result; raises the request's error if it failed."""
        if not self._done.wait(timeout):
            raise MXNetError("predict request timed out after %ss"
                             % timeout)
        if self.error is not None:
            raise self.error
        return self.outputs

    def done(self):
        return self._done.is_set()

    @property
    def latency_us(self):
        """Submit-to-completion latency (in-flight: elapsed so far)."""
        end = self.t_done if self.t_done is not None else time.perf_counter()
        return (end - self.t_submit) * 1e6

    def _finish(self, outputs=None, error=None):
        if self.t_done is None:      # dispatcher may have stamped it
            self.t_done = time.perf_counter()
        self.outputs = outputs
        self.error = error
        self._done.set()


class ContinuousBatcher:
    """The per-model queue + scheduler thread (owned by a ModelSlot)."""

    def __init__(self, program, name, metrics=None, queue_cap=None,
                 timeout_ms=None, use_engine=True):
        self._program = program
        self._name = name
        self._metrics = metrics
        self._cap = _QUEUE_CAP if queue_cap is None else max(1, queue_cap)
        timeout_ms = _TIMEOUT_MS if timeout_ms is None else timeout_ms
        self._timeout_s = max(0.0, timeout_ms) / 1e3
        self._queue = deque()
        self._cond = threading.Condition()
        self._stopping = False
        self._use_engine = use_engine
        self._eng = None
        self._var = None
        self._thread = threading.Thread(
            target=self._loop, name="mxnet-serve-%s" % name, daemon=True)

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        if self._use_engine:
            try:
                from .. import engine as _engine
                self._eng = _engine.engine()
                self._var = self._eng.new_variable()
            except Exception:        # engine unavailable: inline dispatch
                self._eng = None
                self._var = None
        self._thread.start()
        return self

    def stop(self, drain=True, timeout=30.0):
        """Stop the scheduler.  *drain* processes what is queued first;
        otherwise pending requests fail with an unload error."""
        with self._cond:
            self._stopping = True
            if not drain:
                dropped, self._queue = list(self._queue), deque()
            else:
                dropped = []
            self._cond.notify_all()
        for req in dropped:
            req._finish(error=MXNetError(
                "model %r unloaded before the request ran" % self._name))
        if self._thread.is_alive():
            self._thread.join(timeout)
        if self._eng is not None and self._var is not None:
            try:
                self._eng.wait_for_var(self._var)
                self._eng.delete_variable(self._var)
            except Exception:
                pass
            self._var = None

    def set_program(self, program):
        """Hot-swap the compiled program table (ModelSlot.reload): takes
        effect at the next batch boundary."""
        with self._cond:
            self._program = program

    # -- client side -------------------------------------------------------

    def queue_depth(self):
        with self._cond:
            return len(self._queue)

    def submit(self, inputs, n):
        """Enqueue *n* rows; returns the request future.  Raises
        :class:`Overloaded` when the bounded queue is full."""
        req = _Request(inputs, n)
        with self._cond:
            if self._stopping:
                raise MXNetError("model %r is unloading" % self._name)
            if len(self._queue) >= self._cap:
                if self._metrics is not None:
                    self._metrics.count("overloads")
                _telemetry.bump("serving_overloads")
                raise Overloaded(
                    "serving queue for %r is full (%d requests); "
                    "retry later" % (self._name, self._cap))
            self._queue.append(req)
            self._cond.notify_all()
        if self._metrics is not None:
            self._metrics.count("requests")
        _telemetry.bump("serving_requests")
        return req

    # -- scheduler ---------------------------------------------------------

    def _packable_rows(self):
        """Rows the head of the queue can contribute to ONE batch (whole
        requests only, capped at max_batch; an oversize head saturates)."""
        max_b = self._program.max_batch
        total = 0
        for req in self._queue:
            if req.n > max_b:
                return max_b if total == 0 else total
            if total + req.n > max_b:
                return total
            total += req.n
        return total

    def _take_batch(self):
        """Pop the requests forming the next batch (caller holds _cond)."""
        max_b = self._program.max_batch
        batch, total = [], 0
        while self._queue:
            req = self._queue[0]
            if req.n > max_b:
                if batch:
                    break                     # oversize goes alone, next
                batch.append(self._queue.popleft())
                total = req.n
                break
            if total + req.n > max_b:
                break
            batch.append(self._queue.popleft())
            total += req.n
        return batch, total

    def _loop(self):
        while True:
            with self._cond:
                while not self._queue and not self._stopping:
                    self._cond.wait()
                if not self._queue and self._stopping:
                    return
                # coalesce: dispatch when the pending rows fill the top
                # bucket, or when the oldest request's deadline lapses
                # (the empty-queue timeout flush)
                deadline = self._queue[0].t_submit + self._timeout_s
                while (not self._stopping
                       and self._packable_rows() < self._program.max_batch):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(remaining)
                batch, total = self._take_batch()
                program = self._program
            if batch:
                self._dispatch(program, batch, total)

    def _dispatch(self, program, batch, total):
        """Hand one assembled batch to the host engine (serialized on the
        slot variable) or run it inline when no engine is available."""
        task = lambda: self._run_batch(program, batch, total)  # noqa: E731
        if self._eng is not None and self._var is not None:
            try:
                self._eng.push(task, mutable_vars=(self._var,),
                               tag="serving:%s" % self._name)
                return
            except Exception:      # engine shutting down: degrade inline
                pass
        task()

    def _run_batch(self, program, batch, total):
        """Execute one coalesced batch and split results per request.
        Never raises: failures land in the request futures."""
        try:
            if len(batch) == 1:
                inputs = batch[0].inputs
            else:
                import numpy as np
                names = list(batch[0].inputs)
                inputs = {name: np.concatenate(
                    [req.inputs[name] for req in batch], axis=0)
                    for name in names}
            if total > program.max_batch:
                outs, bucket, cost = program.run_straight(inputs, total)
            else:
                outs, bucket, cost = program.run(inputs, total)
        except BaseException as exc:  # noqa: BLE001 — futures carry it
            if self._metrics is not None:
                self._metrics.count("errors", len(batch))
            _telemetry.bump("serving_errors", len(batch))
            err = exc if isinstance(exc, MXNetError) else MXNetError(
                "predict batch failed: %r" % (exc,))
            for req in batch:
                req._finish(error=err)
            return
        # book ALL accounting BEFORE waking any waiter: a client reading
        # counters/stats the instant predict() returns must see this
        # batch (the futures' latency stamp is taken here, so the booked
        # number is the one the waiter observes)
        offset, slices = 0, []
        for req in batch:
            slices.append([o[offset:offset + req.n] for o in outs])
            offset += req.n
            req.t_done = time.perf_counter()
            latency = req.latency_us
            _telemetry.observe("serving_latency_us", latency)
            if self._metrics is not None:
                self._metrics.latency(latency)
        occupancy = 100.0 * total / max(bucket, total)
        _telemetry.bump("serving_batches")
        _telemetry.observe("serving_batch_occupancy", occupancy)
        if self._metrics is not None:
            self._metrics.batch(rows=total, bucket=bucket,
                                padded=max(0, bucket - total),
                                cost=cost, n_requests=len(batch))
        for req, outputs in zip(batch, slices):
            req._finish(outputs=outputs)
