"""The /v1 ops surface: model management + predict over the PR-4 server.

The introspection server (``telemetry/server.py``) owns the socket and
the localhost-only policy; this module owns the routes.  The server
delegates any ``/v1/...`` path here through ``sys.modules`` — a process
that never imported ``mxnet_tpu.serving`` answers 404 with a hint and
pays nothing, preserving the server's observe-only contract.  The one
exception is ``POST .../load``, which the server routes through
:func:`mxnet_tpu.serving.handle_http` after importing the package —
an explicit operator action is allowed to initialize the serving tier.

Routes (all JSON):

    GET  /v1/models                        every slot's stats
    GET  /v1/models/<name>                 one slot's stats
    POST /v1/models/<name>/predict         {"inputs": {name: [[...]]}}
                                           (or the input dict directly)
    POST /v1/models/<name>/load            {"prefix", "epoch",
                                            "input_shapes", "buckets"?}
    POST /v1/models/<name>/unload          {}
    POST /v1/models/<name>/reload          {"prefix"?, "epoch"?}

When this process runs a started :class:`~.fleet.FleetRouter`, the same
surface fronts the whole fleet instead of local slots: predict routes
through the balancer (hedged/failed-over; the response names the serving
replica), reload runs the zero-downtime rolling rollout across every
ready replica, and GET returns the fleet table.  load/unload stay
per-replica operations (400 on the router).

Status codes are the contract the load generator and any real LB probe
rely on: 200 ok, 400 malformed, 404 unknown model/route, 503 overloaded
(bounded queue full — retry later), 500 internal.
"""
from __future__ import annotations

import json
import sys

import numpy as np

from ..base import MXNetError
from .batcher import Overloaded
from .slots import get_registry

__all__ = ["handle"]


def _current_router():
    """The process's FleetRouter, or None — a ``sys.modules`` lookup so
    a process that never imported the fleet tier pays nothing."""
    fleet_mod = sys.modules.get("mxnet_tpu.serving.fleet")
    return fleet_mod.current_router() if fleet_mod is not None else None


def _json(code, obj):
    return code, "application/json", json.dumps(obj, default=repr)


def _error(code, message):
    return _json(code, {"error": message})


def handle(method, path, body=None):
    """Dispatch one /v1 request; returns (status, content_type, payload).
    Never raises — the server's handler just writes what it gets."""
    try:
        return _route(method, path, body)
    except Overloaded as exc:
        return _error(503, str(exc))
    except MXNetError as exc:
        message = str(exc)
        if "is not loaded" in message:
            return _error(404, message)
        if "timed out" in message:
            # capacity, not a malformed request: retryable for an LB
            return _error(504, message)
        return _error(400, message)
    except Exception as exc:   # ops surface never takes the process down
        return _error(500, "serving error: %r" % (exc,))


def _route(method, path, body):
    parts = [p for p in path.split("/") if p]      # ["v1", "models", ...]
    if len(parts) < 2 or parts[0] != "v1" or parts[1] != "models":
        return _error(404, "unknown route %r" % path)
    router = _current_router()
    registry = get_registry()
    if len(parts) == 2:
        if method != "GET":
            return _error(400, "use GET on /v1/models")
        if router is not None:
            return _json(200, {"models": registry.stats(),
                               "fleet": router.http_view()})
        return _json(200, {"models": registry.stats()})
    name = parts[2]
    if len(parts) == 3:
        if method != "GET":
            return _error(400, "use GET on /v1/models/<name>")
        if router is not None:
            view = router.http_view()
            if name not in view["models"]:
                return _error(404, "model %r is not loaded on any "
                                   "routable replica" % name)
            return _json(200, {name: {"fleet": view}})
        return _json(200, {name: registry.get(name).stats()})
    action = parts[3]
    if len(parts) > 4:
        return _error(404, "unknown route %r" % path)
    if action == "predict":
        if method != "POST":
            return _error(400, "predict is POST-only")
        if router is not None:
            return _fleet_predict(router, name, body)
        return _predict(registry, name, body)
    if method != "POST":
        return _error(400, "%s is POST-only" % action)
    if action == "reload":
        spec = _parse_body(body)
        if router is not None:
            results = router.rolling_reload(name,
                                            prefix=spec.get("prefix"),
                                            epoch=spec.get("epoch"))
            ok = all(v == "ok" for v in results.values())
            return _json(200 if ok else 500,
                         {"reloaded": name,
                          "replicas": {str(r): v
                                       for r, v in results.items()},
                          "ok": ok})
        registry.reload(name, prefix=spec.get("prefix"),
                        epoch=spec.get("epoch"))
        return _json(200, {"reloaded": name})
    if router is not None:
        return _error(400, "%s is a per-replica operation; the fleet "
                           "router only routes predict and rolling "
                           "reload" % action)
    if action == "load":
        return _load(registry, name, body)
    if action == "unload":
        registry.unload(name)
        return _json(200, {"unloaded": name})
    return _error(404, "unknown action %r" % action)


def _fleet_predict(router, name, body):
    """Router-mode predict: parse like the local path, route through the
    fleet balancer, answer with the serving replica's identity."""
    obj = _parse_body(body)
    raw = obj.get("inputs", obj)
    if not isinstance(raw, dict) or not raw:
        raise MXNetError(
            'predict body must be {"inputs": {name: [[...]], ...}}')
    timeout = _number(obj, "timeout_s")
    inputs = {}
    for key, val in raw.items():
        if key in ("inputs", "timeout_s", "deadline_ms"):
            continue
        try:
            arr = np.asarray(val)
            if arr.dtype == np.float64:     # replicas re-cast anyway;
                arr = arr.astype(np.float32)  # don't ship double bytes
            inputs[key] = arr
        except (TypeError, ValueError) as exc:
            raise MXNetError("input %r is not a numeric array: %s"
                             % (key, exc))
    outs, meta = router.predict_detail(name, inputs, timeout_s=timeout)
    rows = int(next(iter(inputs.values())).shape[0])
    return _json(200, {
        "model": name,
        "batch": rows,
        "latency_us": round(meta["latency_us"], 1),
        "replica": meta["replica"],
        "attempts": meta["attempts"],
        "hedged": meta["hedged_win"],
        "outputs": {out_name: np.asarray(out).tolist()
                    for out_name, out in zip(meta["output_names"],
                                             outs)},
    })


def _parse_body(body):
    if not body:
        return {}
    try:
        obj = json.loads(body)
    except ValueError as exc:
        raise MXNetError("request body is not JSON: %s" % exc)
    if not isinstance(obj, dict):
        raise MXNetError("request body must be a JSON object")
    return obj


def _predict(registry, name, body):
    slot = registry.get(name)
    obj = _parse_body(body)
    raw = obj.get("inputs", obj)
    if not isinstance(raw, dict) or not raw:
        raise MXNetError(
            'predict body must be {"inputs": {name: [[...]], ...}}')
    timeout = _number(obj, "timeout_s", 60.0)
    # queue deadline: how long the request may WAIT before dispatch
    # (docs/FAULT_TOLERANCE.md; default MXNET_SERVE_REQUEST_TIMEOUT_MS)
    deadline_ms = _number(obj, "deadline_ms")
    inputs = {}
    for key, val in raw.items():
        if key in ("inputs", "timeout_s", "deadline_ms"):
            continue
        dtype = slot.program._ex.arg_dict[key].dtype \
            if key in slot.program._ex.arg_dict else np.float32
        try:
            inputs[key] = np.asarray(val, dtype)
        except (TypeError, ValueError) as exc:
            raise MXNetError("input %r is not a numeric array: %s"
                             % (key, exc))
    request = slot.submit(inputs, timeout_ms=deadline_ms)
    outs = request.wait(timeout)
    return _json(200, {
        "model": name,
        "batch": request.n,
        "latency_us": round(request.latency_us, 1),
        # request tracing: the id joins this request to its spans in the
        # process trace (/trace) and any fleet-merged timeline; the
        # segments say where the latency went (queue vs pad/execute/
        # slice — the batch-shared segments ride on every coalesced
        # member)
        "trace_id": request.trace_id,
        "spans": {key: round(val, 1) if isinstance(val, float) else val
                  for key, val in sorted(request.segments.items())},
        "outputs": {out_name: out.tolist() for out_name, out
                    in zip(slot.program.output_names, outs)},
    })


def _number(spec, key, default=None):
    """Client-controlled numeric field: a bad value is a 400 (malformed
    request), never a 500 (server fault a balancer would retry)."""
    val = spec.get(key, default)
    if val is None:
        return None
    try:
        return float(val)
    except (TypeError, ValueError):
        raise MXNetError("%r must be a number, got %r" % (key, val))


def _load(registry, name, body):
    spec = _parse_body(body)
    if "prefix" not in spec or "input_shapes" not in spec:
        raise MXNetError(
            'load body needs {"prefix": ..., "epoch": ..., '
            '"input_shapes": {name: [dims]}}')
    try:
        shapes = {k: tuple(int(d) for d in v)
                  for k, v in spec["input_shapes"].items()}
        buckets = spec.get("buckets")
        if buckets is not None:
            buckets = [int(b) for b in buckets]
    except (TypeError, ValueError) as exc:
        raise MXNetError("malformed load body: %s" % exc)
    epoch = _number(spec, "epoch", 0)
    max_batch = _number(spec, "max_batch")
    queue_cap = _number(spec, "queue_cap")
    slot = registry.load(
        name, prefix=spec["prefix"], epoch=int(epoch),
        input_shapes=shapes, buckets=buckets,
        max_batch=None if max_batch is None else int(max_batch),
        queue_cap=None if queue_cap is None else int(queue_cap),
        timeout_ms=_number(spec, "timeout_ms"))
    return _json(200, {"loaded": name,
                       "buckets": list(slot.program.buckets)})
