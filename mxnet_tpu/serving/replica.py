"""One serving replica: a slot-table process on the dist_ps transport.

A replica is the fleet's unit of capacity and of failure: an ordinary
:class:`~.slots.ModelRegistry` (AOT bucket tables + continuous batchers,
exactly the PR-6 single-process serving tier) wrapped in a wire server
speaking the hardened :class:`mxnet_tpu.dist_ps.Conn` protocol
(length-prefixed, magic/version-checked, restricted-unpickler payloads)
so the :class:`~.fleet.FleetRouter` can spread predict traffic over N of
them and kill -9 any one without losing accepted requests.

Lifecycle / readiness state machine (what ``/readyz`` and the router's
routing decision key off)::

    starting ──register──▶ warming ──slots compiled──▶ ready
        ready ──reload RPC──▶ reloading ──swap done──▶ ready
        ready ──drain RPC───▶ draining (in-flight finishes, no new work)

A replica registers with its router *before* warming (so the fleet view
shows it coming up), but advertises ``ready`` only after every slot's
bucket table is compiled — warm loads come from the checkpoint tier (the
same ``save_checkpoint`` artifacts ``ModelRegistry.load`` already
consumes), so a restarted replica re-registers into its dead rank,
recompiles, and only then takes traffic.  Heartbeats ride a dedicated
router connection (``MXNET_FLEET_HEARTBEAT_S``) carrying the current
state, so the router's view converges within one interval and a dead
process is detected by disconnect instantly.

Wire ops (request → reply):

=====================================  ===============================
``("predict", model, inputs, dl_ms)``  ``("outs", names, arrays, rank)``
                                       / ``("busy", msg)`` backpressure
                                       / ``("fail", msg)`` replica fault
                                       / ``("err", msg)`` bad request
                                       / ``("not_ready", state)``
``("reload", model, spec)``            ``("ok",)`` / ``("err", msg)``
``("load", model, spec)``              ``("ok",)`` / ``("err", msg)``
``("status",)``                        ``("status", dict)``
``("drain",)`` / ``("shutdown",)``     ``("ok",)``
=====================================  ===============================

The :mod:`mxnet_tpu.chaos` ``replica.predict`` seam fires once per
predict RPC served, so replica-side faults (delays, failures) are
deterministically injectable under a seeded spec.

Run one from the command line (the shape ``tools/fleet_smoke.py`` and
``serve_bench --fleet`` spawn)::

    python -m mxnet_tpu.serving.replica --router 127.0.0.1:9200 \\
        --name mlp --prefix ckpt/mlp --epoch 0 \\
        --input-shapes '{"data": [1, 784]}'
"""
from __future__ import annotations

import threading
import time

from .. import chaos as _chaos
from .. import dist_ps as _ps
from .. import telemetry as _telemetry
from ..base import MXNetError
from ..lint import lockwitness as _lockwitness
from .batcher import Overloaded
from .slots import ModelRegistry
from . import fleet as _fleet

__all__ = ["ReplicaServer", "current_replica", "main"]


_CURRENT = None            # the process's ReplicaServer (readiness view)


def current_replica():
    """This process's replica server, or None (the /readyz hook)."""
    return _CURRENT


class ReplicaServer:
    """The wire wrapper around one process's model slots.

    *router* is the ``(host, port)`` of the fleet router to register
    with (None = standalone, for tests driving the wire ops directly);
    *registry* defaults to a private :class:`ModelRegistry` so several
    in-process replicas (tests) stay independent — the CLI main uses
    the process singleton so ``/v1`` and ``/readyz`` work locally too.
    """

    def __init__(self, router=None, port=0, rank_hint=None,
                 registry=None):
        global _CURRENT
        self.router = tuple(router) if router is not None else None
        self.rank = None
        self.rank_hint = rank_hint
        self.state = "starting"
        self.registry = registry if registry is not None \
            else ModelRegistry()
        self._outstanding = 0
        self._served = 0
        self._lock = _lockwitness.make_lock("ReplicaServer._lock")
        self._stop = threading.Event()
        self._hb_conn = None
        self._hb_thread = None
        self._listener = _ps.RpcListener(self._serve_conn, port=port,
                                         name="replica")
        self.addr = self._listener.addr
        _CURRENT = self

    # -- lifecycle ---------------------------------------------------------

    def start(self):
        self._listener.start()
        if self.router is not None:
            self._register()                 # raises if the router is gone
            self._hb_thread = threading.Thread(
                target=self._hb_loop, name="mxnet-replica-hb", daemon=True)
            self._hb_thread.start()
        return self

    def load(self, name, **kwargs):
        """Warm one slot from the checkpoint tier (compiles the whole
        bucket table before returning — the warm-load cost that buys a
        retrace-free request path)."""
        self._set_state("warming")
        slot = self.registry.load(name, **kwargs)
        _telemetry.flight.record("replica_warm", name,
                                 rank=self.rank,
                                 buckets=len(slot.program.buckets))
        return slot

    def advertise_ready(self):
        """Flip to ``ready`` — call after every slot is loaded.  The
        next heartbeat carries the state; the router routes from then."""
        self._set_state("ready")
        self._send_heartbeat_now()
        return self

    def _set_state(self, value):
        # the state machine is written from the RPC threads (load/drain
        # ops), the heartbeat thread, and the owner — one lock, one word
        with self._lock:
            self.state = value

    def stop(self, drain=True):
        """Stop serving.  *drain=False* is the test harness's stand-in
        for a crash: listener and conns die with requests in flight."""
        global _CURRENT
        self._set_state("draining" if drain else "stopped")
        self._stop.set()
        self._listener.stop()
        conn = self._hb_conn
        if conn is not None:
            conn.close()
        self.registry.shutdown(drain=drain)
        self._set_state("stopped")
        if _CURRENT is self:       # a stopped replica gates nothing
            _CURRENT = None

    def wait_shutdown(self, poll_s=1.0):
        """Block until a ``shutdown`` RPC (the CLI main's park loop)."""
        while not self._stop.wait(poll_s):
            pass

    # -- router registration + heartbeats ----------------------------------

    def _register(self, retries=50, delay=0.1):
        """Dial the router, claim a rank (preferring *rank_hint* — a
        restarted replica re-registers into its dead rank), then open
        the dedicated heartbeat connection."""
        hint = self.rank if self.rank is not None else self.rank_hint
        conn = _ps.Conn.connect(self.router, retries=retries, delay=delay)
        try:
            conn.send(("reg_replica", tuple(self.addr), hint,
                       self.registry.names()))
            reply = conn.recv(timeout=max(_fleet.dead_after_s() * 5, 15.0))
        finally:
            conn.close()
        if not (isinstance(reply, tuple) and reply
                and reply[0] == "ranked"):
            raise MXNetError("router at %s:%s refused registration: %r"
                             % (self.router[0], self.router[1], reply))
        self.rank = int(reply[1])
        hb = _ps.Conn.connect(self.router, retries=retries, delay=delay)
        hb.send(("hb_replica", self.rank))
        with self._lock:
            self._hb_conn = hb
        _telemetry.flight.record("replica_registered", str(self.rank),
                                 addr="%s:%s" % self.addr)
        return self.rank

    def _send_heartbeat_now(self):
        conn = self._hb_conn
        if conn is None:
            return
        try:
            with self._lock:
                outstanding = self._outstanding
            conn.send(("hb", self.state, outstanding,
                       self.registry.names()))
        except (OSError, ConnectionError):
            with self._lock:
                self._hb_conn = None   # the hb loop re-registers

    def _hb_loop(self):
        """Periodic state heartbeats; a lost router connection triggers
        re-registration (bounded dial per tick, so a router restart is
        survived without a thundering reconnect loop)."""
        while not self._stop.wait(_fleet.heartbeat_s()):
            if self._hb_conn is None:
                try:
                    self._register(retries=1, delay=0)
                except (OSError, ConnectionError, MXNetError):
                    continue           # router still gone; next tick
            self._send_heartbeat_now()

    # -- the wire ops ------------------------------------------------------

    def _serve_conn(self, conn):
        while not self._stop.is_set():
            try:
                # a replica waits on its router between RPCs by design
                # (liveness is the heartbeat link's job): deliberate
                # unbounded recv, the JG007 annotation
                msg = conn.recv(timeout=None)
            except (OSError, ConnectionError):
                return
            try:
                reply = self._handle(msg)
            except Exception as exc:   # the ops surface never dies
                reply = ("err", "replica error: %r" % (exc,))
            if reply is not None:
                conn.send(reply)

    def _handle(self, msg):
        if not (isinstance(msg, tuple) and msg
                and isinstance(msg[0], str)):
            raise _ps.ProtocolError("malformed replica request %r"
                                    % (msg,))
        op = msg[0]
        if op == "predict":
            return self._predict(*msg[1:])
        if op == "status":
            return ("status", self.status())
        if op == "load":
            _, name, spec = msg
            # a replica that was serving keeps serving: load() flips to
            # "warming" for the compile, but an already-ready replica
            # must come back even when the load FAILS — its existing
            # models are intact (only the initial CLI warm-up leaves
            # the ready flip to an explicit advertise_ready)
            was_ready = self.state == "ready"
            try:
                self.load(name, **self._load_kwargs(spec))
            finally:
                if was_ready:
                    self._set_state("ready")
                    self._send_heartbeat_now()
            return ("ok",)
        if op == "reload":
            return self._reload(*msg[1:])
        if op == "drain":
            self._set_state("draining")
            self._send_heartbeat_now()
            return ("ok",)
        if op == "shutdown":
            self._stop.set()
            return ("ok",)
        raise _ps.ProtocolError("unknown replica op %r" % (op,))

    @staticmethod
    def _load_kwargs(spec):
        shapes = {k: tuple(int(d) for d in v)
                  for k, v in spec["input_shapes"].items()}
        return dict(prefix=spec["prefix"],
                    epoch=int(spec.get("epoch") or 0),
                    input_shapes=shapes,
                    buckets=spec.get("buckets"),
                    max_batch=spec.get("max_batch"))

    def _predict(self, model, inputs, deadline_ms=None):
        """Serve one routed predict.  Reply tags encode retryability for
        the router: ``busy``/``fail``/``not_ready`` are safe to route
        elsewhere (predict is idempotent), ``err`` is the request's own
        fault and retrying would fail identically."""
        if self.state != "ready":
            return ("not_ready", self.state)
        if _chaos.active():
            act = _chaos.decide("replica.predict")
            if act is not None:
                try:
                    _chaos.apply_inline(act)
                except (OSError, _chaos.ChaosError) as exc:
                    return ("fail", "chaos: %r" % (exc,))
        timeout_s = max(0.01, float(deadline_ms) / 1e3) \
            if deadline_ms else 60.0
        with self._lock:
            self._outstanding += 1
        try:
            slot = self.registry.get(model)
            request = slot.submit(inputs, timeout_ms=deadline_ms)
            outs = request.wait(timeout_s)
        except Overloaded as exc:
            return ("busy", str(exc))
        except MXNetError as exc:
            message = str(exc)
            # executor failures are the replica's fault (retry elsewhere);
            # malformed requests would fail identically on any replica
            if "predict batch failed" in message \
                    or "timed out" in message:
                return ("fail", message)
            return ("err", message)
        finally:
            with self._lock:
                self._outstanding -= 1
        with self._lock:
            self._served += 1
        _telemetry.bump("replica_predicts")
        return ("outs", slot.program.output_names, outs, self.rank)

    def _reload(self, model, spec=None):
        """Compile-then-swap reload, readiness-gated: the replica
        reports ``reloading`` (no new fleet traffic) for the compile,
        in-flight batches finish on the old program."""
        spec = spec or {}
        self._set_state("reloading")
        self._send_heartbeat_now()
        try:
            self.registry.reload(model, prefix=spec.get("prefix"),
                                 epoch=spec.get("epoch"))
        except MXNetError as exc:
            return ("err", str(exc))
        finally:
            self._set_state("ready")
            self._send_heartbeat_now()
        return ("ok",)

    def status(self):
        with self._lock:
            outstanding, served = self._outstanding, self._served
        return {"rank": self.rank, "state": self.state,
                "addr": "%s:%s" % self.addr,
                "outstanding": outstanding, "served": served,
                "models": self.registry.names()}


def main(argv=None):
    """CLI entry: warm the slots from the checkpoint tier, register,
    serve until the router says shutdown (or the process is killed)."""
    import argparse
    import json

    parser = argparse.ArgumentParser(
        description="one mxnet_tpu serving replica")
    parser.add_argument("--router", required=True,
                        help="fleet router host:port")
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--rank-hint", type=int, default=None)
    parser.add_argument("--name", required=True)
    parser.add_argument("--prefix", required=True)
    parser.add_argument("--epoch", type=int, default=0)
    parser.add_argument("--input-shapes", required=True,
                        help='JSON, e.g. {"data": [1, 784]}')
    parser.add_argument("--max-batch", type=int, default=None)
    parser.add_argument("--buckets", default=None,
                        help="comma-separated bucket ladder")
    args = parser.parse_args(argv)

    host, _, port = args.router.partition(":")
    shapes = {k: tuple(int(d) for d in v)
              for k, v in json.loads(args.input_shapes).items()}
    buckets = [int(b) for b in args.buckets.split(",")] \
        if args.buckets else None

    from .slots import get_registry
    replica = ReplicaServer(router=(host, int(port)), port=args.port,
                            rank_hint=args.rank_hint,
                            registry=get_registry()).start()
    replica.load(args.name, prefix=args.prefix, epoch=args.epoch,
                 input_shapes=shapes, max_batch=args.max_batch,
                 buckets=buckets)
    replica.advertise_ready()
    replica.wait_shutdown()
    replica.stop(drain=True)
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
