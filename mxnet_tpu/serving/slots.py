"""Multi-tenant model slots: named load/unload/reload over checkpoints.

The registry is the serving analogue of the reference's model-zoo-backed
deployment loop: every slot owns one :class:`~.program.PredictProgram`
(the AOT bucket table), one :class:`~.batcher.ContinuousBatcher` (queue
+ scheduler), and its own metrics.  Slots are independent — one model's
overload or reload never blocks another's request path — and the
process-wide registry is what the ``/v1/models`` ops surface reports.

``reload`` swaps weights without dropping traffic: the new predictor's
program table is compiled *first* (the expensive part), then swapped at
a batch boundary; in-flight batches finish on the old program.
"""
from __future__ import annotations

import threading
import time

from .. import telemetry as _telemetry
from ..base import MXNetError
from ..lint import lockwitness as _lockwitness
from .batcher import CircuitBreaker, ContinuousBatcher
from .program import PredictProgram

__all__ = ["ModelSlot", "ModelRegistry", "SlotMetrics", "CircuitBreaker",
           "get_registry", "reset_registry"]


class SlotMetrics:
    """Per-model accounting behind ``/v1/models/<name>`` — counters plus
    a latency histogram reusing the telemetry Histogram/percentile
    machinery (an unregistered instance: per-model series stay out of
    the flat global registry and live in the slot's JSON instead)."""

    def __init__(self):
        self._lock = _lockwitness.make_lock("SlotMetrics._lock")
        self._counts = {"requests": 0, "batches": 0, "rows": 0,
                        "padded_rows": 0, "overloads": 0, "errors": 0,
                        "deadline_drops": 0, "breaker_shed": 0}
        self._latency = _telemetry.Histogram("latency_us")
        # the request-span decomposition: where the latency above went
        self._queue_wait = _telemetry.Histogram("queue_wait_us")
        self._execute = _telemetry.Histogram("execute_us")
        self._occupancy_sum = 0.0
        self._flops = 0.0
        self.t_loaded = time.perf_counter()

    def count(self, key, n=1):
        with self._lock:
            self._counts[key] = self._counts.get(key, 0) + n

    def latency(self, us):
        self._latency.observe(us)

    def queue_wait(self, us):
        self._queue_wait.observe(us)

    def execute(self, us):
        self._execute.observe(us)

    def batch(self, rows, bucket, padded, cost=None, n_requests=1):
        with self._lock:
            self._counts["batches"] += 1
            self._counts["rows"] += rows
            self._counts["padded_rows"] += padded
            self._occupancy_sum += rows / max(bucket, rows)
            if cost is not None:
                self._flops += cost[0]
        if padded:
            _telemetry.bump("serving_padded_rows", padded)

    def snapshot(self):
        with self._lock:
            counts = dict(self._counts)
            occ_sum = self._occupancy_sum
            flops = self._flops
        batches = counts["batches"]
        elapsed = max(time.perf_counter() - self.t_loaded, 1e-9)
        mfu = None
        if flops > 0:
            try:
                from ..telemetry import costs as _costs
                peak = _costs.peaks()["flops"]
                if peak > 0:
                    mfu = flops / (elapsed * peak)
            except Exception:
                pass
        def _pcts(hist):
            return {"p50": hist.percentile(50),
                    "p90": hist.percentile(90),
                    "p99": hist.percentile(99),
                    "mean": (hist.total / hist.count)
                    if hist.count else 0.0,
                    "count": hist.count}

        lat = self._latency
        return dict(counts, **{
            "latency_us": _pcts(lat),
            "queue_wait_us": _pcts(self._queue_wait),
            "execute_us": _pcts(self._execute),
            "batch_occupancy_mean": (occ_sum / batches) if batches else None,
            "model_flops_total": flops,
            "mfu_since_load": mfu,
            "uptime_s": round(elapsed, 3),
        })


class ModelSlot:
    """One named, loaded model: predictor + AOT program + batcher."""

    def __init__(self, name, predictor, source=None, buckets=None,
                 max_batch=None, queue_cap=None, timeout_ms=None,
                 use_engine=True):
        self.name = name
        self.source = dict(source or {})
        self.metrics = SlotMetrics()
        self._lock = _lockwitness.make_lock("ModelSlot._lock")
        self.predictor = predictor
        self.program = PredictProgram(predictor, buckets=buckets,
                                      max_batch=max_batch, name=name)
        self.batcher = ContinuousBatcher(
            self.program, name, metrics=self.metrics,
            queue_cap=queue_cap, timeout_ms=timeout_ms,
            use_engine=use_engine)
        self.status = "ready"

    def start(self):
        self.batcher.start()
        return self

    def submit(self, inputs, timeout_ms=None):
        """Async predict: returns the request future.  *timeout_ms*
        bounds the request's QUEUE time (deadline shed, HTTP 504)."""
        n = self.program.check_rows(inputs)
        return self.batcher.submit(inputs, n, timeout_ms=timeout_ms)

    def predict(self, inputs, timeout=60.0):
        """Sync predict: submit + wait; returns the output list."""
        return self.submit(inputs).wait(timeout)

    def swap(self, predictor):
        """Replace the weights/program behind this slot (reload): the
        new table is already compiled when the batcher flips over."""
        program = PredictProgram(predictor, buckets=self.program.buckets,
                                 name=self.name)
        with self._lock:
            self.predictor = predictor
            self.program = program
        self.batcher.set_program(program)

    def stats(self):
        detail = self.metrics.snapshot()
        detail.update({
            "status": self.status,
            "buckets": list(self.program.buckets),
            "max_batch": self.program.max_batch,
            "queue_depth": self.batcher.queue_depth(),
            "breaker": self.batcher.breaker_state(),
            "inputs": {n: list(s)
                       for n, s in self.program._input_shapes.items()},
            "outputs": self.program.output_names,
            "source": self.source,
            "program_costs": self.program.costs(),
        })
        return detail


class ModelRegistry:
    """The process-wide name -> ModelSlot table (the /v1 ops surface)."""

    def __init__(self):
        self._slots = {}
        self._loading = set()      # names mid-compile (the /readyz view)
        self._lock = _lockwitness.make_lock("ModelRegistry._lock")

    # -- management --------------------------------------------------------

    def load(self, name, prefix=None, epoch=0, input_shapes=None,
             predictor=None, ctx=None, buckets=None, max_batch=None,
             queue_cap=None, timeout_ms=None, use_engine=True):
        """Load a checkpoint (or adopt a built Predictor) under *name*.
        Compilation of the whole bucket table happens here, not on the
        first request."""
        if predictor is None:
            if prefix is None or input_shapes is None:
                raise MXNetError(
                    "load(%r) needs prefix+input_shapes or a predictor"
                    % name)
            from ..predict import Predictor
            predictor = Predictor.load(prefix, epoch, input_shapes,
                                       ctx=ctx)
        with self._lock:
            if name in self._slots:
                raise MXNetError(
                    "model %r is already loaded (reload() to swap "
                    "weights, unload() first to change shapes)" % name)
            self._loading.add(name)      # /readyz: compiling = not ready
        try:
            slot = ModelSlot(name, predictor,
                             source={"prefix": prefix, "epoch": epoch},
                             buckets=buckets, max_batch=max_batch,
                             queue_cap=queue_cap, timeout_ms=timeout_ms,
                             use_engine=use_engine).start()
        finally:
            with self._lock:
                self._loading.discard(name)
        with self._lock:
            if name in self._slots:      # lost a concurrent load race
                slot.batcher.stop(drain=False)
                raise MXNetError("model %r is already loaded" % name)
            self._slots[name] = slot
        self.refresh_gauges()
        _telemetry.flight.record("serving_load", name,
                                 buckets=len(slot.program.buckets))
        return slot

    def unload(self, name, drain=True):
        """Remove a slot; *drain* finishes queued requests first."""
        with self._lock:
            slot = self._slots.pop(name, None)
        if slot is None:
            raise MXNetError("model %r is not loaded" % name)
        slot.status = "unloading"
        slot.batcher.stop(drain=drain)
        self.refresh_gauges()
        _telemetry.flight.record("serving_unload", name)
        return slot

    def reload(self, name, prefix=None, epoch=None, ctx=None):
        """Swap a slot's weights from its (or a new) checkpoint without
        dropping queued traffic."""
        slot = self.get(name)
        src = dict(slot.source)
        if prefix is not None:
            src["prefix"] = prefix
        if epoch is not None:
            src["epoch"] = epoch
        if not src.get("prefix"):
            raise MXNetError(
                "model %r was loaded from an in-memory predictor; "
                "reload needs an explicit prefix" % name)
        from ..predict import Predictor
        slot.status = "reloading"       # /readyz: compiling = not ready
        try:
            predictor = Predictor.load(
                src["prefix"], src.get("epoch") or 0,
                {n: tuple(s)
                 for n, s in slot.program._input_shapes.items()},
                ctx=ctx)
            slot.swap(predictor)
            slot.source = src
        finally:
            slot.status = "ready"
        _telemetry.flight.record("serving_reload", name)
        return slot

    # -- access ------------------------------------------------------------

    def get(self, name):
        with self._lock:
            slot = self._slots.get(name)
        if slot is None:
            raise MXNetError("model %r is not loaded (have %s)"
                             % (name, self.names()))
        return slot

    def names(self):
        with self._lock:
            return sorted(self._slots)

    def predict(self, name, inputs, timeout=60.0):
        return self.get(name).predict(inputs, timeout=timeout)

    def submit(self, name, inputs):
        return self.get(name).submit(inputs)

    def stats(self):
        with self._lock:
            slots = dict(self._slots)
        return {name: slot.stats() for name, slot in sorted(slots.items())}

    def readiness(self):
        """(ok, detail) for the ``/readyz`` view: not ready while any
        slot is compiling (load in flight), reloading, or draining —
        the state an external LB must not route new traffic into."""
        with self._lock:
            loading = sorted(self._loading)
            slots = {name: slot.status
                     for name, slot in sorted(self._slots.items())}
        not_ready = loading + [name for name, status in slots.items()
                               if status != "ready"]
        return not not_ready, {"slots": slots, "loading": loading,
                               "not_ready": sorted(set(not_ready))}

    def queue_depth_total(self):
        with self._lock:
            slots = list(self._slots.values())
        return sum(s.batcher.queue_depth() for s in slots)

    def refresh_gauges(self):
        """Feed the aggregate serving gauges (also called by the
        introspection sampler via ``serving.refresh_gauges``)."""
        with self._lock:
            n = len(self._slots)
            slots = list(self._slots.values())
        _telemetry.set_gauge("serving_models_loaded", n)
        _telemetry.set_gauge(
            "serving_queue_depth",
            sum(s.batcher.queue_depth() for s in slots))

    def shutdown(self, drain=True):
        """Unload everything (tests / process teardown)."""
        for name in self.names():
            try:
                self.unload(name, drain=drain)
            except MXNetError:
                pass


_registry = None
_registry_lock = _lockwitness.make_lock("slots._registry_lock")
_atexit_installed = False


def _atexit_shutdown():  # pragma: no cover - interpreter teardown
    """Stop every batcher before the engine's own atexit drain runs
    (atexit is LIFO and the engine registers at import, long before any
    registry exists) — a script that exits with models still loaded must
    not race scheduler threads against engine shutdown."""
    registry = _registry
    if registry is not None:
        try:
            registry.shutdown(drain=False)
        except Exception:
            pass


def get_registry():
    """The process-wide model registry (created on first use)."""
    global _registry, _atexit_installed
    if _registry is None:
        with _registry_lock:
            if _registry is None:
                _registry = ModelRegistry()
                if not _atexit_installed:
                    import atexit
                    atexit.register(_atexit_shutdown)
                    _atexit_installed = True
    return _registry


def reset_registry():
    """Tear down and forget the singleton (tests)."""
    global _registry
    with _registry_lock:
        registry, _registry = _registry, None
    if registry is not None:
        registry.shutdown(drain=False)
