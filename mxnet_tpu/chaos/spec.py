"""``MXNET_CHAOS`` spec grammar: text → fault-injection rules.

One spec string describes every fault a run will inject, so a failing
chaos run is reproducible from its environment alone::

    MXNET_CHAOS="seed=7;conn.send.pull:drop@2;conn.recv:delay~0.1=2ms"

Grammar (clauses are ';'-separated)::

    spec   := clause (';' clause)*
    clause := 'seed=' INT
            | SITE ':' fault (',' fault)*
    fault  := KIND trigger? ('=' VALUE)?
    trigger:= '@' N           -- only the N-th matching call (1-based)
            | '@' N '-' M     -- calls N through M inclusive
            | '~' P           -- each matching call with probability P
    VALUE  := duration ('5ms', '0.25s', '10us', bare seconds float)

Sites are dotted and match by prefix: a rule for ``conn.send`` fires on
``conn.send.pull`` and ``conn.send.push`` alike; ``conn.send.pull``
fires only on pull frames.  A fault with no trigger fires on every
matching call.

Kinds (how each is applied is the owning seam's business —
see :mod:`mxnet_tpu.chaos`):

==========  ==========================================================
``drop``    conn.send: the frame is silently discarded
``delay``   sleep VALUE seconds before the operation
``stall``   alias of ``delay`` (reads better at ``engine.task``)
``close``   conn.*: close the socket (the peer sees EOF / reset)
``garbage`` conn.send: replace the frame with garbage bytes
``exc``     raise :class:`~mxnet_tpu.chaos.ChaosError` at the site
``fail``    raise ``OSError`` (transient-IO flavor, e.g. ``ckpt.io``)
``nan``     grad.bucket: deterministically replace a gradient bucket
            with NaNs (drives the training guardian end-to-end)
==========  ==========================================================
"""
from __future__ import annotations

import re

__all__ = ["ChaosSpecError", "Fault", "Rule", "KINDS", "SITES",
           "parse_spec", "parse_duration"]

KINDS = frozenset({"drop", "delay", "stall", "close", "garbage",
                   "exc", "fail", "nan"})

# the seams wired up in this build (documentation + spec validation;
# prefixes of these are fine, arbitrary others are a typo'd spec)
SITES = ("conn.send", "conn.recv", "engine.task", "ckpt.io",
         "serving.batch", "grad.bucket", "fleet.route",
         "replica.predict")

_DUR_RE = re.compile(r"^(\d+(?:\.\d+)?)(us|ms|s)?$")
_FAULT_RE = re.compile(
    r"^(?P<kind>[a-z]+)"
    r"(?:@(?P<lo>\d+)(?:-(?P<hi>\d+))?|~(?P<prob>[0-9.]+))?"
    r"(?:=(?P<value>[^=]+))?$")


class ChaosSpecError(ValueError):
    """The MXNET_CHAOS string does not parse — fail the run loudly; a
    silently ignored chaos spec would report phantom robustness."""


def parse_duration(raw):
    """'5ms' / '0.25s' / '10us' / bare float → seconds."""
    m = _DUR_RE.match(raw.strip())
    if not m:
        raise ChaosSpecError("bad duration %r (want e.g. 5ms, 0.25s)" % raw)
    val = float(m.group(1))
    unit = m.group(2) or "s"
    return val * {"us": 1e-6, "ms": 1e-3, "s": 1.0}[unit]


class Fault:
    """One fault kind + its trigger window/probability + value."""

    __slots__ = ("kind", "lo", "hi", "prob", "value")

    def __init__(self, kind, lo=None, hi=None, prob=None, value=None):
        self.kind, self.lo, self.hi = kind, lo, hi
        self.prob, self.value = prob, value

    def describe(self):
        out = self.kind
        if self.lo is not None:
            out += "@%d" % self.lo
            if self.hi != self.lo:
                out += "-%d" % self.hi
        elif self.prob is not None:
            out += "~%g" % self.prob
        if self.value is not None:
            out += "=%gs" % self.value
        return out


class Rule:
    """All faults configured for one site prefix."""

    __slots__ = ("site", "faults")

    def __init__(self, site, faults):
        self.site, self.faults = site, faults

    def matches(self, site):
        return site == self.site or site.startswith(self.site + ".")

    def describe(self):
        return "%s:%s" % (self.site,
                          ",".join(f.describe() for f in self.faults))


def _parse_fault(raw, site):
    m = _FAULT_RE.match(raw.strip())
    if not m:
        raise ChaosSpecError("bad fault %r in clause for %r" % (raw, site))
    kind = m.group("kind")
    if kind not in KINDS:
        raise ChaosSpecError(
            "unknown fault kind %r (know: %s)" % (kind, sorted(KINDS)))
    lo = hi = prob = None
    if m.group("lo") is not None:
        lo = int(m.group("lo"))
        hi = int(m.group("hi")) if m.group("hi") is not None else lo
        if lo < 1 or hi < lo:
            raise ChaosSpecError("bad occurrence window in %r" % raw)
    elif m.group("prob") is not None:
        prob = float(m.group("prob"))
        if not 0.0 <= prob <= 1.0:
            raise ChaosSpecError("probability out of [0,1] in %r" % raw)
    value = None
    if m.group("value") is not None:
        value = parse_duration(m.group("value"))
    if kind in ("delay", "stall") and value is None:
        raise ChaosSpecError("%r needs a duration (e.g. %s=5ms)"
                             % (kind, kind))
    return Fault(kind, lo=lo, hi=hi, prob=prob, value=value)


def parse_spec(text):
    """Parse a full MXNET_CHAOS string → (seed-or-None, [Rule])."""
    seed, rules = None, []
    for clause in (text or "").split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if clause.startswith("seed="):
            try:
                seed = int(clause[5:])
            except ValueError:
                raise ChaosSpecError("bad seed in %r" % clause)
            continue
        if ":" not in clause:
            raise ChaosSpecError(
                "clause %r is neither 'seed=N' nor 'site:fault,...'"
                % clause)
        site, _, faults_raw = clause.partition(":")
        site = site.strip()
        if not site or not re.match(r"^[a-z0-9_.]+$", site):
            raise ChaosSpecError("bad site %r" % site)
        if not any(site == s or site.startswith(s + ".") or
                   s.startswith(site + ".") or s == site
                   for s in SITES):
            raise ChaosSpecError(
                "site %r matches no known injection seam %s"
                % (site, list(SITES)))
        faults = [_parse_fault(f, site)
                  for f in faults_raw.split(",") if f.strip()]
        if not faults:
            raise ChaosSpecError("no faults in clause for %r" % site)
        rules.append(Rule(site, faults))
    return seed, rules
