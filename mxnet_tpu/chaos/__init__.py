"""mxnet_tpu.chaos: deterministic, seedable fault injection.

Fault tolerance that is never exercised is a comment, not a property.
This package is the chaos tier that makes the dist transport's recovery
paths *verifiable*: a seeded plan (``MXNET_CHAOS=<spec>`` or
:func:`configure`) decides, deterministically, which calls at the owned
seams fail and how — so a failing chaos run replays exactly from its
seed + spec, and a transient-faults-only run can be asserted bitwise
against a fault-free one.

Injection seams wired in this build (each seam asks :func:`decide` and
applies the returned fault itself, because only the seam knows what
"drop" or "close" means there):

=================  ======================================================
``conn.send.<op>`` :meth:`mxnet_tpu.dist_ps.Conn.send` — *op* is the wire
                   message's op name (``pull``, ``push``, ``barrier``, …)
``conn.recv``      :meth:`mxnet_tpu.dist_ps.Conn.recv`
``engine.task``    :meth:`mxnet_tpu.engine.ThreadedEngine.push` — decided
                   at push time (deterministic order), applied in-task
``ckpt.io``        each checkpoint shard/manifest file write
                   (:mod:`mxnet_tpu.checkpoint.manager`)
``serving.batch``  each coalesced serving batch execution
                   (:mod:`mxnet_tpu.serving.batcher`)
``grad.bucket``    the reduced-gradient seam of ``Trainer.step`` (both
                   the fused and per-slot paths, once per step); the
                   ``nan`` kind poisons a bucket via :func:`poison_grads`
``fleet.route``    each predict request the serving fleet router
                   accepts, decided in routing order BEFORE a replica
                   is picked (:mod:`mxnet_tpu.serving.fleet`)
``replica.predict`` each predict RPC a replica process serves
                   (:mod:`mxnet_tpu.serving.replica`)
=================  ======================================================

Determinism contract: every rule counts its own matching calls, and a
fault triggers on the count (``@N`` windows) or on a per-fault
``random.Random`` derived from ``(seed, site, kind, position)`` (``~P``
probabilities).  Given the same spec, seed, and per-site call sequence,
the injected-fault sequence is identical — :func:`fault_log` exposes it
for replay assertions.

Keyed sites (comm/compute overlap): seams whose calls can be
*reordered* by concurrent dispatch — the per-bucket gradient seam and
the bucket push frames the overlap tier fires while backward is still
running — pass ``decide(site, key=<bucket id>)``.  A keyed call counts
against a per-``(rule, key)`` counter and draws its ``~P`` randomness
from ``(seed, site, kind, key, occurrence)``, so the decision depends
only on *which bucket, which occurrence* — never on dispatch order —
and the same spec+seed yields an identical :func:`fault_log` whether
overlap is on or off.  ``@N`` windows on keyed sites mean "the N-th
occurrence of that key" (one occurrence per step for gradient buckets,
so ``@N`` keeps reading as "step N").  :func:`fault_log` returns the
log in a canonical ``(site, key, occurrence)`` order for the same
reason: arrival order is a property of thread interleaving, not of the
fault plan.  Every injected fault is also booked as the
``chaos_faults`` telemetry counter and a ``chaos`` flight-ring event, so
post-mortems distinguish injected pain from real failures.

Off path: one module-bool check (:func:`active`); with ``MXNET_CHAOS``
unset nothing else runs.
"""
from __future__ import annotations

import os
import threading
import time
import zlib
from random import Random

from ..lint import lockwitness as _lockwitness
from .spec import (ChaosSpecError, Fault, Rule, KINDS, SITES,  # noqa: F401
                   parse_spec, parse_duration)

__all__ = ["ChaosError", "ChaosSpecError", "ChaosPlan", "active",
           "configure", "refresh_from_env", "decide", "apply_inline",
           "chaos_task", "poison_grads", "fault_log", "plan", "reset",
           "describe", "KINDS", "SITES", "parse_spec", "parse_duration"]


class ChaosError(RuntimeError):
    """An injected failure (never raised by real code paths): test
    harnesses assert on this type to separate chaos from genuine bugs."""


class ChaosPlan:
    """A parsed spec + per-rule deterministic trigger state."""

    def __init__(self, spec_text, seed=None):
        env_seed, rules = parse_spec(spec_text)
        self.spec = spec_text
        self.seed = env_seed if env_seed is not None \
            else (0 if seed is None else int(seed))
        self.rules = rules
        self._lock = _lockwitness.make_lock("ChaosPlan._lock")
        self._counts = [0] * len(rules)
        self._kcounts = [{} for _ in rules]   # per-rule {key: count}
        self._rngs = {}
        # unkeyed: (site, rule_site, kind, match_index)
        # keyed:   (site, rule_site, kind, match_index, key)
        self.log = []

    def _rng(self, ridx, fidx):
        key = (ridx, fidx)
        rng = self._rngs.get(key)
        if rng is None:
            rule = self.rules[ridx]
            token = "%d|%s|%s|%d" % (self.seed, rule.site,
                                     rule.faults[fidx].kind, fidx)
            rng = self._rngs[key] = Random(zlib.adler32(token.encode()))
        return rng

    def decide(self, site, key=None):
        """The fault to inject for this call at *site*, or None.

        Counts every matching rule (so ``@N`` windows are stable no
        matter which other rules exist); the first triggering fault of
        the first matching rule wins.  With *key* (a bucket id), the
        count is per ``(rule, key)`` and the ``~P`` draw depends only on
        ``(seed, site, kind, key, occurrence)`` — dispatch-order
        independent, see the module docstring.
        """
        hit = None
        with self._lock:
            for ridx, rule in enumerate(self.rules):
                if not rule.matches(site):
                    continue
                if key is None:
                    n = self._counts[ridx] = self._counts[ridx] + 1
                else:
                    kc = self._kcounts[ridx]
                    n = kc[key] = kc.get(key, 0) + 1
                if hit is not None:
                    continue        # keep counting later rules anyway
                for fidx, fault in enumerate(rule.faults):
                    if fault.lo is not None:
                        fired = fault.lo <= n <= fault.hi
                    elif fault.prob is not None:
                        fired = self._draw(ridx, fidx, key, n) < fault.prob
                    else:
                        fired = True
                    if fired:
                        hit = (fault.kind, fault.value, site, n)
                        entry = (site, rule.site, fault.kind, n)
                        self.log.append(entry if key is None
                                        else entry + (key,))
                        break
        if hit is not None:
            self._book(hit)
        return hit

    def _draw(self, ridx, fidx, key, n):
        """One ``~P`` uniform draw.  Unkeyed: the rule's sequential RNG
        (stream position = call order, which IS deterministic for
        unkeyed sites).  Keyed: a fresh value from ``(seed, site, kind,
        key, occurrence)`` — no shared stream, so concurrent dispatch
        order cannot shift anyone's draw."""
        if key is None:
            return self._rng(ridx, fidx).random()
        rule = self.rules[ridx]
        token = "%d|%s|%s|%d|%s|%d" % (self.seed, rule.site,
                                       rule.faults[fidx].kind, fidx,
                                       key, n)
        return Random(zlib.adler32(token.encode())).random()

    def _book(self, hit):
        kind, _value, site, n = hit
        try:
            from ..telemetry import core as _tel
            from ..telemetry import flight as _flight
            _tel.bump("chaos_faults")
            _flight.record("chaos", site, fault=kind, n=n)
        except Exception:        # booking must never break injection
            pass

    def reset(self):
        """Restart counters/RNGs/log (a fresh deterministic replay)."""
        with self._lock:
            self._counts = [0] * len(self.rules)
            self._kcounts = [{} for _ in self.rules]
            self._rngs.clear()
            self.log = []

    def describe(self):
        return {"seed": self.seed,
                "rules": [r.describe() for r in self.rules]}


_PLAN = None
_ACTIVE = False
_CONF_LOCK = _lockwitness.make_lock("chaos._CONF_LOCK")


def active():
    """One cached-bool check: is any chaos plan installed?"""
    return _ACTIVE


def plan():
    return _PLAN


def configure(spec=None, seed=None):
    """Install (or with a falsy *spec*, remove) the process chaos plan."""
    global _PLAN, _ACTIVE
    with _CONF_LOCK:
        if not spec:
            _PLAN, _ACTIVE = None, False
            return None
        _PLAN = ChaosPlan(spec, seed=seed)
        _ACTIVE = _PLAN.rules != []
        return _PLAN


def refresh_from_env():
    """Re-read ``MXNET_CHAOS`` (import-time default; tests/late config)."""
    return configure(os.environ.get("MXNET_CHAOS", ""))


def decide(site, key=None):
    """The seam-facing entry point: fault tuple ``(kind, value, site,
    n)`` or None.  Call only after an :func:`active` check.  Pass
    ``key=<bucket id>`` from seams whose dispatch order is not
    deterministic (overlapped bucket reduces) — see the module
    docstring's keyed-sites contract."""
    p = _PLAN
    return None if p is None else p.decide(site, key=key)


def apply_inline(act):
    """Apply a fault generically at a non-socket seam: delays sleep,
    everything else raises (``fail`` as OSError so transient-IO retry
    paths engage; the rest as :class:`ChaosError`)."""
    kind, value = act[0], act[1]
    if kind in ("delay", "stall"):
        time.sleep(value)
        return
    if kind == "fail":
        raise OSError("chaos: injected transient IO failure at %s #%d"
                      % (act[2], act[3]))
    raise ChaosError("chaos: injected %s at %s #%d"
                     % (kind, act[2], act[3]))


def poison_grads(raw_grads, site="grad.bucket", key=None):
    """The gradient seam: decide at *site*; a ``nan`` fault replaces
    the FIRST array of the list with NaNs — deterministic, so a
    poisoned run replays exactly from seed + spec.  Other kinds apply
    inline; no active plan means the input list passes through
    untouched.

    Unkeyed (the per-slot ``MXNET_FUSED_TRAINER=0`` oracle loop):
    decided once per step in step order, *raw_grads* is the whole
    gradient list and "first bucket" means its first array.  Keyed (the
    whole fused path — kvstore or not, overlap on or off): decided once
    per step PER BUCKET with ``key=<bucket index>``, *raw_grads* is
    that bucket's gradient list — the per-key occurrence count equals
    the step number, so ``nan@K`` still reads "poison at step K" while
    the decision stays identical under overlapped dispatch."""
    if not _ACTIVE:
        return raw_grads
    act = decide(site, key=key)
    if act is None:
        return raw_grads
    if act[0] != "nan":
        apply_inline(act)
        return raw_grads
    import numpy as np
    import jax.numpy as jnp
    out = list(raw_grads)
    g0 = out[0]
    out[0] = jnp.full(getattr(g0, "shape", ()), np.nan,
                      getattr(g0, "dtype", np.float32))
    return out


def chaos_task(fn, act):
    """Wrap an engine task with a fault decided at push time: the
    decision order is the deterministic push order, the effect happens
    where the failure matters (inside the task)."""
    def _chaotic():
        apply_inline(act)
        return fn()
    _chaotic.__qualname__ = (getattr(fn, "__qualname__", None)
                             or getattr(fn, "__name__", "task")) + "[chaos]"
    return _chaotic


def fault_log():
    """The injected faults so far, in canonical ``(site, key,
    rule, occurrence)`` order (replay/determinism asserts).  Arrival
    order is a property of thread interleaving — overlapped bucket
    dispatch, heartbeat threads — so the log is sorted into an order
    every equally-faulted run shares; entries themselves are unchanged
    (keyed entries carry their key as a 5th element)."""
    p = _PLAN
    if p is None:
        return []
    with p._lock:
        entries = list(p.log)
    return sorted(entries,
                  key=lambda e: (e[0], "" if len(e) < 5 else str(e[4]),
                                 e[1], e[3], e[2]))


def reset():
    p = _PLAN
    if p is not None:
        p.reset()


def describe():
    p = _PLAN
    return None if p is None else p.describe()


refresh_from_env()
