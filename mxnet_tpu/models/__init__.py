"""First-class model definitions built on the parallel layer.

The Gluon model zoo (``mxnet_tpu.gluon.model_zoo``) carries the reference's
vision families (SURVEY §2.3); this package holds TPU-native SPMD models —
currently the transformer LM with data/tensor/sequence parallel shardings —
used by the scale-out benchmarks and the multi-chip dry run.
"""
from .transformer import (TransformerLMConfig, init_transformer_params,
                          transformer_forward, make_train_step)

__all__ = ["TransformerLMConfig", "init_transformer_params",
           "transformer_forward", "make_train_step"]
