"""Mixture-of-experts FFN with expert parallelism (EP).

Beyond-reference capability (the reference predates MoE): a
switch-style top-1 routed expert FFN in the Mesh-TensorFlow dispatch
formulation — routing produces static-shape dispatch/combine tensors,
expert compute is one batched einsum over the expert dimension, and
placing the expert dim on a mesh axis makes the XLA SPMD partitioner
insert the all-to-all exchanges that NCCL-based frameworks hand-code.

Design notes (TPU-first):
* Static shapes everywhere: capacity-based routing (tokens over an
  expert's capacity are dropped and pass through the residual), so one
  compiled program serves every batch.
* ``expert_axis`` defaults to ``"model"`` — EP reuses the tensor-
  parallel axis the way production MoE stacks overlap EP with TP.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..parallel import mesh as mesh_mod

__all__ = ["init_moe_params", "moe_ffn", "moe_param_specs"]


def moe_param_specs(d_model, d_ff, n_experts, expert_axis="model"):
    """name -> (shape, PartitionSpec) with the expert dim sharded."""
    e = expert_axis
    return {
        "gate_w": ((d_model, n_experts), P()),
        "expert_w1": ((n_experts, d_model, d_ff), P(e, None, None)),
        "expert_b1": ((n_experts, d_ff), P(e, None)),
        "expert_w2": ((n_experts, d_ff, d_model), P(e, None, None)),
    }


def init_moe_params(key, d_model, d_ff, n_experts, mesh=None,
                    dtype=jnp.float32, expert_axis="model"):
    params = {}
    for name, (shape, spec) in sorted(
            moe_param_specs(d_model, d_ff, n_experts,
                            expert_axis).items()):
        key, sub = jax.random.split(key)
        if name == "expert_b1":
            v = jnp.zeros(shape, dtype)
        else:
            fan_in = shape[-2] if name != "gate_w" else shape[0]
            v = (jax.random.normal(sub, shape, dtype)
                 * (1.0 / math.sqrt(max(fan_in, 1))))
        if mesh is not None:
            v = mesh_mod.shard_put(v, mesh_mod.named_sharding(mesh, spec))
        params[name] = v
    return params


def _route_top1(logits, capacity):
    """Switch routing: per-token argmax expert with capacity cutoff.

    Returns (dispatch [n, E, C] in {0,1}, combine [n, E, C] floats):
    ``dispatch`` scatters token n into its expert's buffer slot,
    ``combine`` gathers the expert output back scaled by the gate
    probability. Tokens beyond an expert's capacity drop (all-zero
    rows) — the caller's residual connection carries them through.
    """
    n, num_experts = logits.shape
    gates = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert_idx = jnp.argmax(gates, axis=-1)                 # [n]
    onehot = jax.nn.one_hot(expert_idx, num_experts,
                            dtype=jnp.float32)              # [n, E]
    # position of each token within its chosen expert's queue
    pos = jnp.cumsum(onehot, axis=0) * onehot               # [n, E], 1-based
    within = (pos > 0) & (pos <= capacity)
    slot = jnp.clip(pos - 1, 0, capacity - 1).astype(jnp.int32)
    slot_oh = jax.nn.one_hot(jnp.max(slot, axis=-1), capacity,
                             dtype=jnp.float32)             # [n, C]
    dispatch = (onehot * within)[:, :, None] * slot_oh[:, None, :]
    gate_val = jnp.sum(gates * onehot, axis=-1)             # [n]
    combine = dispatch * gate_val[:, None, None]
    return dispatch, combine


def moe_ffn(x, params, capacity_factor=1.25, mesh=None,
            expert_axis="model"):
    """Apply the routed expert FFN to ``x`` [B, S, D] -> [B, S, D].

    With a mesh, expert weights and the expert compute shard over
    ``expert_axis``; the dispatch/combine einsums become the token
    all-to-all. Add the result to a residual: dropped tokens contribute
    zero here.
    """
    b, s, d = x.shape
    n = b * s
    num_experts = params["expert_w1"].shape[0]
    capacity = max(1, int(math.ceil(
        capacity_factor * n / num_experts)))
    flat = x.reshape(n, d)
    logits = flat @ params["gate_w"]
    dispatch, combine = _route_top1(logits, capacity)

    expert_in = jnp.einsum("nec,nd->ecd", dispatch, flat)   # [E, C, D]
    if mesh is not None and expert_axis in mesh.shape:
        espec = mesh_mod.named_sharding(mesh, P(expert_axis, None, None))
        expert_in = jax.lax.with_sharding_constraint(expert_in, espec)
    h = jax.nn.gelu(
        jnp.einsum("ecd,edf->ecf", expert_in, params["expert_w1"])
        + params["expert_b1"][:, None, :])
    out_e = jnp.einsum("ecf,efd->ecd", h, params["expert_w2"])
    if mesh is not None and expert_axis in mesh.shape:
        out_e = jax.lax.with_sharding_constraint(out_e, espec)
    out = jnp.einsum("nec,ecd->nd", combine.astype(out_e.dtype), out_e)
    return out.reshape(b, s, d)
