"""Transformer LM with 3D (data × sequence × tensor) parallelism.

The reference framework predates attention (SURVEY §5.7 — its long-sequence
answer was bucketing); this model is the TPU-native long-context flagship:

- **data parallel**: batch sharded over the ``data`` mesh axis; gradient
  all-reduce inserted by XLA (replaces kvstore push/pull, SURVEY §2.5).
- **tensor parallel**: attention heads and MLP hidden sharded over
  ``model``; the pair of matmuls per block keeps one all-reduce per
  sub-layer (Megatron layout), compiled to ICI collectives.
- **sequence parallel**: activations sharded over ``seq``; exact attention
  across shards via the ring-attention ppermute pipeline
  (``parallel/ring_attention.py``) inside a ``shard_map`` island.

Everything else is plain ``jit`` + ``NamedSharding`` annotations: pick a
mesh, annotate, let XLA insert collectives (the scaling-book recipe).
"""
from __future__ import annotations

import dataclasses
import functools
import math

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel import mesh as mesh_mod
from ..parallel.ring_attention import ring_attention

__all__ = ["TransformerLMConfig", "init_transformer_params",
           "transformer_forward", "make_train_step",
           "make_train_step_zero1"]


@dataclasses.dataclass(frozen=True)
class TransformerLMConfig:
    vocab: int = 256
    d_model: int = 64
    n_heads: int = 4
    d_ff: int = 256
    n_layers: int = 2
    max_len: int = 128
    dtype: object = jnp.float32


def _param_specs(cfg):
    """name -> (shape, PartitionSpec). Megatron TP layout over 'model'."""
    hd = cfg.d_model // cfg.n_heads
    specs = {
        "embed": ((cfg.vocab, cfg.d_model), P(None, None)),
        "pos_embed": ((cfg.max_len, cfg.d_model), P(None, None)),
        "out_norm_scale": ((cfg.d_model,), P(None)),
        "out_proj": ((cfg.d_model, cfg.vocab), P(None, None)),
    }
    for i in range(cfg.n_layers):
        pre = "layer%d_" % i
        specs.update({
            # QKV/out projections: head dim sharded over 'model'
            pre + "wq": ((cfg.d_model, cfg.n_heads, hd), P(None, "model", None)),
            pre + "wk": ((cfg.d_model, cfg.n_heads, hd), P(None, "model", None)),
            pre + "wv": ((cfg.d_model, cfg.n_heads, hd), P(None, "model", None)),
            pre + "wo": ((cfg.n_heads, hd, cfg.d_model), P("model", None, None)),
            # MLP: hidden sharded over 'model' (col- then row-parallel)
            pre + "w1": ((cfg.d_model, cfg.d_ff), P(None, "model")),
            pre + "b1": ((cfg.d_ff,), P("model")),
            pre + "w2": ((cfg.d_ff, cfg.d_model), P("model", None)),
            pre + "norm1_scale": ((cfg.d_model,), P(None)),
            pre + "norm2_scale": ((cfg.d_model,), P(None)),
        })
    return specs


# the spec/placement helpers moved into the sharding substrate
# (parallel/mesh.py); these names remain the model-layer spelling
_filter_spec = mesh_mod.filter_spec
global_put = mesh_mod.shard_put


def init_transformer_params(key, cfg, mesh=None):
    """Initialize params; placed with TP shardings when a mesh is given."""
    specs = _param_specs(cfg)
    params = {}
    for name, (shape, spec) in sorted(specs.items()):
        spec = _filter_spec(spec, mesh)
        key, sub = jax.random.split(key)
        if name.endswith(("_scale",)):
            v = jnp.ones(shape, cfg.dtype)
        elif name.endswith(("b1",)):
            v = jnp.zeros(shape, cfg.dtype)
        else:
            # fan-in = the contracted dims: leading axis for wq/wk/wv/w1/w2
            # (they contract shape[0]), all-but-last for wo (contracts h,k)
            if name.endswith("wo"):
                fan_in = int(np.prod(shape[:-1]))
            else:
                fan_in = shape[0]
            v = (jax.random.normal(sub, shape, cfg.dtype)
                 * (1.0 / math.sqrt(max(fan_in, 1))))
        if mesh is not None:
            v = global_put(np.asarray(v), NamedSharding(mesh, spec))
        params[name] = v
    return params


def _rmsnorm(x, scale):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x * jax.lax.rsqrt(var + 1e-6)).astype(x.dtype) * scale


def transformer_forward(params, tokens, cfg, mesh=None, seq_axis="seq"):
    """Causal LM forward: tokens [B, S] int32 -> logits [B, S, vocab].

    With a mesh, attention runs as a shard_map ring over ``seq_axis`` and
    activations carry (data, seq, -) shardings; without one it is plain
    single-device jax (used by tests and the single-chip entry).
    """
    b, s = tokens.shape
    x = params["embed"][tokens] + params["pos_embed"][:s][None, :, :]
    use_ring = mesh is not None and mesh.shape.get(seq_axis, 1) > 1

    if use_ring:
        qkv_spec = _filter_spec(P("data", "model", seq_axis, None), mesh)
        attn = mesh_mod.shard_map(
            functools.partial(ring_attention, axis_name=seq_axis,
                              causal=True),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec), out_specs=qkv_spec,
            check=False)
    else:
        attn = functools.partial(_causal_attn_local, mesh=mesh)

    for i in range(cfg.n_layers):
        pre = "layer%d_" % i
        h = _rmsnorm(x, params[pre + "norm1_scale"])
        q = jnp.einsum("bsd,dhk->bhsk", h, params[pre + "wq"])
        k = jnp.einsum("bsd,dhk->bhsk", h, params[pre + "wk"])
        v = jnp.einsum("bsd,dhk->bhsk", h, params[pre + "wv"])
        o = attn(q, k, v)
        x = x + jnp.einsum("bhsk,hkd->bsd", o, params[pre + "wo"])
        h = _rmsnorm(x, params[pre + "norm2_scale"])
        h = jax.nn.gelu(h @ params[pre + "w1"] + params[pre + "b1"])
        x = x + h @ params[pre + "w2"]

    x = _rmsnorm(x, params["out_norm_scale"])
    return x @ params["out_proj"]


def _use_flash(s):
    # TPU only (axon = the tunneled TPU backend); pallas_call lowers via
    # Mosaic and is untested on other backends, and interpret mode on CPU
    # would be needlessly slow — XLA fuses the jnp reference fine there.
    if jax.default_backend() not in ("tpu", "axon") or s < 128:
        return False
    from ..ops.pallas_kernels import HAS_PALLAS
    return HAS_PALLAS


def _causal_attn_local(q, k, v, mesh=None):
    if _use_flash(q.shape[2]):
        from ..ops.pallas_kernels import flash_attention
        fn = functools.partial(flash_attention, causal=True)
        if mesh is not None:
            # pallas_call is opaque to GSPMD: shard batch/heads explicitly
            # so the TP split survives (each shard runs the kernel locally)
            spec = _filter_spec(P("data", "model", None, None), mesh)
            return mesh_mod.shard_map(lambda a, b_, c: fn(a, b_, c),
                                      mesh=mesh, in_specs=(spec,) * 3,
                                      out_specs=spec, check=False)(q, k, v)
        return fn(q, k, v)
    from ..parallel.ring_attention import local_attention
    return local_attention(q, k, v, causal=True)


def _lm_loss_fn(cfg, mesh, seq_axis):
    """Mean next-token NLL in fp32 — the loss shared by every train-step
    builder in this module."""

    def loss_of(params, tokens, labels):
        logits = transformer_forward(params, tokens, cfg, mesh, seq_axis)
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)
        return jnp.mean(nll)

    return loss_of


def make_train_step(cfg, mesh, lr=0.1, seq_axis="seq"):
    """Build the jitted SPMD train step: (params, tokens, labels) ->
    (new_params, loss).  Batch is sharded P('data', seq_axis); gradient
    reduction, TP collectives and the loss mean are all XLA-inserted."""
    loss_of = _lm_loss_fn(cfg, mesh, seq_axis)

    def step(params, tokens, labels):
        loss, grads = jax.value_and_grad(loss_of)(params, tokens, labels)
        new_params = jax.tree_util.tree_map(
            lambda p, g: p - lr * g.astype(p.dtype), params, grads)
        return new_params, loss

    return mesh_mod.jit_sharded(step, "transformer_train_step",
                                donate_argnums=(0,))


def make_train_step_zero1(cfg, mesh, params, lr=0.1, momentum=0.9,
                          seq_axis="seq"):
    """SGD-momentum train step with cross-replica weight-update sharding
    (ZeRO-1, arXiv:2004.13336) layered on the dp x sp x tp shardings.

    Momentum buffers for replicated (non-TP) parameters shard over the
    ``data`` axis when the leading dim divides evenly; the sharding
    constraints make XLA reduce-scatter those gradients, update 1/N of
    the rows per data replica, and all-gather the weights back.  Returns
    ``(step, momenta)`` where ``step(params, momenta, tokens, labels) ->
    (new_params, new_momenta, loss)``.
    """
    from ..parallel.zero import sharded_update, update_sharding

    upd_shardings = {
        n: update_sharding(mesh, p.shape, "data",
                           getattr(p.sharding, "spec", P()))
        for n, p in params.items()}
    param_shardings = {n: p.sharding for n, p in params.items()}
    momenta = {
        n: jax.device_put(jnp.zeros_like(p),
                          upd_shardings[n] or p.sharding)
        for n, p in params.items()}

    loss_of = _lm_loss_fn(cfg, mesh, seq_axis)

    def momentum_sgd(p, g, m, hyper):
        new_m = momentum * m + g.astype(m.dtype)
        return p - lr * new_m.astype(p.dtype), new_m

    def step(ps, ms, tokens, labels):
        loss, grads = jax.value_and_grad(loss_of)(ps, tokens, labels)
        # the shared ZeRO-1 placement core (parallel/zero.py): the same
        # wsc sandwich the fused Trainer's MXNET_ZERO path and
        # ShardedTrainer compile
        new_p, new_m = {}, {}
        for n in ps:
            new_p[n], new_m[n] = sharded_update(
                momentum_sgd, ps[n], grads[n], ms[n], {},
                upd_shardings[n], param_shardings[n])
        return new_p, new_m, loss

    return mesh_mod.jit_sharded(step, "transformer_train_step_zero1",
                                donate_argnums=(0, 1)), momenta


def place_batch(tokens, labels, mesh, seq_axis="seq"):
    """Shard a [B, S] token batch over (data, seq)."""
    spec = NamedSharding(mesh, _filter_spec(P("data", seq_axis), mesh))
    return global_put(tokens, spec), global_put(labels, spec)


# the provider's programs close over live params/momenta; keep them
# alive until the driver traces (same idiom as gluon/fused_trainer)
_TRACECHECK_KEEPALIVE = []


def tracecheck_programs():
    """graftcheck provider: the plain and ZeRO-1 train steps of a tiny
    LM over the live 3D mesh (whatever device count the process has —
    auto_mesh collapses absent axes to size 1)."""
    mesh = mesh_mod.auto_mesh(("data", "seq", "model"))
    dp, sp, tp = (mesh.shape[a] for a in ("data", "seq", "model"))
    cfg = TransformerLMConfig(vocab=32, d_model=8 * max(tp, 1),
                              n_heads=max(tp, 2), d_ff=16 * max(tp, 1),
                              n_layers=1, max_len=8 * max(sp, 1))
    params = init_transformer_params(jax.random.PRNGKey(0), cfg, mesh)
    b, s = 2 * dp, 8 * sp
    rng = np.random.RandomState(0)
    tokens = rng.randint(0, cfg.vocab, (b, s)).astype(np.int32)
    labels = rng.randint(0, cfg.vocab, (b, s)).astype(np.int32)
    tokens, labels = place_batch(tokens, labels, mesh)

    step = make_train_step(cfg, mesh, lr=0.1)
    step_z, momenta = make_train_step_zero1(cfg, mesh, params, lr=0.1)
    _TRACECHECK_KEEPALIVE.append((params, momenta, tokens, labels))
    axes = {"mesh_axes": ("data", "seq", "model")}
    return [
        ("transformer_train_step", step, (params, tokens, labels), {},
         axes),
        ("transformer_train_step_zero1", step_z,
         (params, momenta, tokens, labels), {}, axes),
    ]
