"""Executor: a bound symbolic graph compiled to single XLA programs.

Parity surface: reference ``python/mxnet/executor.py`` (forward :113,
backward :154, outputs, arg/grad/aux dicts, reshape, monitor) over
``src/executor/graph_executor.cc`` (Init :507/916, RunOps :1403).

TPU-native redesign (SURVEY §7 step 4): the entire GraphExecutor machinery —
gradient-graph synthesis (nnvm Gradient pass), memory planning
(PlanMemory/DetectInplaceAddTo), op-executor attachment, bulk segmenting —
collapses into *one jitted function per (train/eval) mode*:

    eval:  jit(graph_fn)                         — XLA plans memory, fuses
    train: jit(vjp(graph_fn))                    — replaces pass::Gradient.

The train path compiles exactly TWO programs per bind, traced once and
cached for the executor's lifetime (reference parity: after
GraphExecutor::Init the per-step RunOps loop at graph_executor.cc:1403
does no graph work, it only pushes cached engine ops):

    _fwd_train_jit: (args, aux, rng) -> (outputs, new_aux, vjp_fn)
        jax.vjp runs INSIDE the jit; the returned ``vjp_fn`` is a
        jax.tree_util.Partial — a pytree whose leaves are the on-device
        residuals — so it crosses the jit boundary as data.
    _bwd_jit: (vjp_fn, out_grads) -> input_grads
        applies the residual pytree; same treedef every step, so this
        compiles once too.

``forward_backward`` additionally fuses both legs (and the ones-like
head gradient) into ONE XLA program — the Module.fit hot path, where XLA
schedules forward and backward together and residual layouts never
round-trip through program boundaries.

Auxiliary state (BatchNorm moving stats) flows functionally: graph_fn
returns updated aux values, forward writes them back into the aux NDArrays
(reference mutates aux in-kernel).
"""
from __future__ import annotations

from functools import partial

import numpy as np
import jax
import jax.numpy as jnp

from .base import MXNetError
from .context import Context, current_context
from . import random as _random
from . import telemetry as _telemetry
from .ndarray import NDArray, _wrap, zeros as nd_zeros
from .symbol.symbol import Symbol, _topo

__all__ = ["Executor"]


def _build_graph_fn(symbol, train_mode):
    """Build pure fn(arg_vals, aux_vals, rng) -> (outputs, new_aux)."""
    nodes = _topo(symbol._outputs)
    arg_nodes = [n for n in nodes if n.op is None and not n.is_aux]
    aux_nodes = [n for n in nodes if n.op is None and n.is_aux]
    rng_nodes = [n for n in nodes if n.op is not None and n.op.needs_rng]
    arg_pos = {id(n): i for i, n in enumerate(arg_nodes)}
    aux_pos = {id(n): i for i, n in enumerate(aux_nodes)}
    rng_pos = {id(n): i for i, n in enumerate(rng_nodes)}

    # map aux var node -> (producing op node, output index of new value)
    aux_update_src = {}
    for node in nodes:
        if node.op is None or not node.op.aux_updates:
            continue
        for aux_in, out_idx in node.op.aux_updates.items():
            if aux_in < len(node.inputs):
                src, _ = node.inputs[aux_in]
                if src.op is None and src.is_aux:
                    aux_update_src[id(src)] = (node, out_idx)

    heads = list(symbol._outputs)

    def graph_fn(arg_vals, aux_vals, rng):
        env = {}
        for n in arg_nodes:
            env[(id(n), 0)] = arg_vals[arg_pos[id(n)]]
        for n in aux_nodes:
            env[(id(n), 0)] = aux_vals[aux_pos[id(n)]]
        keys = (jax.random.split(rng, len(rng_nodes))
                if rng_nodes else None)
        for node in nodes:
            if node.op is None:
                continue
            ins = [env[(id(s), oi)] for s, oi in node.inputs]
            key = keys[rng_pos[id(node)]] if node.op.needs_rng else None
            fn = node.op.traceable(node.attrs, train_mode=train_mode, rng=key)
            outs = fn(*ins)
            if not isinstance(outs, tuple):
                outs = (outs,)
            for i, o in enumerate(outs):
                env[(id(node), i)] = o
        outputs = tuple(env[(id(n), oi)] for n, oi in heads)
        new_aux = tuple(
            env[(id(aux_update_src[id(n)][0]), aux_update_src[id(n)][1])]
            if id(n) in aux_update_src else env[(id(n), 0)]
            for n in aux_nodes)
        return outputs, new_aux

    return graph_fn, arg_nodes, aux_nodes


class Executor:
    """A bound computation graph (create via Symbol.bind / simple_bind)."""

    def __init__(self, symbol, ctx, arg_dict, grad_dict, grad_req, aux_dict,
                 group2ctx=None):
        self._symbol = symbol
        self._ctx = ctx if isinstance(ctx, Context) else Context(ctx)
        self.arg_names = symbol.list_arguments()
        self.aux_names = symbol.list_auxiliary_states()
        self.output_names = symbol.list_outputs()
        missing = [n for n in self.arg_names if n not in arg_dict]
        if missing:
            raise MXNetError("bind: missing arguments %s" % missing)
        self.arg_dict = {n: arg_dict[n] for n in self.arg_names}
        self.aux_dict = {n: aux_dict.get(n) for n in self.aux_names}
        for n in self.aux_names:
            if self.aux_dict[n] is None:
                raise MXNetError("bind: missing auxiliary state %s" % n)
        if isinstance(grad_req, str):
            grad_req = {n: grad_req for n in self.arg_names}
        elif isinstance(grad_req, (list, tuple)):
            grad_req = dict(zip(self.arg_names, grad_req))
        self.grad_req = {n: grad_req.get(n, "null") for n in self.arg_names}
        self.grad_dict = {n: (grad_dict or {}).get(n) for n in self.arg_names}
        for n, req in self.grad_req.items():
            if req != "null" and self.grad_dict[n] is None:
                self.grad_dict[n] = nd_zeros(self.arg_dict[n].shape,
                                             ctx=self._ctx,
                                             dtype=self.arg_dict[n].dtype)
        self._grad_names = [n for n in self.arg_names
                            if self.grad_req[n] != "null"]

        fn_eval, self._arg_nodes, self._aux_nodes = _build_graph_fn(
            symbol, train_mode=False)
        fn_train, _, _ = _build_graph_fn(symbol, train_mode=True)
        # every jit product goes through the retrace watchdog: a bound
        # executor that keeps recompiling (shape-unstable feed) is exactly
        # the storm the telemetry layer exists to surface
        self._eval_jit = _telemetry.watch_jit(jax.jit(fn_eval),
                                              "executor_eval")
        self._train_fn = fn_train  # raw, for the debug (monitor/group) paths
        self._train_jit = _telemetry.watch_jit(jax.jit(fn_train),
                                               "executor_train")

        gpos = tuple(self.arg_names.index(n) for n in self._grad_names)
        self._gpos = gpos

        def _fwd_vjp(arg_vals, aux_vals, rng):
            def g(grad_vals):
                full = list(arg_vals)
                for p, v in zip(gpos, grad_vals):
                    full[p] = v
                return fn_train(full, aux_vals, rng)
            outs, vjp_fn, new_aux = jax.vjp(
                g, [arg_vals[p] for p in gpos], has_aux=True)
            return outs, new_aux, vjp_fn

        def _fwd_bwd(arg_vals, aux_vals, rng, ograds):
            outs, new_aux, vjp_fn = _fwd_vjp(arg_vals, aux_vals, rng)
            (in_grads,) = vjp_fn(tuple(ograds))
            return outs, new_aux, in_grads

        def _fwd_bwd_ones(arg_vals, aux_vals, rng):
            outs, new_aux, vjp_fn = _fwd_vjp(arg_vals, aux_vals, rng)
            (in_grads,) = vjp_fn(tuple(jnp.ones_like(o) for o in outs))
            return outs, new_aux, in_grads

        self._fwd_train_jit = _telemetry.watch_jit(
            jax.jit(_fwd_vjp), "executor_fwd_vjp")
        self._bwd_jit = _telemetry.watch_jit(
            jax.jit(lambda vjp_fn, og: vjp_fn(og)), "executor_bwd")
        self._fwd_bwd_jit = _telemetry.watch_jit(
            jax.jit(_fwd_bwd), "executor_fwd_bwd")
        self._fwd_bwd_ones_jit = _telemetry.watch_jit(
            jax.jit(_fwd_bwd_ones), "executor_fwd_bwd_ones")
        self._vjp = None
        self._vjp_jitted = False
        self._outputs = None
        self._monitor = None
        self._group2ctx = group2ctx

    # -- array views -------------------------------------------------------
    @property
    def arg_arrays(self):
        return [self.arg_dict[n] for n in self.arg_names]

    @property
    def grad_arrays(self):
        return [self.grad_dict[n] for n in self.arg_names]

    @property
    def aux_arrays(self):
        return [self.aux_dict[n] for n in self.aux_names]

    @property
    def outputs(self):
        if self._outputs is None:
            raise MXNetError("run forward() first")
        return self._outputs

    @property
    def output_dict(self):
        return dict(zip(self.output_names, self.outputs))

    # -- execution ---------------------------------------------------------
    def forward(self, is_train=False, **kwargs):
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown argument %s" % k)
            src = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            self.arg_dict[k]._set_data(src.astype(self.arg_dict[k].dtype))
        arg_vals = [self._place(n, self.arg_dict[n]) for n in self.arg_names]
        aux_vals = [self._place(n, self.aux_dict[n]) for n in self.aux_names]
        rng = self._place_rng(_random.next_key())

        if self._group2ctx:
            # manual model parallelism (__ctx_group__ + group2ctx, ref
            # graph_executor.cc:403 PlaceDevice): run node-by-node with
            # per-group device placement; eager dispatch inserts the
            # cross-device copies the reference's _CrossDeviceCopy did
            outs, new_aux = self._forward_grouped(arg_vals, aux_vals, rng,
                                                  is_train)
            if is_train and self._grad_names:
                gpos = [self.arg_names.index(n) for n in self._grad_names]

                def f_grp(grad_vals):
                    full = list(arg_vals)
                    for p, v in zip(gpos, grad_vals):
                        full[p] = v
                    return self._train_jit(full, aux_vals, rng)

                _o, self._vjp, _na = jax.vjp(
                    f_grp, [arg_vals[p] for p in gpos], has_aux=True)
                self._vjp_jitted = False
        elif self._monitor is not None and \
                getattr(self._monitor, "is_active", lambda: True)():
            outs, new_aux = self._forward_monitored(arg_vals, aux_vals, rng,
                                                    is_train)
            if is_train and self._grad_names:
                # monitor path is observation-only; still set up the vjp so
                # backward() works (costs one extra forward, debug mode only)
                gpos = [self.arg_names.index(n) for n in self._grad_names]

                def f_mon(grad_vals):
                    full = list(arg_vals)
                    for p, v in zip(gpos, grad_vals):
                        full[p] = v
                    return self._train_jit(full, aux_vals, rng)

                _outs, self._vjp, _na = jax.vjp(
                    f_mon, [arg_vals[p] for p in gpos], has_aux=True)
                self._vjp_jitted = False
        elif is_train and self._grad_names:
            # hot path: ONE cached compiled program; the vjp residuals come
            # back as a Partial pytree and stay on device for _bwd_jit
            outs, new_aux, self._vjp = self._fwd_train_jit(
                arg_vals, aux_vals, rng)
            self._vjp_jitted = True
        elif is_train:
            outs, new_aux = self._train_jit(arg_vals, aux_vals, rng)
        else:
            outs, new_aux = self._eval_jit(arg_vals, aux_vals, rng)

        for n, v in zip(self.aux_names, new_aux):
            self.aux_dict[n]._set_data(v)
        self._outputs = [_wrap(o, self._ctx) for o in outs]
        return self._outputs

    def _place_rng(self, key):
        """Hook: sharded executors re-place the PRNG key on their mesh."""
        return key

    def cost_analysis(self):
        """Analytical XLA cost of THIS executor's programs, ahead of time.

        Lowers the bound inference and train-step programs from
        shape/dtype specs (no buffers touched, nothing executed, the
        global PRNG stream not consumed) and returns
        ``{"eval": {"flops", "bytes_accessed"}, "fwd_bwd": {...}}`` —
        the numbers the MFU gauges are built from, per bound executor
        instead of per process.  Entries are omitted where XLA reports
        no cost (e.g. an empty graph).
        """
        import jax
        from .telemetry import costs as _costs
        key = jax.random.PRNGKey(0)
        arg_specs = [jax.ShapeDtypeStruct(self.arg_dict[n].shape,
                                          self.arg_dict[n].dtype)
                     for n in self.arg_names]
        aux_specs = [jax.ShapeDtypeStruct(self.aux_dict[n].shape,
                                          self.aux_dict[n].dtype)
                     for n in self.aux_names]
        key_spec = jax.ShapeDtypeStruct(key.shape, key.dtype)
        out = {}
        programs = [("eval", self._eval_jit)]
        if self._grad_names:
            programs.append(("fwd_bwd", self._fwd_bwd_ones_jit))
        for label, watched in programs:
            try:
                cost = _costs.capture(
                    watched._fn, (arg_specs, aux_specs, key_spec), {},
                    force=True)
            except Exception:
                cost = None
            if cost is not None:
                out[label] = {"flops": cost[0], "bytes_accessed": cost[1]}
        return out

    def _place(self, name, arr):
        """Ensure the buffer is committed to this executor's device (cross-
        device inputs arrive when the user loads data on another context —
        reference engine would insert a CrossDeviceCopy node). Sharded
        executors override this per-name to spread batches over a mesh."""
        dev = self._ctx.jax_device
        data = arr._data
        arr_dev = getattr(data, "devices", lambda: {None})()
        if arr_dev != {dev}:
            data = jax.device_put(data, dev)
            arr._set_data(data)
        return data

    def _eager_walk(self, arg_vals, aux_vals, rng, is_train,
                    place_fn=None, observe_fn=None):
        """Node-by-node eager execution of the bound graph.

        Shared by the monitor path (observe_fn taps every output,
        ref ExecuteMonCallback graph_executor.cc:1380) and the group2ctx
        path (place_fn pins each node's compute to its __ctx_group__
        device, ref PlaceDevice graph_executor.cc:403). RNG keys follow
        the SAME split-by-rng-node-index scheme as the jitted graph_fn so
        stochastic ops agree between this walk and the vjp's replay.
        """
        from .symbol.symbol import _topo as topo
        nodes = topo(self._symbol._outputs)
        env = {}
        ai = {id(n): i for i, n in enumerate(self._arg_nodes)}
        xi = {id(n): i for i, n in enumerate(self._aux_nodes)}
        rng_nodes = [n for n in nodes if n.op is not None and n.op.needs_rng]
        rng_pos = {id(n): i for i, n in enumerate(rng_nodes)}
        keys = jax.random.split(rng, len(rng_nodes)) if rng_nodes else None

        for n in nodes:
            if n.op is None:
                val = arg_vals[ai[id(n)]] if id(n) in ai \
                    else aux_vals[xi[id(n)]]
                if place_fn is not None:
                    val = jax.device_put(val, place_fn(n))
                env[(id(n), 0)] = val
        aux_new = {id(n): None for n in self._aux_nodes}
        for node in nodes:
            if node.op is None:
                continue
            ins = [env[(id(s), oi)] for s, oi in node.inputs]
            if place_fn is not None:
                dev = place_fn(node)
                ins = [jax.device_put(v, dev) for v in ins]
            sub = keys[rng_pos[id(node)]] if node.op.needs_rng else None
            outs = node.op.traceable(node.attrs, train_mode=is_train,
                                     rng=sub)(*ins)
            outs = outs if isinstance(outs, tuple) else (outs,)
            for i, o in enumerate(outs):
                env[(id(node), i)] = o
                if observe_fn is not None:
                    observe_fn(node, i, o)
            for aux_in, oidx in (node.op.aux_updates or {}).items():
                if aux_in < len(node.inputs):
                    src, _ = node.inputs[aux_in]
                    if id(src) in aux_new:
                        aux_new[id(src)] = outs[oidx]
        outs = tuple(env[(id(n), oi)] for n, oi in self._symbol._outputs)
        new_aux = tuple(aux_new[id(n)] if aux_new[id(n)] is not None
                        else env[(id(n), 0)] for n in self._aux_nodes)
        return outs, new_aux

    def _forward_monitored(self, arg_vals, aux_vals, rng, is_train):
        """Monitor path: eager walk tapping every intermediate.

        THE SLOW PATH, by design: a compiled XLA program has no per-op
        boundaries, so an armed monitor abandons whole-program
        compilation for this batch and runs node by node.  Reserve it
        for per-activation ``pattern=`` taps; for per-parameter health
        (grad/weight norms, update ratios, loss) set
        ``MXNET_MODEL_STATS`` instead — the Monitor's compiled mode
        reads those out of the training program itself and
        ``is_active()`` keeps this walk dormant (mxnet_tpu/model_stats,
        docs/OBSERVABILITY.md §model-health)."""
        def observe(node, i, o):
            name = node.output_name(i) if i < node.num_outputs() \
                else "%s_aux%d" % (node.name, i)
            self._monitor(name, _wrap(o, self._ctx))
        return self._eager_walk(arg_vals, aux_vals, rng, is_train,
                                observe_fn=observe)

    def _forward_grouped(self, arg_vals, aux_vals, rng, is_train):
        """group2ctx path: eager walk with per-group device placement."""
        def place(node):
            group = (node.attrs or {}).get("__ctx_group__")
            ctx = self._group2ctx.get(group) if group else None
            return (ctx or self._ctx).jax_device
        return self._eager_walk(arg_vals, aux_vals, rng, is_train,
                                place_fn=place)

    def backward(self, out_grads=None, is_train=True):
        if self._vjp is None:
            if not self._grad_names:
                return  # nothing requires grad
            raise MXNetError("backward called before forward(is_train=True)")
        if out_grads is None:
            grads_in = tuple(jnp.ones_like(o._data) for o in self._outputs)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            grads_in = tuple(
                g._data if isinstance(g, NDArray) else jnp.asarray(g)
                for g in out_grads)
        if self._vjp_jitted:
            (in_grads,) = self._bwd_jit(self._vjp, grads_in)
        else:
            (in_grads,) = self._vjp(grads_in)
        self._write_grads(in_grads)

    def _write_grads(self, in_grads):
        for n, g in zip(self._grad_names, in_grads):
            dst = self.grad_dict[n]
            if self.grad_req[n] == "add":
                dst._set_data(dst._data + g.astype(dst.dtype))
            else:
                dst._set_data(g.astype(dst.dtype))

    def forward_backward(self, out_grads=None, **kwargs):
        """Forward + backward as ONE compiled XLA program (Module.fit hot
        path). Equivalent to ``forward(is_train=True)`` + ``backward()``
        but with no program boundary between the legs: XLA schedules the
        whole step, residual layouts never materialize at a program edge.
        Falls back to the two-call path under a monitor or group2ctx."""
        if self._group2ctx or (self._monitor is not None and getattr(
                self._monitor, "is_active", lambda: True)()):
            self.forward(is_train=True, **kwargs)
            self.backward(out_grads)
            return self._outputs
        if not self._grad_names:
            self.forward(is_train=True, **kwargs)
            return self._outputs
        for k, v in kwargs.items():
            if k not in self.arg_dict:
                raise MXNetError("unknown argument %s" % k)
            src = v._data if isinstance(v, NDArray) else jnp.asarray(v)
            self.arg_dict[k]._set_data(src.astype(self.arg_dict[k].dtype))
        arg_vals = [self._place(n, self.arg_dict[n]) for n in self.arg_names]
        aux_vals = [self._place(n, self.aux_dict[n]) for n in self.aux_names]
        rng = self._place_rng(_random.next_key())
        if out_grads is None:
            outs, new_aux, in_grads = self._fwd_bwd_ones_jit(
                arg_vals, aux_vals, rng)
        else:
            if isinstance(out_grads, NDArray):
                out_grads = [out_grads]
            ograds = tuple(
                g._data if isinstance(g, NDArray) else jnp.asarray(g)
                for g in out_grads)
            outs, new_aux, in_grads = self._fwd_bwd_jit(
                arg_vals, aux_vals, rng, ograds)
        for n, v in zip(self.aux_names, new_aux):
            self.aux_dict[n]._set_data(v)
        self._outputs = [_wrap(o, self._ctx) for o in outs]
        self._vjp = None  # grads already written; stale vjp must not linger
        self._write_grads(in_grads)
        return self._outputs

    # -- params ------------------------------------------------------------
    def copy_params_from(self, arg_params, aux_params=None,
                         allow_extra_params=False):
        for name, array in (arg_params or {}).items():
            if name in self.arg_dict:
                array.copyto(self.arg_dict[name])
            elif not allow_extra_params:
                raise MXNetError("Found name \"%s\" that is not in the "
                                 "arguments" % name)
        for name, array in (aux_params or {}).items():
            if name in self.aux_dict:
                array.copyto(self.aux_dict[name])
            elif not allow_extra_params:
                raise MXNetError("Found name \"%s\" that is not in the "
                                 "auxiliary states" % name)

    def reshape(self, partial_shaping=False, allow_up_sizing=False, **kwargs):
        """Return a new executor with new input shapes, sharing parameter
        values (reference executor.py reshape)."""
        new_shapes = {}
        for n in self.arg_names:
            new_shapes[n] = kwargs.get(n, self.arg_dict[n].shape)
        ex = Executor._simple_bind(self._symbol, self._ctx, self.grad_req,
                                   None, self._group2ctx,
                                   {n: kwargs[n] for n in kwargs})
        for n in ex.arg_names:
            if n not in kwargs and n in self.arg_dict and \
                    ex.arg_dict[n].shape == self.arg_dict[n].shape:
                self.arg_dict[n].copyto(ex.arg_dict[n])
        for n in ex.aux_names:
            if ex.aux_dict[n].shape == self.aux_dict[n].shape:
                self.aux_dict[n].copyto(ex.aux_dict[n])
        return ex

    def set_monitor_callback(self, callback, monitor_all=False):
        self._monitor = callback

    def debug_str(self):
        lines = ["Symbol outputs: %s" % self.output_names]
        for n in self.arg_names:
            lines.append("arg %s: %s %s" % (n, self.arg_dict[n].shape,
                                            self.grad_req[n]))
        for n in self.aux_names:
            lines.append("aux %s: %s" % (n, self.aux_dict[n].shape))
        return "\n".join(lines)

    # -- binding entry points ---------------------------------------------
    @staticmethod
    def _bind(symbol, ctx, args, args_grad, grad_req, aux_states, group2ctx):
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        if isinstance(args, (list, tuple)):
            arg_dict = dict(zip(arg_names, args))
        else:
            arg_dict = dict(args)
        if isinstance(args_grad, (list, tuple)):
            grad_dict = dict(zip(arg_names, args_grad))
        else:
            grad_dict = dict(args_grad or {})
        if isinstance(aux_states, (list, tuple)):
            aux_dict = dict(zip(aux_names, aux_states))
        else:
            aux_dict = dict(aux_states or {})
        return Executor(symbol, ctx, arg_dict, grad_dict, grad_req, aux_dict,
                        group2ctx)

    @staticmethod
    def _simple_bind(symbol, ctx, grad_req, type_dict, group2ctx,
                     shape_kwargs):
        a, o, x = symbol._infer(shape_kwargs=shape_kwargs,
                                dtype_kwargs=type_dict)
        arg_names = symbol.list_arguments()
        aux_names = symbol.list_auxiliary_states()
        unknown = [n for n, s in zip(arg_names, a) if s is None]
        if unknown:
            raise MXNetError("simple_bind could not infer shapes for %s; "
                             "pass their shapes as kwargs" % unknown)
        ctx = ctx if isinstance(ctx, Context) else Context(ctx)
        arg_dict = {n: nd_zeros(tuple(s.shape), ctx=ctx, dtype=s.dtype)
                    for n, s in zip(arg_names, a)}
        aux_dict = {n: nd_zeros(tuple(s.shape), ctx=ctx, dtype=s.dtype)
                    for n, s in zip(aux_names, x)}
        return Executor(symbol, ctx, arg_dict, None, grad_req, aux_dict,
                        group2ctx)


def _profiled(method, label):
    """Wrap an Executor method with a program span (SURVEY §5.1: the
    reference stamps engine ops; here the unit of execution is the whole
    compiled program, so that's what gets a trace event).  Spans nest —
    a forward issued inside a ``trainer_step`` span records it as parent."""
    def wrapper(self, *args, **kwargs):
        with _telemetry.span(label, cat="program"):
            return method(self, *args, **kwargs)
    wrapper.__name__ = method.__name__
    wrapper.__doc__ = method.__doc__
    return wrapper


Executor.forward = _profiled(Executor.forward, "executor_forward")
Executor.backward = _profiled(Executor.backward, "executor_backward")
Executor.forward_backward = _profiled(Executor.forward_backward,
                                      "executor_forward_backward")


def _tracecheck_executor():
    """Specimen bound executor for graftcheck: a tiny two-layer MLP with
    grads on the weights (data stays grad_req null, like Module binds)."""
    from . import symbol as S
    data = S.var("data")
    net = S.FullyConnected(data, num_hidden=8, name="tc_fc1")
    net = S.relu(net)
    net = S.FullyConnected(net, num_hidden=4, name="tc_fc2")
    net = S.sum(net)
    grad_req = {"data": "null"}
    ex = net.simple_bind(Context("cpu"), grad_req=grad_req, data=(4, 16))
    return ex


def tracecheck_programs():
    """AOT specimens for graftcheck: every program a bound executor
    ships — eval, train, fwd_vjp (residuals out), bwd (residuals in),
    and both fused fwd+bwd forms (implicit ones-grads used by Module.fit,
    explicit out_grads used by ``forward_backward(out_grads=...)``).

    The bwd program's input is the vjp residual pytree; its avals come
    from ``jax.eval_shape`` over the fwd_vjp program — shape metadata
    only, nothing executed.
    """
    ex = _tracecheck_executor()
    key = _random.next_key()
    arg_specs = [jax.ShapeDtypeStruct(ex.arg_dict[n].shape,
                                      ex.arg_dict[n].dtype)
                 for n in ex.arg_names]
    aux_specs = [jax.ShapeDtypeStruct(ex.aux_dict[n].shape,
                                      ex.aux_dict[n].dtype)
                 for n in ex.aux_names]
    key_spec = jax.ShapeDtypeStruct(key.shape, key.dtype)
    fwd = (arg_specs, aux_specs, key_spec)
    outs_spec, _aux_spec, vjp_spec = jax.eval_shape(
        ex._fwd_train_jit._fn, *fwd)
    return [
        ("executor_eval", ex._eval_jit, fwd, {}),
        ("executor_train", ex._train_jit, fwd, {}),
        ("executor_fwd_vjp", ex._fwd_train_jit, fwd, {}),
        ("executor_bwd", ex._bwd_jit, (vjp_spec, tuple(outs_spec)), {}),
        ("executor_fwd_bwd_ones", ex._fwd_bwd_ones_jit, fwd, {}),
        ("executor_fwd_bwd", ex._fwd_bwd_jit,
         fwd + (tuple(outs_spec),), {}),
    ]
