"""Training-loop callbacks: checkpointing, metric logging, throughput.

API parity with the reference's ``python/mxnet/callback.py`` (Speedometer at
:120, checkpoint helpers at :27-90), implemented independently around a small
metric-formatting helper and a wall-clock rate tracker.
"""
from __future__ import annotations

import logging
import os
import threading
import time

__all__ = ["module_checkpoint", "do_checkpoint", "log_train_metric",
           "Speedometer", "ProgressBar"]


def _metric_pairs(metric):
    """Flatten an EvalMetric into a list of (name, value) tuples, or []."""
    if metric is None:
        return []
    return list(metric.get_name_value())


def _fmt_pairs(pairs):
    return "".join("\t%s=%f" % nv for nv in pairs)


def module_checkpoint(mod, prefix, period=1, save_optimizer_states=False):
    """Return an epoch-end callback that snapshots *mod* every *period* epochs.

    The callback signature matches the reference contract
    ``cb(epoch, symbol, arg_params, aux_params)``; only the epoch number is
    consulted — the module itself knows its parameters.
    """
    every = max(int(period), 1)

    def _on_epoch_end(epoch, sym=None, arg=None, aux=None):
        done = epoch + 1
        if done % every == 0:
            mod.save_checkpoint(prefix, done, save_optimizer_states)

    return _on_epoch_end


# One engine variable per checkpoint prefix (absolute path), so every
# async writer targeting the same files serializes — and repeated
# callback construction reuses the variable instead of leaking one each.
_PREFIX_VARS = {}
_PREFIX_VARS_LOCK = threading.Lock()


def _prefix_var(prefix):
    from . import engine as _engine
    key = os.path.abspath(prefix)
    with _PREFIX_VARS_LOCK:
        if key not in _PREFIX_VARS:
            _PREFIX_VARS[key] = _engine.engine().new_variable()
        return _PREFIX_VARS[key]


def do_checkpoint(prefix, period=1, async_write=False):
    """Return an epoch-end callback writing ``prefix-symbol.json`` +
    ``prefix-NNNN.params`` every *period* epochs (ref callback.py:56).

    ``async_write=True`` schedules the serialization on the host-task
    engine so the save overlaps the next epoch's compute, the way the
    reference pushed IO through its dependency engine: parameters are
    snapshotted zero-copy at callback time (immutable device buffers),
    and writes to one *prefix* serialize on a shared per-prefix engine
    variable (two callbacks on the same prefix cannot interleave).
    Pending saves drain at ``engine.wait_for_all()``, where IO errors
    re-raise; at interpreter exit remaining saves drain automatically
    and errors are logged.
    """
    from .model import save_checkpoint as _save
    every = max(int(period), 1)

    if async_write:
        from . import engine as _engine
        ckpt_var = _prefix_var(prefix)

    def _on_epoch_end(epoch, sym, arg, aux):
        done = epoch + 1
        if done % every != 0:
            return
        if not async_write:
            _save(prefix, done, sym, arg, aux)
            return
        snap_arg = {k: v.detach() for k, v in arg.items()}
        snap_aux = {k: v.detach() for k, v in aux.items()}
        _engine.engine().push(
            lambda d=done, a=snap_arg, x=snap_aux:
                _save(prefix, d, sym, a, x),
            mutable_vars=[ckpt_var])

    return _on_epoch_end


def log_train_metric(period, auto_reset=False):
    """Return a batch-end callback logging the running training metric
    every *period* batches (ref callback.py:84)."""

    def _on_batch_end(param):
        if param.nbatch % period != 0:
            return
        for name, value in _metric_pairs(param.eval_metric):
            logging.info("Iter[%d] Batch[%d] Train-%s=%f",
                         param.epoch, param.nbatch, name, value)
        if auto_reset and param.eval_metric is not None:
            param.eval_metric.reset()

    return _on_batch_end


class Speedometer:
    """Batch-end callback printing samples/sec every ``frequent`` batches.

    Mirrors the reference Speedometer (callback.py:120): the first batch of an
    epoch only arms the timer; subsequent multiples of ``frequent`` report the
    rate over the window since the last report and (optionally) reset the
    metric so each report covers only its own window.
    """

    def __init__(self, batch_size, frequent=50, auto_reset=True):
        self.batch_size = batch_size
        self.frequent = frequent
        self.auto_reset = auto_reset
        self._mark = None       # wall-clock at window start; None = disarmed
        self._prev_batch = -1

    def __call__(self, param):
        nbatch = param.nbatch
        if nbatch < self._prev_batch:   # new epoch rewound the counter
            self._mark = None
        self._prev_batch = nbatch

        if self._mark is None:
            self._mark = time.time()
            return
        if nbatch % self.frequent != 0:
            return

        elapsed = time.time() - self._mark
        rate = self.frequent * self.batch_size / max(elapsed, 1e-12)
        pairs = _metric_pairs(param.eval_metric)
        if pairs:
            if self.auto_reset:
                param.eval_metric.reset()
            logging.info("Epoch[%d] Batch [%d]\tSpeed: %.2f samples/sec%s",
                         param.epoch, nbatch, rate, _fmt_pairs(pairs))
        else:
            logging.info("Iter[%d] Batch [%d]\tSpeed: %.2f samples/sec",
                         param.epoch, nbatch, rate)
        self._mark = time.time()


class ProgressBar:
    """Batch-end callback drawing an ASCII progress bar (ref callback.py:169)."""

    def __init__(self, total, length=80):
        self.total = max(int(total), 1)
        self.bar_len = int(length)

    def __call__(self, param):
        frac = min(param.nbatch / float(self.total), 1.0)
        ticks = int(self.bar_len * frac + 0.5)
        bar = "=" * ticks + "-" * (self.bar_len - ticks)
        logging.info("[%s] %d%%\r", bar, int(frac * 100 + 0.999))
