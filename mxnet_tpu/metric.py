"""Evaluation metrics.

API parity with the reference ``python/mxnet/metric.py:44-1167`` (EvalMetric
base, registry + ``create``, Accuracy/TopK/F1/Perplexity/regression-error/
CrossEntropy/Pearson/Loss/Custom families). Independent design: most metrics
derive from ``_PairAccumulator``, which owns the per-(label, pred) iteration
and running-sum bookkeeping; each concrete metric contributes a single
``measure(label, pred) -> (value, count)`` function on numpy arrays.
"""
from __future__ import annotations

import math

import numpy as _np

from .base import Registry
from . import ndarray as nd
from . import telemetry as _tel
from .ndarray import NDArray

__all__ = ["EvalMetric", "CompositeEvalMetric", "Accuracy", "TopKAccuracy",
           "F1", "Perplexity", "MAE", "MSE", "RMSE", "CrossEntropy",
           "NegativeLogLikelihood", "PearsonCorrelation", "Loss", "Torch",
           "Caffe", "CustomMetric", "np", "create", "check_label_shapes"]

_REG = Registry("metric")


def check_label_shapes(labels, preds, shape=False):
    """Raise when label/pred list lengths (or array shapes) disagree."""
    got = (labels.shape, preds.shape) if shape else (len(labels), len(preds))
    if got[0] != got[1]:
        raise ValueError("Shape of labels {} does not match shape of "
                         "predictions {}".format(got[0], got[1]))


def _numpy(x):
    return x.asnumpy() if isinstance(x, NDArray) else _np.asarray(x)


def _finite_contribution(value):
    """Gate one accumulator contribution: a NaN/Inf value would poison
    the running sum FOREVER (every later ``get()`` reports NaN, long
    after the sick batch scrolled off the log).  Nonfinite updates are
    excluded and booked as ``metric_nonfinite_updates`` so the exclusion
    is visible instead of silent."""
    if math.isfinite(value):
        return True
    _tel.bump("metric_nonfinite_updates")
    return False


def _column(arr):
    """Ensure a 2-D (n, k) view for regression metrics."""
    a = _numpy(arr)
    return a.reshape(-1, 1) if a.ndim == 1 else a


class EvalMetric:
    """Running-average metric base (ref metric.py:44).

    State is a (sum_metric, num_inst) pair; ``get`` reports their ratio.
    """

    def __init__(self, name, output_names=None, label_names=None, **kwargs):
        self.name = str(name)
        self.output_names, self.label_names = output_names, label_names
        self._kwargs = kwargs
        self.reset()

    def __str__(self):
        return "EvalMetric: %s" % dict(self.get_name_value())

    def get_config(self):
        cfg = dict(self._kwargs,
                   metric=type(self).__name__, name=self.name,
                   output_names=self.output_names,
                   label_names=self.label_names)
        return cfg

    def update_dict(self, label, pred):
        """Update from name→array dicts, selecting declared names if any."""
        preds = [pred[n] for n in self.output_names] \
            if self.output_names is not None else list(pred.values())
        labels = [label[n] for n in self.label_names] \
            if self.label_names is not None else list(label.values())
        self.update(labels, preds)

    def update(self, labels, preds):
        raise NotImplementedError()

    def reset(self):
        self.sum_metric, self.num_inst = 0.0, 0

    def get(self):
        if not self.num_inst:
            return self.name, float("nan")
        return self.name, self.sum_metric / self.num_inst

    def get_name_value(self):
        names, values = self.get()
        if not isinstance(names, list):
            names, values = [names], [values]
        return list(zip(names, values))


class _PairAccumulator(EvalMetric):
    """Template for metrics that reduce each (label, pred) pair to a
    (contribution, count) tuple via :meth:`measure`."""

    check_shapes = True

    def update(self, labels, preds):
        if self.check_shapes:
            check_label_shapes(labels, preds)
        for label, pred in zip(labels, preds):
            value, count = self.measure(_numpy(label), _numpy(pred))
            if not _finite_contribution(float(value)):
                continue
            self.sum_metric += value
            self.num_inst += count

    def measure(self, label, pred):
        raise NotImplementedError()


_ALIASES = {
    "Accuracy": ["acc"], "TopKAccuracy": ["top_k_accuracy", "top_k_acc"],
    "CrossEntropy": ["ce"], "NegativeLogLikelihood": ["nll_loss"],
    "PearsonCorrelation": ["pearsonr"], "CompositeEvalMetric": ["composite"],
}


def register(klass):
    _REG.register(klass, klass.__name__,
                  aliases=_ALIASES.get(klass.__name__, ()))
    return klass


def create(metric, *args, **kwargs):
    """Build a metric from a callable, instance, list, or registered name."""
    if callable(metric):
        return CustomMetric(metric, *args, **kwargs)
    if isinstance(metric, EvalMetric):
        return metric
    if isinstance(metric, list):
        bundle = CompositeEvalMetric()
        for item in metric:
            bundle.add(create(item, *args, **kwargs))
        return bundle
    return _REG.get(metric)(*args, **kwargs)


@register
class CompositeEvalMetric(EvalMetric):
    """Fan-out wrapper reporting every child metric's name/value."""

    def __init__(self, metrics=None, name="composite", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names)
        self.metrics = [create(m) for m in (metrics or [])]

    def add(self, metric):
        self.metrics.append(create(metric))

    def get_metric(self, index):
        return self.metrics[index]

    def update_dict(self, labels, preds):
        for child in self.metrics:
            child.update_dict(labels, preds)

    def update(self, labels, preds):
        for child in self.metrics:
            child.update(labels, preds)

    def reset(self):
        for child in getattr(self, "metrics", ()):
            child.reset()

    def get(self):
        names, values = [], []
        for child in self.metrics:
            n, v = child.get()
            names += n if isinstance(n, list) else [n]
            values += v if isinstance(v, list) else [v]
        return names, values


@register
class Accuracy(_PairAccumulator):
    """Top-1 classification accuracy; argmaxes preds when ranks differ."""

    def __init__(self, axis=1, name="accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, axis=axis)
        self.axis = axis

    def measure(self, label, pred):
        # argmax whenever SHAPES differ, not just ranks: 2D sequence
        # labels (batch, seq) vs (batch*seq, vocab) scores must reduce
        # too (ref python/mxnet/metric.py:391-392)
        if pred.shape != label.shape:
            pred = pred.argmax(axis=self.axis)
        check_label_shapes(label.ravel(), pred.ravel(), shape=True)
        hits = pred.astype("int64").ravel() == label.astype("int64").ravel()
        return int(hits.sum()), hits.size


@register
class TopKAccuracy(_PairAccumulator):
    """Fraction of rows whose label lands in the top-k scored classes."""

    def __init__(self, top_k=1, name="top_k_accuracy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, top_k=top_k)
        if top_k <= 1:
            raise ValueError("use Accuracy for top_k <= 1")
        self.top_k = top_k
        self.name = "%s_%d" % (self.name, top_k)

    def measure(self, label, pred):
        if pred.ndim > 2:
            raise ValueError("Predictions should be no more than 2 dims")
        label = label.astype("int64").ravel()
        if pred.ndim == 1:
            return int((pred.astype("int64") == label).sum()), label.size
        k = min(self.top_k, pred.shape[1])
        # indices of the k best classes per row
        ranked = _np.argsort(pred.astype("float32"), axis=1)[:, -k:]
        hits = (ranked == label[:, None]).any(axis=1)
        return int(hits.sum()), label.size


@register
class F1(_PairAccumulator):
    """Binary F1 over argmaxed predictions, one score per batch."""

    def __init__(self, name="f1", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def measure(self, label, pred):
        label = label.astype("int64").ravel()
        if _np.unique(label).size > 2:
            raise ValueError("F1 currently only supports binary classification.")
        decided = pred.argmax(axis=1)
        tp = float(((decided == 1) & (label == 1)).sum())
        fp = float(((decided == 1) & (label == 0)).sum())
        fn = float(((decided == 0) & (label == 1)).sum())
        prec = tp / (tp + fp) if tp + fp else 0.0
        rec = tp / (tp + fn) if tp + fn else 0.0
        score = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
        return score, 1


@register
class Perplexity(EvalMetric):
    """exp(mean negative log prob of the target class), with an optional
    ignored label id (padding)."""

    def __init__(self, ignore_label, axis=-1, name="perplexity",
                 output_names=None, label_names=None):
        super().__init__(name, output_names, label_names,
                         ignore_label=ignore_label, axis=axis)
        self.ignore_label = ignore_label
        self.axis = axis

    def update(self, labels, preds):
        if len(labels) != len(preds):
            raise ValueError("label/pred list length mismatch")
        for label, pred in zip(labels, preds):
            if label.size != pred.size // pred.shape[-1]:
                raise ValueError("shape mismatch: %s vs. %s"
                                 % (label.shape, pred.shape))
            flat = label.as_in_context(pred.context).reshape((label.size,))
            target_p = nd.pick(pred, flat.astype(dtype="int32"),
                               axis=self.axis).asnumpy()
            lab = flat.asnumpy()
            count = target_p.size
            if self.ignore_label is not None:
                masked = lab == self.ignore_label
                count -= int(masked.sum())
                target_p = _np.where(masked, 1.0, target_p)
            value = -float(_np.log(_np.maximum(target_p, 1e-10)).sum())
            if not _finite_contribution(value):
                continue
            self.sum_metric += value
            self.num_inst += count

    def get(self):
        if not self.num_inst:
            return self.name, float("nan")
        return self.name, math.exp(self.sum_metric / self.num_inst)


@register
class MAE(_PairAccumulator):
    def __init__(self, name="mae", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def measure(self, label, pred):
        return float(_np.abs(_column(label) - _column(pred)).mean()), 1


@register
class MSE(_PairAccumulator):
    def __init__(self, name="mse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def measure(self, label, pred):
        return float(((_column(label) - _column(pred)) ** 2).mean()), 1


@register
class RMSE(_PairAccumulator):
    def __init__(self, name="rmse", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def measure(self, label, pred):
        return float(_np.sqrt(((_column(label) - _column(pred)) ** 2).mean())), 1


@register
class CrossEntropy(_PairAccumulator):
    """Mean -log p(target) given per-class probability rows."""

    def __init__(self, eps=1e-12, name="cross-entropy", output_names=None,
                 label_names=None):
        super().__init__(name, output_names, label_names, eps=eps)
        self.eps = eps

    def measure(self, label, pred):
        idx = label.ravel().astype("int64")
        if idx.shape[0] != pred.shape[0]:
            raise ValueError("label/pred row mismatch")
        target_p = pred[_np.arange(idx.shape[0]), idx]
        return float(-_np.log(target_p + self.eps).sum()), idx.shape[0]


@register
class NegativeLogLikelihood(CrossEntropy):
    def __init__(self, eps=1e-12, name="nll-loss", output_names=None,
                 label_names=None):
        super().__init__(eps=eps, name=name, output_names=output_names,
                         label_names=label_names)


@register
class PearsonCorrelation(_PairAccumulator):
    def __init__(self, name="pearsonr", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def measure(self, label, pred):
        check_label_shapes(label, pred, shape=True)
        return float(_np.corrcoef(pred.ravel(), label.ravel())[0, 1]), 1


@register
class Loss(EvalMetric):
    """Mean of raw outputs — pair with loss-valued heads."""

    def __init__(self, name="loss", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)

    def update(self, _, preds):
        for pred in preds:
            value = float(_numpy(pred).sum())
            if not _finite_contribution(value):
                continue
            self.sum_metric += value
            self.num_inst += pred.size


@register
class Torch(Loss):
    def __init__(self, name="torch", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class Caffe(Loss):
    def __init__(self, name="caffe", output_names=None, label_names=None):
        super().__init__(name, output_names, label_names)


@register
class CustomMetric(EvalMetric):
    """Adapter for a user ``feval(label, pred)`` returning a value or a
    (sum, count) tuple."""

    def __init__(self, feval, name=None, allow_extra_outputs=False,
                 output_names=None, label_names=None):
        if name is None:
            name = feval.__name__
            if "<" in name:
                name = "custom(%s)" % name
        super().__init__(name, output_names, label_names, feval=feval,
                         allow_extra_outputs=allow_extra_outputs)
        self._feval = feval
        self._allow_extra_outputs = allow_extra_outputs

    def update(self, labels, preds):
        if not self._allow_extra_outputs:
            check_label_shapes(labels, preds)
        for pred, label in zip(preds, labels):
            result = self._feval(_numpy(label), _numpy(pred))
            if isinstance(result, tuple):
                self.sum_metric += result[0]
                self.num_inst += result[1]
            else:
                self.sum_metric += result
                self.num_inst += 1


def np(numpy_feval, name=None, allow_extra_outputs=False):
    """Wrap a numpy eval function as a CustomMetric (ref metric.np)."""
    def feval(label, pred):
        return numpy_feval(label, pred)
    feval.__name__ = numpy_feval.__name__
    return CustomMetric(feval, name, allow_extra_outputs)
