"""tracecheck: trace-time jaxpr/HLO analysis of the owned XLA entry points.

graftlint (``rules.py``) works on source text; whole classes of silent
performance/correctness bugs only exist in the *lowered program* and are
invisible to an AST pass — a closure-baked weight matrix, an accidental
f64 widening, a host callback compiled into the train step, a donated
buffer that can never alias an output.  The reference framework closed
the same gap with graph-level passes over NNVM IR rather than C++ lint
(SURVEY layer map; cf. TVM/NNVM graph passes and Grappler's analyzers in
PAPERS.md).  This module is that tier for the JAX rebuild: it lowers the
programs the framework actually ships to XLA — AOT, on CPU, from
``ShapeDtypeStruct`` specimens, no TPU and no real data — and walks the
resulting jaxprs with a rule registry mirroring graftlint's.

Rule catalogue (rationale in docs/LINT.md):

JX101 baked-constant          large arrays captured by closure become
                              jaxpr constants: copied into every compiled
                              variant, silently stale after updates.
JX102 dtype-widening          f64/i64 appearing in a program whose inputs
                              are all <=32-bit: 2x HBM + matmul slowdown,
                              usually one forgotten ``np.float64`` scalar.
JX103 host-callback           ``pure_callback``/``io_callback``/
                              ``debug.print`` compiled into an owned hot
                              program: a host round-trip per step.
JX104 donation-waste          donated args that cannot alias any output
                              (buffer freed for nothing), large
                              non-donated args that alias outputs in a
                              program that already donates, and dead
                              (pass-through / constant) outputs.
JX105 retrace-explainer       on a ``watch_jit`` recompile, diff the new
                              avals/statics against the cached variants
                              and NAME the axis that changed — turns the
                              telemetry retrace-storm warning into a
                              diagnosis.  Runtime-only (``MXNET_TRACECHECK``).

The JX2xx family (ISSUE 18) adds the SPMD/memory tier — collective
safety and device-memory budgets proven AOT over the same ledger:

JX201 collective-divergence   a collective (psum/all_gather/ppermute/
                              all_to_all/reduce_scatter) whose rendezvous
                              depends on a data-dependent branch: the two
                              arms of a ``lax.cond`` disagree on their
                              collective sequence, or a collective sits
                              inside a ``while`` whose trip count ranks
                              can disagree on — one rank enters the
                              collective, its peers never do, the mesh
                              deadlocks.  The guardian ``jnp.where``-skip
                              pattern is the clean twin: every rank runs
                              the same collectives, the *values* branch.
JX202 collective-order        per-mesh-axis collective sequences must be
                              identical across programs sharing a lane
                              (provider ``meta={"lane": ...}``) and must
                              only touch axes the provider declared
                              (``meta={"mesh_axes": ...}``) — the PR-13
                              descending-bucket canonical-order contract
                              as a proven invariant, not a comment.
JX203 replication-waste       an ``all_gather`` whose fully-replicated
                              result is returned as a program/shard_map
                              output: the sharded producer's bytes are
                              multiplied by the axis size in HBM — the
                              accidental gather that blows memory.
JX204 memory-budget           per-program ``compiled.memory_analysis()``
                              (argument/output/temp/generated-code bytes)
                              against the count-keyed MEM_BASELINE.json
                              with an ``MXNET_MEM_TOLERANCE`` band: a
                              program growing past budget is a lint-time
                              finding instead of an OOM at step time.

Two drivers share the registry:

* AOT (``check_entry_points`` / ``tools/graftcheck.py`` /
  ``python -m mxnet_tpu.lint --trace``): every owned jit entry point
  declares a ``tracecheck_programs()`` provider next to the jit itself
  (executor, fused trainer, optimizer, kvstore, module cached step,
  gluon cached op); the driver traces each with specimen shapes and runs
  JX101-JX104.  CI gates on zero findings (tests/test_tracecheck_clean.py).
* Runtime (``on_compile``): ``telemetry._WatchedJit`` calls in on every
  compile event when ``MXNET_TRACECHECK`` is truthy; findings are booked
  into the ``tracecheck_findings`` counter, the flight ring, and one
  structured log line each — JX105 included, because only the runtime
  hook sees *two* variants to diff.

Import-light on purpose: jax is imported inside functions only, so the
stdlib-only lint CLI can show the JX catalogue (``--list-rules``) without
initializing a backend.
"""
from __future__ import annotations

import json
import logging
import os

from .core import Finding

__all__ = ["TRACE_RULES", "GROUP_RULES", "TraceRule", "TraceConfig",
           "ProgramRecord", "trace_program", "run_rules",
           "run_group_rules", "check_entry_points", "analyze_entry_points",
           "iter_owned_programs", "groups_for_paths", "on_compile",
           "signature", "explain_retrace", "ENTRY_POINTS",
           "collective_sequence", "measure_memory", "compile_record",
           "mem_tolerance", "load_mem_baseline", "save_mem_baseline",
           "default_mem_baseline_path", "MEM_FIELDS"]
# NOTE: the MXNET_TRACECHECK gate itself lives in telemetry.core
# (_env_tracecheck) — the hook's caller owns the env parsing.

_LOG = logging.getLogger("mxnet_tpu.lint.tracecheck")

_WIDE_DTYPES = ("float64", "int64", "uint64", "complex128")


class TraceConfig:
    """Thresholds for the size-gated rules.

    The defaults are deliberately conservative: the AOT driver runs tiny
    specimen models, so an owned entry point only fires when it bakes or
    wastes something *structurally* (a closure-captured table, an
    unaliasable donation), never because a real model is large.  Tests
    shrink the thresholds to exercise the rules on toy programs.
    """

    __slots__ = ("const_bytes", "donation_bytes", "passthrough_bytes",
                 "replication_bytes")

    def __init__(self, const_bytes=64 << 10, donation_bytes=1 << 20,
                 passthrough_bytes=64 << 10, replication_bytes=64 << 10):
        self.const_bytes = const_bytes
        self.donation_bytes = donation_bytes
        self.passthrough_bytes = passthrough_bytes
        self.replication_bytes = replication_bytes


DEFAULT_CONFIG = TraceConfig()


# ---------------------------------------------------------------------------
# rule registry (mirrors rules.RULES)
# ---------------------------------------------------------------------------

TRACE_RULES = {}


class TraceRule:
    __slots__ = ("code", "name", "rationale", "_check")

    def __init__(self, code, name, rationale, check):
        self.code, self.name, self.rationale = code, name, rationale
        self._check = check

    def check(self, record, config):
        if self._check is None:        # runtime-only rule (JX105)
            return []
        return list(self._check(record, config))


def trace_rule(code, name, rationale):
    def deco(fn):
        TRACE_RULES[code] = TraceRule(code, name, rationale, fn)
        return fn
    return deco


# ---------------------------------------------------------------------------
# program record: one traced entry point
# ---------------------------------------------------------------------------

def _spec(leaf):
    """ShapeDtypeStruct skeleton of one pytree leaf (python scalars pass
    through and trace as weak-typed scalars, exactly like at runtime)."""
    shape = getattr(leaf, "shape", None)
    dtype = getattr(leaf, "dtype", None)
    if shape is None or dtype is None:
        return leaf
    import jax
    return jax.ShapeDtypeStruct(tuple(shape), dtype)


def _aval_nbytes(aval):
    n = 1
    for d in getattr(aval, "shape", ()):
        n *= int(d)
    dtype = getattr(aval, "dtype", None)
    return n * (dtype.itemsize if dtype is not None else 1)


def _aval_key(aval):
    return (tuple(getattr(aval, "shape", ())), str(getattr(aval, "dtype",
                                                           "?")))


def _fmt_aval(aval):
    return "%s[%s]" % (getattr(aval, "dtype", "?"),
                       ",".join(str(d) for d in getattr(aval, "shape", ())))


class ProgramRecord:
    """One owned program, traced: jaxpr + flat arg labels/avals/donation.

    ``lowered`` keeps the AOT lowering so JX204 can compile for
    ``memory_analysis()`` without re-tracing; ``meta`` carries the
    provider's sharding metadata (``lane``/``mesh_axes``) for JX202.
    """

    __slots__ = ("name", "origin", "closed_jaxpr", "arg_labels", "in_avals",
                 "donated", "out_avals", "lowered", "meta")

    def __init__(self, name, origin, closed_jaxpr, arg_labels, in_avals,
                 donated, out_avals, lowered=None, meta=None):
        self.name = name
        self.origin = origin
        self.closed_jaxpr = closed_jaxpr
        self.arg_labels = arg_labels      # flat, parallel to in_avals
        self.in_avals = in_avals
        self.donated = donated            # set of flat arg indices
        self.out_avals = out_avals
        self.lowered = lowered
        self.meta = dict(meta or {})

    @property
    def jaxpr(self):
        return self.closed_jaxpr.jaxpr

    @property
    def consts(self):
        return self.closed_jaxpr.consts

    def label(self, i):
        if 0 <= i < len(self.arg_labels):
            return self.arg_labels[i]
        return "arg[%d]" % i

    def finding(self, rule, message, key=""):
        """A Finding whose fingerprint is stable across runs: the path is
        the program identity, the snippet a short structural key (NOT the
        prose message, which may carry sizes that drift)."""
        return Finding(rule, "trace://%s" % self.name, 0, 0,
                       "%s [%s]: %s" % (self.name, self.origin, message),
                       snippet=key or rule)


def trace_program(name, fn, args, kwargs=None, origin="", meta=None):
    """Trace *fn* (a jitted callable or its watch_jit wrapper) with
    ShapeDtypeStruct skeletons of *args*/*kwargs* and return the
    :class:`ProgramRecord` the JX rules analyze.  Nothing is compiled or
    executed; lowering metadata supplies per-argument donation flags.
    (JX204 compiles *later*, from the kept lowering, only when a memory
    budget is actually being checked.)
    """
    import jax
    kwargs = dict(kwargs or {})
    fn = getattr(fn, "_fn", fn)          # unwrap telemetry._WatchedJit
    sargs, skwargs = jax.tree_util.tree_map(_spec, (tuple(args), kwargs))
    traced = fn.trace(*sargs, **skwargs)
    closed = traced.jaxpr
    lowered = traced.lower()

    flat, _ = jax.tree_util.tree_flatten_with_path((sargs, skwargs))
    labels = []
    for path, _leaf in flat:
        label = jax.tree_util.keystr(path)
        # keystr yields "[0][1]['lr']": [0]=args/[1]=kwargs bucket, next
        # index the position — keep it verbatim but drop the bucket
        labels.append("arg%s" % label[3:] if label.startswith("[0]")
                      else "kwarg%s" % label[3:])

    donated = set()
    info_leaves = jax.tree_util.tree_leaves(
        lowered.args_info, is_leaf=lambda v: hasattr(v, "donated"))
    for i, info in enumerate(info_leaves):
        if getattr(info, "donated", False):
            donated.add(i)

    return ProgramRecord(name, origin, closed, labels,
                         list(closed.in_avals), donated,
                         list(closed.out_avals), lowered=lowered,
                         meta=meta)


def _iter_eqns(jaxpr):
    """Every eqn in *jaxpr* and its nested sub-jaxprs (pjit bodies, scan
    carries, cond branches, custom-vjp closures, ...)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from _iter_eqns(sub)


def _sub_jaxprs(eqn):
    for val in eqn.params.values():
        yield from _extract_jaxprs(val)


def _extract_jaxprs(val):
    # a ClosedJaxpr has .jaxpr; a raw Jaxpr has .eqns
    inner = getattr(val, "jaxpr", None)
    if inner is not None and hasattr(inner, "eqns"):
        yield inner
    elif hasattr(val, "eqns"):
        yield val
    elif isinstance(val, (tuple, list)):
        for item in val:
            yield from _extract_jaxprs(item)


def _all_jaxprs(jaxpr):
    """*jaxpr* and every nested sub-jaxpr, each as its own scope (JX203
    needs per-scope outvars, not just the flat eqn stream)."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for sub in _sub_jaxprs(eqn):
            yield from _all_jaxprs(sub)


# ---------------------------------------------------------------------------
# collective extraction (shared by JX201/JX202/JX203)
# ---------------------------------------------------------------------------

# jaxpr-level cross-rank primitives.  GSPMD-inserted collectives (from
# jit out_shardings) are out of scope on purpose: the partitioner emits
# them uniformly on every rank — divergence risk lives in hand-written
# shard_map bodies, which is exactly what lowers to these primitives.
_COLLECTIVE_PRIMS = {"psum", "psum2", "pmax", "pmin", "all_gather",
                     "all_to_all", "reduce_scatter", "psum_scatter",
                     "ppermute", "pshuffle", "axis_index"}
# psum2: what shard_map's replication checker rewrites psum into — the
# same all-reduce rendezvous under a different primitive name.
# axis_index is rank-local (no rendezvous): tracked for JX202's declared-
# axis check but excluded from order/divergence sequences.
_RENDEZVOUS_PRIMS = _COLLECTIVE_PRIMS - {"axis_index"}


def _collective_axes(eqn):
    """Named mesh axes a collective eqn communicates over.  ``psum``
    carries ``axes``, the permute/gather family ``axis_name``; positional
    (int) axes are vmap-internal, not cross-rank, and are dropped.  An
    empty result means no communication (e.g. ``psum(x, axes=())``)."""
    axes = eqn.params.get("axes")
    if axes is None:
        axes = eqn.params.get("axis_name")
    if axes is None:
        return ()
    if not isinstance(axes, (tuple, list)):
        axes = (axes,)
    return tuple(str(a) for a in axes if not isinstance(a, int))


def _collectives_in(jaxpr):
    """Ordered ``(primitive, axes)`` rendezvous sequence of *jaxpr*
    (nested scopes included, eqn order — the order ranks meet in)."""
    out = []
    for eqn in _iter_eqns(jaxpr):
        prim = eqn.primitive.name
        if prim in _RENDEZVOUS_PRIMS:
            axes = _collective_axes(eqn)
            if axes:
                # one rendezvous, two spellings: sequences must compare
                # equal whether or not the rep-checker rewrote the prim
                out.append(("psum" if prim == "psum2" else prim, axes))
    return tuple(out)


def collective_sequence(record):
    """Per-mesh-axis ordered collective op sequence of a program —
    ``{"pipe": ("ppermute", "psum"), ...}`` — the JX202 comparison key."""
    seq = {}
    for prim, axes in _collectives_in(record.jaxpr):
        for axis in axes:
            seq.setdefault(axis, []).append(prim)
    return {axis: tuple(ops) for axis, ops in seq.items()}


# ---------------------------------------------------------------------------
# JX101 baked-constant
# ---------------------------------------------------------------------------

@trace_rule("JX101", "baked-constant",
            "large arrays captured by closure become jaxpr constants — "
            "copied into every compiled variant and silently stale after "
            "host-side updates; pass them as arguments")
def _jx101(rec, cfg):
    for var, const in zip(rec.jaxpr.constvars, rec.consts):
        nbytes = _aval_nbytes(var.aval)
        if nbytes < cfg.const_bytes:
            continue
        yield rec.finding(
            "JX101",
            "%s constant (%d bytes) baked into the program — a closure "
            "capture; the compiled program holds a frozen copy that host "
            "mutations never reach. Pass it as an argument instead."
            % (_fmt_aval(var.aval), nbytes),
            key="const:%s" % _fmt_aval(var.aval))


# ---------------------------------------------------------------------------
# JX102 dtype-widening
# ---------------------------------------------------------------------------

@trace_rule("JX102", "dtype-widening",
            "f64/i64 values inside a program whose inputs are all "
            "<=32-bit: doubled HBM traffic and slow double-precision "
            "units, usually one forgotten numpy float64 scalar")
def _jx102(rec, cfg):
    def wide(aval):
        return str(getattr(aval, "dtype", "")) in _WIDE_DTYPES

    if any(wide(a) for a in rec.in_avals):
        return          # wide inputs: the caller asked for 64-bit
    seen = set()
    for var, _const in zip(rec.jaxpr.constvars, rec.consts):
        if wide(var.aval):
            key = ("const", str(var.aval.dtype))
            if key not in seen:
                seen.add(key)
                yield rec.finding(
                    "JX102",
                    "closure constant is %s while every program input is "
                    "<=32-bit — the widening happens before the program "
                    "boundary" % _fmt_aval(var.aval),
                    key="widen-const:%s" % var.aval.dtype)
    for eqn in _iter_eqns(rec.jaxpr):
        for var in eqn.outvars:
            aval = getattr(var, "aval", None)
            if aval is None or not wide(aval):
                continue
            key = (eqn.primitive.name, str(aval.dtype))
            if key in seen:
                continue
            seen.add(key)
            yield rec.finding(
                "JX102",
                "'%s' produces %s in a program whose inputs are all "
                "<=32-bit — check for a python float / np.float64 scalar "
                "or an explicit astype widening the lattice"
                % (eqn.primitive.name, _fmt_aval(aval)),
                key="widen:%s:%s" % (eqn.primitive.name, aval.dtype))


# ---------------------------------------------------------------------------
# JX103 host-callback-in-hot-program
# ---------------------------------------------------------------------------

_CALLBACK_PRIMS = {"pure_callback", "io_callback", "debug_callback"}

@trace_rule("JX103", "host-callback",
            "pure_callback/io_callback/debug.print compiled into an owned "
            "hot program: every execution round-trips through the host — "
            "the async dispatch pipeline stalls behind python")
def _jx103(rec, cfg):
    seen = set()
    for eqn in _iter_eqns(rec.jaxpr):
        prim = eqn.primitive.name
        if prim not in _CALLBACK_PRIMS or prim in seen:
            continue
        seen.add(prim)
        yield rec.finding(
            "JX103",
            "'%s' is compiled into this program: a host python call per "
            "execution. Debug prints belong outside the jit; data-dependent "
            "host logic belongs between programs, not inside them." % prim,
            key="callback:%s" % prim)


# ---------------------------------------------------------------------------
# JX104 donation-waste
# ---------------------------------------------------------------------------

@trace_rule("JX104", "donation-waste",
            "donated buffers that cannot alias any output (freed for "
            "nothing), large aliasable args left undonated in a program "
            "that already donates, and dead pass-through/constant outputs")
def _jx104(rec, cfg):
    # multiset of output avals available for aliasing
    pool = {}
    for aval in rec.out_avals:
        key = _aval_key(aval)
        pool[key] = pool.get(key, 0) + 1

    # donated args consume matching outputs first (they will alias)
    for i in sorted(rec.donated):
        aval = rec.in_avals[i]
        key = _aval_key(aval)
        if pool.get(key, 0) > 0:
            pool[key] -= 1
        else:
            yield rec.finding(
                "JX104",
                "%s (%s) is donated but no output has a matching "
                "shape/dtype — XLA frees the buffer without reusing it, "
                "and the caller lost the ability to read it for nothing"
                % (rec.label(i), _fmt_aval(aval)),
                key="donate-unaliasable:%s" % rec.label(i))

    # a program that already donates, leaving a LARGE aliasable arg
    # undonated, is leaving HBM on the table (grads kept for grad_req=add
    # are the legitimate exception — suppress or baseline those)
    if rec.donated:
        for i, aval in enumerate(rec.in_avals):
            if i in rec.donated:
                continue
            nbytes = _aval_nbytes(aval)
            if nbytes < cfg.donation_bytes:
                continue
            key = _aval_key(aval)
            if pool.get(key, 0) > 0:
                pool[key] -= 1
                yield rec.finding(
                    "JX104",
                    "%s (%s, %d bytes) aliases an output aval but is not "
                    "donated in a program that donates other args — "
                    "donating it would save one HBM-resident copy"
                    % (rec.label(i), _fmt_aval(aval), nbytes),
                    key="donate-missed:%s" % rec.label(i))

    # dead outputs: identity pass-through of an input, or a constant
    invar_pos = {id(v): i for i, v in enumerate(rec.jaxpr.invars)}
    for k, var in enumerate(rec.jaxpr.outvars):
        aval = getattr(var, "aval", None)
        if aval is None or _aval_nbytes(aval) < cfg.passthrough_bytes:
            continue
        if id(var) in invar_pos:
            i = invar_pos[id(var)]
            if i in rec.donated:
                continue   # donated pass-through: XLA aliases it, free
            yield rec.finding(
                "JX104",
                "output #%d (%s) is an unmodified pass-through of input "
                "%s — XLA must still materialize a fresh output copy; "
                "drop it from the returns and reuse the input at the "
                "call site" % (k, _fmt_aval(aval), rec.label(i)),
                key="dead-output:passthrough:%d" % k)
        elif hasattr(var, "val"):     # Literal output
            yield rec.finding(
                "JX104",
                "output #%d (%s) is a compile-time constant — computed "
                "nowhere, transferred every call" % (k, _fmt_aval(aval)),
                key="dead-output:const:%d" % k)


# ---------------------------------------------------------------------------
# JX201 collective-divergence
# ---------------------------------------------------------------------------

def _branch_label(i, n):
    if n == 2:
        return ("false-branch", "true-branch")[i]
    return "branch %d" % i


@trace_rule("JX201", "collective-divergence",
            "a collective under a data-dependent branch: lax.cond arms "
            "that disagree on their collective sequence, or a collective "
            "inside a while whose trip count ranks can disagree on — one "
            "rank enters the rendezvous, its peers never do, the mesh "
            "deadlocks; branch the VALUES with jnp.where instead")
def _jx201(rec, cfg):
    # Conservative on purpose: a cond predicate we could prove uniform
    # across ranks would be safe, but nothing at the jaxpr level proves
    # uniformity — suppress/baseline the (rare) justified case.
    for eqn in _iter_eqns(rec.jaxpr):
        prim = eqn.primitive.name
        if prim == "cond":
            branches = eqn.params.get("branches", ())
            sigs = [_collectives_in(br) for br in _extract_jaxprs(
                tuple(branches))]
            if len(set(sigs)) <= 1:
                continue          # all arms rendezvous identically: safe
            parts = []
            for i, sig in enumerate(sigs):
                shown = ",".join("%s@%s" % (p, "/".join(a))
                                 for p, a in sig) or "none"
                parts.append("%s: %s" % (_branch_label(i, len(sigs)),
                                         shown))
            yield rec.finding(
                "JX201",
                "lax.cond arms disagree on their collective sequence "
                "(%s) — a data-dependent predicate lets ranks take "
                "different arms and deadlock on the missing rendezvous; "
                "run the collective unconditionally and jnp.where the "
                "values" % "; ".join(parts),
                key="cond-divergence")
        elif prim == "while":
            colls = []
            for pkey in ("cond_jaxpr", "body_jaxpr"):
                sub = eqn.params.get(pkey)
                if sub is not None:
                    for j in _extract_jaxprs(sub):
                        colls.extend(_collectives_in(j))
            if not colls:
                continue
            shown = ",".join("%s@%s" % (p, "/".join(a))
                             for p, a in colls)
            yield rec.finding(
                "JX201",
                "collective(s) %s inside a lax.while_loop: the trip "
                "count is data-dependent by construction, so ranks can "
                "run the rendezvous a different number of times and "
                "deadlock — use a static-length scan (mask the tail) or "
                "hoist the collective out of the loop" % shown,
                key="while-collective")


# ---------------------------------------------------------------------------
# JX202 collective-order (per-record declared-axis check + lane groups)
# ---------------------------------------------------------------------------

@trace_rule("JX202", "collective-order",
            "per-mesh-axis collective sequences must match across "
            "programs sharing a lane and stay on the axes the provider "
            "declared — the canonical reduction order (PR 13) as a "
            "proven invariant")
def _jx202(rec, cfg):
    declared = rec.meta.get("mesh_axes")
    if declared is None:
        return
    declared = {str(a) for a in declared}
    seen = set()
    for eqn in _iter_eqns(rec.jaxpr):
        if eqn.primitive.name not in _COLLECTIVE_PRIMS:
            continue
        for axis in _collective_axes(eqn):
            if axis in declared or axis in seen:
                continue
            seen.add(axis)
            yield rec.finding(
                "JX202",
                "'%s' communicates over mesh axis '%s' which the "
                "provider did not declare (mesh_axes=%s) — an "
                "undeclared axis is invisible to the lane-order "
                "contract; declare it or drop the collective"
                % (eqn.primitive.name, axis, sorted(declared)),
                key="undeclared-axis:%s" % axis)


GROUP_RULES = {}


def _group_rule(code):
    def deco(fn):
        GROUP_RULES[code] = fn
        return fn
    return deco


@_group_rule("JX202")
def _jx202_group(records, cfg):
    """Cross-program half of JX202: programs sharing a provider-declared
    ``lane`` run concurrently on the same serialized collective stream,
    so their per-axis collective sequences must be identical — two
    members disagreeing on order is the classic cross-program deadlock
    (rank A runs program P's psum while rank B runs program Q's
    ppermute).  Today's lane members are collective-free or identical;
    the rule is the tripwire for drift."""
    lanes = {}
    for rec in records:
        lane = rec.meta.get("lane")
        if lane:
            lanes.setdefault(lane, []).append(rec)
    for lane in sorted(lanes):
        recs = lanes[lane]
        if len(recs) < 2:
            continue
        ref, ref_seq = recs[0], collective_sequence(recs[0])
        for rec in recs[1:]:
            seq = collective_sequence(rec)
            axes = sorted(set(ref_seq) | set(seq))
            for axis in axes:
                if ref_seq.get(axis, ()) == seq.get(axis, ()):
                    continue
                yield rec.finding(
                    "JX202",
                    "lane '%s' collective order diverges from '%s' on "
                    "axis '%s': %s vs %s — concurrent programs on one "
                    "lane must rendezvous in one canonical order"
                    % (lane, ref.name, axis,
                       list(seq.get(axis, ())),
                       list(ref_seq.get(axis, ()))),
                    key="lane-order:%s:%s" % (lane, axis))


# ---------------------------------------------------------------------------
# JX203 replication-waste
# ---------------------------------------------------------------------------

# ops that forward a gathered value unchanged (same bytes, new var)
_TRANSPARENT_PRIMS = {"convert_element_type", "reshape", "transpose",
                      "squeeze", "expand_dims", "copy", "stop_gradient",
                      "rev"}


@trace_rule("JX203", "replication-waste",
            "an all_gather whose fully-replicated result is returned as "
            "a program output: the sharded producer's bytes are "
            "multiplied by the axis size in HBM — keep the output "
            "sharded (out_specs) or reduce before returning")
def _jx203(rec, cfg):
    for jaxpr in _all_jaxprs(rec.jaxpr):
        gathered = {}          # id(var) -> (axes, nbytes)
        for eqn in jaxpr.eqns:
            prim = eqn.primitive.name
            if prim in ("all_gather", "all_gather_invariant"):
                axes = _collective_axes(eqn)
                if not axes:
                    continue
                for ov in eqn.outvars:
                    gathered[id(ov)] = (axes, _aval_nbytes(ov.aval))
            elif prim in _TRANSPARENT_PRIMS and eqn.invars \
                    and id(eqn.invars[0]) in gathered:
                axes, _n = gathered[id(eqn.invars[0])]
                for ov in eqn.outvars:
                    gathered[id(ov)] = (axes, _aval_nbytes(ov.aval))
        seen = set()
        for k, var in enumerate(jaxpr.outvars):
            info = gathered.get(id(var))
            if info is None or id(var) in seen:
                continue
            seen.add(id(var))
            axes, nbytes = info
            if nbytes < cfg.replication_bytes:
                continue
            yield rec.finding(
                "JX203",
                "output #%d (%s, %d bytes) is an all_gather over axis "
                "%s returned fully replicated — every rank materializes "
                "the whole array; shard the output spec or reduce "
                "before returning"
                % (k, _fmt_aval(getattr(var, "aval", None)), nbytes,
                   "/".join(axes)),
                key="gathered-output:%s" % "/".join(axes))


# ---------------------------------------------------------------------------
# JX204 memory-budget (driver-level: needs compile + MEM_BASELINE.json)
# ---------------------------------------------------------------------------

MEM_FIELDS = ("argument_bytes", "output_bytes", "temp_bytes",
              "generated_code_bytes", "alias_bytes")
# the budgeted figure: alias bytes are savings, not spend
_MEM_TOTAL_FIELDS = ("argument_bytes", "output_bytes", "temp_bytes",
                     "generated_code_bytes")

TRACE_RULES["JX204"] = TraceRule(
    "JX204", "memory-budget",
    "per-program compiled.memory_analysis() bytes (argument/output/temp/"
    "generated-code) vs the count-keyed MEM_BASELINE.json budget with an "
    "MXNET_MEM_TOLERANCE band — growth past budget is a lint-time "
    "finding, not an OOM at step time (driver tier: needs a compile)",
    None)


def default_mem_baseline_path():
    from .core import repo_root
    return os.path.join(repo_root(), "MEM_BASELINE.json")


def mem_tolerance(default=0.25):
    """The MXNET_MEM_TOLERANCE fractional band (0.25 = +25% headroom).
    Parsed per call — this only runs in the AOT driver and on compile
    events, never on the step path."""
    # driver/compile-event tier only, never the step path; a fresh read
    # per check lets tests and CI move the band without process restarts
    raw = os.environ.get("MXNET_MEM_TOLERANCE", "")  # graftlint: disable=JG006
    try:
        val = float(raw) if raw else default
    except ValueError:
        return default
    return val if val >= 0 else default


# byte jitter floor: sub-4KiB drift on tiny specimens is allocator noise,
# not a regression — the tolerance band is fractional, this is absolute
_MEM_SLACK_BYTES = 4096


def record_digest(rec):
    """Stable identity of a specimen's trace signature (in/out avals).
    Budgets are per-specimen: the runtime hook only compares a compile
    whose signature matches what the budget was captured from."""
    import hashlib
    sig = ";".join(_fmt_aval(a) for a in rec.in_avals) + "->" + \
        ";".join(_fmt_aval(a) for a in rec.out_avals)
    return hashlib.sha1(sig.encode("utf-8")).hexdigest()[:12]


def compile_record(rec):
    """Compile *rec*'s kept AOT lowering (the JX204 compile path — also
    what ``telemetry.opprof`` reuses for its HLO walk, so attribution
    adds zero new XLA entry points).  Returns the compiled executable,
    or None when there is no lowering or the backend refuses."""
    if rec.lowered is None:
        return None
    try:
        return rec.lowered.compile()
    except Exception:
        return None


def measure_memory(rec):
    """Compile *rec*'s kept lowering and return its memory_analysis()
    byte fields, or None when the backend cannot report them."""
    compiled = compile_record(rec)
    if compiled is None:
        return None
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return None
    if ma is None:
        return None
    out = {}
    for field in MEM_FIELDS:
        xla_name = field.replace("_bytes", "_size_in_bytes")
        try:
            out[field] = int(getattr(ma, xla_name))
        except (AttributeError, TypeError, ValueError):
            out[field] = 0
    out["total_bytes"] = sum(out[f] for f in _MEM_TOTAL_FIELDS)
    return out


def measure_programs(records):
    """Aggregate measured memory per program NAME (count-keyed: a name
    traced from k specimens sums its bytes and records ``specimens: k``
    so dropping a specimen is as visible as growing one).  Returns
    ``{name: entry}``; an unmeasurable specimen is recorded with
    ``measured: False`` rather than silently skipped."""
    import hashlib
    out = {}
    for rec in records:
        entry = out.setdefault(rec.name, dict(
            {f: 0 for f in MEM_FIELDS}, total_bytes=0, specimens=0,
            measured=True, digests=[]))
        entry["specimens"] += 1
        entry["digests"].append(record_digest(rec))
        m = measure_memory(rec)
        if m is None:
            entry["measured"] = False
            continue
        for f in MEM_FIELDS:
            entry[f] += m[f]
        entry["total_bytes"] += m["total_bytes"]
    for entry in out.values():
        digest = hashlib.sha1(
            ",".join(sorted(entry.pop("digests"))).encode()).hexdigest()
        entry["digest"] = digest[:12]
    return out


def _device_count():
    import jax
    return len(jax.devices())


def load_mem_baseline(path=None):
    """MEM_BASELINE.json -> dict, or None when absent/unreadable."""
    path = path or default_mem_baseline_path()
    if not os.path.exists(path):
        return None
    try:
        with open(path, encoding="utf-8") as f:
            payload = json.load(f)
    except (OSError, ValueError):
        return None
    if not isinstance(payload.get("programs"), dict):
        return None
    return payload


def save_mem_baseline(measured, path=None, n_devices=None, prior=None,
                      scoped_names=None):
    """Write *measured* (from :func:`measure_programs`) as the budget.
    A scoped run (``--diff``/entry groups) merges: names outside
    *scoped_names* keep their prior entries untouched, exactly like the
    LINT baseline's out-of-scope preservation."""
    path = path or default_mem_baseline_path()
    programs = {}
    if prior and scoped_names is not None:
        programs.update({k: v for k, v in prior.get("programs", {}).items()
                         if k not in scoped_names})
    programs.update(measured)
    payload = {"version": 1,
               "n_devices": int(n_devices if n_devices is not None
                                else _device_count()),
               "tolerance": mem_tolerance(),
               "programs": {k: programs[k] for k in sorted(programs)}}
    with open(path, "w", encoding="utf-8") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
        f.write("\n")
    return payload


def check_memory(records, baseline=None, tolerance=None, full=True):
    """JX204 over measured *records* vs *baseline* (a loaded
    MEM_BASELINE payload).  Returns ``(findings, report)`` where report
    is the stdlib-renderable dict ``trace_report.py --memory`` consumes.

    Topology honesty: memory bytes are a function of the device count
    the specimens lower against (conftest pins 8 virtual CPU devices);
    when the live topology differs from the baseline's, comparison is
    SKIPPED and the report says so — a gate that cannot measure must
    fail loudly downstream (``--gate-memory`` exits 4), never drift."""
    tol = mem_tolerance() if tolerance is None else tolerance
    n_dev = _device_count()
    measured = measure_programs(records)
    base_progs = (baseline or {}).get("programs", {})
    base_dev = (baseline or {}).get("n_devices")
    topology_match = baseline is not None and int(base_dev or 0) == n_dev
    findings = []
    report_programs = []
    by_name = {}
    for rec in records:
        by_name.setdefault(rec.name, rec)
    for name in sorted(measured):
        entry = dict(measured[name])
        rec = by_name[name]
        budget = base_progs.get(name) if topology_match else None
        entry.update(name=name, origin=rec.origin,
                     budget_total_bytes=None, over_budget=False,
                     unbudgeted=False)
        if not entry.pop("measured"):
            entry["unbudgeted"] = True
            findings.append(rec.finding(
                "JX204", "program could not be compiled for "
                "memory_analysis() — the budget gate cannot see it",
                key="mem:unmeasurable"))
        elif baseline is None or (topology_match and budget is None):
            entry["unbudgeted"] = True
            findings.append(rec.finding(
                "JX204",
                "no memory budget for this program in MEM_BASELINE.json "
                "— every owned program is born budgeted; run "
                "graftcheck --write-mem-baseline", key="mem:unbudgeted"))
        elif budget is not None:
            if int(budget.get("specimens", 1)) != entry["specimens"]:
                findings.append(rec.finding(
                    "JX204",
                    "specimen count changed (%d budgeted, %d traced) — "
                    "the budget no longer describes this program; "
                    "re-run --write-mem-baseline"
                    % (int(budget.get("specimens", 1)),
                       entry["specimens"]), key="mem:specimens"))
            b_total = int(budget.get("total_bytes", 0))
            limit = b_total + max(int(b_total * tol), _MEM_SLACK_BYTES)
            entry["budget_total_bytes"] = b_total
            if entry["total_bytes"] > limit:
                entry["over_budget"] = True
                deltas = ", ".join(
                    "%s %+d" % (f, entry[f] - int(budget.get(f, 0)))
                    for f in _MEM_TOTAL_FIELDS
                    if entry[f] != int(budget.get(f, 0)))
                findings.append(rec.finding(
                    "JX204",
                    "memory over budget: %d bytes vs %d budgeted "
                    "(+%d%% tolerance -> limit %d) [%s] — an HBM "
                    "regression caught at lint time; shrink the program "
                    "or re-budget deliberately with --write-mem-baseline"
                    % (entry["total_bytes"], b_total, int(tol * 100),
                       limit, deltas or "same fields"),
                    key="mem:over"))
        report_programs.append(entry)
    stale = []
    if topology_match and full:
        stale = sorted(set(base_progs) - set(measured))
    report = {"schema": "memcheck-v1", "n_devices": n_dev,
              "tolerance": tol,
              "baseline_n_devices": base_dev,
              "baseline_present": baseline is not None,
              "topology_match": bool(topology_match),
              "stale_budgets": stale,
              "programs": report_programs}
    return findings, report


# ---------------------------------------------------------------------------
# JX105 retrace-explainer (runtime-only; registered for the catalogue)
# ---------------------------------------------------------------------------

TRACE_RULES["JX105"] = TraceRule(
    "JX105", "retrace-explainer",
    "on a watch_jit recompile, diff the new avals/static args against "
    "the cached variants and name the axis that changed (runtime tier, "
    "MXNET_TRACECHECK)", None)


def signature(args, kwargs):
    """Flat trace signature of a call: [(label, kind, detail...)] —
    arrays collapse to shape/dtype, everything else to type + repr."""
    import jax
    flat, treedef = jax.tree_util.tree_flatten_with_path(
        (tuple(args), dict(kwargs or {})))
    sig = []
    for path, leaf in flat:
        label = jax.tree_util.keystr(path)
        label = ("arg%s" % label[3:]) if label.startswith("[0]") \
            else ("kwarg%s" % label[3:])
        shape = getattr(leaf, "shape", None)
        dtype = getattr(leaf, "dtype", None)
        if shape is not None and dtype is not None:
            sig.append((label, "array", tuple(shape), str(dtype)))
        else:
            sig.append((label, "static", type(leaf).__name__,
                        repr(leaf)[:80]))
    return sig


def _diff_entries(old, new):
    """Human sentences for what changed between two signature entries."""
    label = new[0]
    if old[1] == "array" and new[1] == "array":
        msgs = []
        if old[2] != new[2]:
            axes = [("axis %d: %s->%s" % (d, o, n))
                    for d, (o, n) in enumerate(zip(old[2], new[2]))
                    if o != n]
            if len(old[2]) != len(new[2]):
                axes.append("rank %d->%d" % (len(old[2]), len(new[2])))
            msgs.append("%s shape %s->%s (%s)"
                        % (label, old[2], new[2], ", ".join(axes)))
        if old[3] != new[3]:
            msgs.append("%s dtype %s->%s" % (label, old[3], new[3]))
        return msgs
    if old[1] != new[1]:
        return ["%s changed kind %s->%s" % (label, old[1], new[1])]
    if old[2:] != new[2:]:
        return ["%s static value %s -> %s (each distinct hashable value "
                "is a separate compiled variant)" % (label, old[3], new[3])]
    return []


def explain_retrace(name, history, new_sig):
    """Diff *new_sig* against its closest cached variant and name the
    axis of change.  Returns the one-line diagnosis."""
    def diffs_against(old):
        old_map = {e[0]: e for e in old}
        new_map = {e[0]: e for e in new_sig}
        out = []
        for label, entry in new_map.items():
            if label in old_map:
                out.extend(_diff_entries(old_map[label], entry))
            else:
                out.append("%s appeared (structure change)" % label)
        for label in old_map:
            if label not in new_map:
                out.append("%s disappeared (structure change)" % label)
        return out

    best = min((diffs_against(old) for old in history), key=len)
    if not best:
        return ("recompile of '%s' with no visible shape/dtype/structure "
                "change — suspect weak_type promotion, sharding change, or "
                "a non-pytree closure input" % name)
    shown = "; ".join(best[:4])
    if len(best) > 4:
        shown += "; ... %d more" % (len(best) - 4)
    return ("recompile of '%s' caused by: %s — pad or bucket the changing "
            "axis so the compiled program is reused" % (name, shown))


# ---------------------------------------------------------------------------
# running rules
# ---------------------------------------------------------------------------

def run_rules(record, select=None, config=None):
    cfg = config or DEFAULT_CONFIG
    findings = []
    for code, rule in sorted(TRACE_RULES.items()):
        if select is not None and code not in select:
            continue
        findings.extend(rule.check(record, cfg))
    return findings


def run_group_rules(records, select=None, config=None):
    """The cross-program rules (JX202 lane order): per-record checks
    cannot see two programs at once, so the driver hands the whole
    record set over after tracing."""
    cfg = config or DEFAULT_CONFIG
    findings = []
    for code in sorted(GROUP_RULES):
        if select is not None and code not in select:
            continue
        findings.extend(GROUP_RULES[code](records, cfg))
    return findings


# ---------------------------------------------------------------------------
# AOT driver over the owned entry points
# ---------------------------------------------------------------------------

# (group, module) — each module owns jits and exposes tracecheck_programs()
# yielding (name, fn, args, kwargs) specimens for every program it ships.
ENTRY_POINTS = (
    ("kvstore", "mxnet_tpu.kvstore"),
    ("collective", "mxnet_tpu.parallel.collective"),
    ("optimizer", "mxnet_tpu.optimizer"),
    ("fused_trainer", "mxnet_tpu.gluon.fused_trainer"),
    ("executor", "mxnet_tpu.executor"),
    ("module_cached_step", "mxnet_tpu.module.cached_step"),
    ("gluon_cached_op", "mxnet_tpu.gluon.block"),
    ("predict", "mxnet_tpu.predict"),
    ("serving", "mxnet_tpu.serving.program"),
    ("guardian", "mxnet_tpu.guardian"),
    ("gluon_utils", "mxnet_tpu.gluon.utils"),
    ("pipeline", "mxnet_tpu.parallel.pipeline"),
    ("ring_attention", "mxnet_tpu.parallel.ring_attention"),
    ("sharded_trainer", "mxnet_tpu.parallel.sharded"),
    ("transformer", "mxnet_tpu.models.transformer"),
    ("model_stats", "mxnet_tpu.model_stats"),
)


def iter_owned_programs(entries=None):
    """Yield (group, ProgramRecord-or-Finding) over every owned entry
    point.  A provider that fails to build/trace yields a JX000 finding —
    silent skips would read as coverage."""
    import importlib
    for group, modpath in ENTRY_POINTS:
        if entries is not None and group not in entries:
            continue
        origin = modpath.replace(".", "/") + ".py"
        try:
            mod = importlib.import_module(modpath)
            programs = list(mod.tracecheck_programs())
        except Exception as exc:
            yield group, Finding(
                "JX000", "trace://%s" % group, 0, 0,
                "entry point provider %s failed: %r" % (modpath, exc),
                snippet="provider:%s" % group)
            continue
        for spec in programs:
            # 4-tuple (name, fn, args, kwargs) or 5-tuple with a trailing
            # sharding-metadata dict ({"lane": ..., "mesh_axes": ...})
            name, fn, args, kwargs = spec[:4]
            meta = spec[4] if len(spec) > 4 else None
            try:
                yield group, trace_program(name, fn, args, kwargs,
                                           origin=origin, meta=meta)
            except Exception as exc:
                yield group, Finding(
                    "JX000", "trace://%s" % name, 0, 0,
                    "tracing '%s' (%s) failed: %r" % (name, origin, exc),
                    snippet="trace:%s" % name)


# beyond lint/ itself, these files steer every trace-tier verdict: the
# opprof HLO walk is an analyzer over the same specimen ledger, and the
# costs peak tables decide its compute/HBM/comm classifications
_FULL_SWEEP_PATHS = frozenset({
    "mxnet_tpu/telemetry/opprof.py",
    "mxnet_tpu/telemetry/costs.py",
})


def groups_for_paths(paths):
    """Map changed repo-relative .py paths onto the ENTRY_POINTS groups
    they provide — the ``--diff`` scope for the trace tier.  A change to
    the analyzer itself (``mxnet_tpu/lint/``), to the opprof attribution
    walk, or to the cost-model peak tables dirties every group: the
    rules changed, so every verdict did."""
    norm = {p.replace(os.sep, "/") for p in paths}
    if any(p.startswith("mxnet_tpu/lint/") or p in _FULL_SWEEP_PATHS
           for p in norm):
        return {g for g, _m in ENTRY_POINTS}
    hit = set()
    for group, modpath in ENTRY_POINTS:
        mod_file = modpath.replace(".", "/") + ".py"
        pkg_init = modpath.replace(".", "/") + "/__init__.py"
        if mod_file in norm or pkg_init in norm:
            hit.add(group)
    return hit


def analyze_entry_points(entries=None, select=None, config=None,
                         memory=True, mem_baseline_path=None):
    """The full JX driver: trace every owned program, run the
    per-record rules, the cross-program lane rules, and (when *memory*)
    the JX204 budget comparison.  Returns ``(findings, names,
    mem_report)`` — mem_report is None when the memory pass was skipped
    or JX204 deselected."""
    findings, names, records = [], [], []
    for _group, item in iter_owned_programs(entries):
        if isinstance(item, Finding):
            findings.append(item)
            continue
        names.append(item.name)
        records.append(item)
        findings.extend(run_rules(item, select=select, config=config))
    findings.extend(run_group_rules(records, select=select, config=config))
    mem_report = None
    if memory and (select is None or "JX204" in select):
        baseline = load_mem_baseline(mem_baseline_path)
        mem_findings, mem_report = check_memory(
            records, baseline, full=entries is None)
        findings.extend(mem_findings)
    findings.sort(key=lambda f: (f.path, f.rule, f.snippet))
    return findings, names, mem_report


def check_entry_points(entries=None, select=None, config=None,
                       memory=True, mem_baseline_path=None):
    """Run the JX rules over every owned program; returns (findings,
    program_names) — names prove coverage to the CI gate."""
    findings, names, _mem = analyze_entry_points(
        entries=entries, select=select, config=config, memory=memory,
        mem_baseline_path=mem_baseline_path)
    return findings, names


# ---------------------------------------------------------------------------
# runtime hook (MXNET_TRACECHECK): called by telemetry on compile events
# ---------------------------------------------------------------------------

_SIG_HISTORY = {}    # (watch name, id(jit)) -> [signature, ...] (last 8)
_SEQ_HISTORY = {}    # (watch name, id(jit)) -> first variant's per-axis seq
_MEM_BASELINE_CACHE = []   # [payload-or-None], loaded once per process
_RUNTIME_CONFIG = DEFAULT_CONFIG


def reset_runtime():
    _SIG_HISTORY.clear()
    _SEQ_HISTORY.clear()
    del _MEM_BASELINE_CACHE[:]


def _runtime_spmd_checks(name, fn, record):
    """The JX2xx runtime slice: JX202 across a program's own compiled
    variants (two variants of one watch name disagreeing on collective
    order is the same lane hazard, caught live), and JX204 only when the
    compile's trace signature matches the digest its budget was captured
    from — a real model compiling under the same watch name is a
    different program and must not be judged by the specimen's budget
    (or pay a second compile)."""
    findings = []
    key = (name, id(fn))
    seq = collective_sequence(record)
    prev = _SEQ_HISTORY.setdefault(key, seq)
    if prev is not seq and prev != seq:
        findings.append(record.finding(
            "JX202",
            "compiled variant changed the collective order: %s vs the "
            "first variant's %s — variants of one program must "
            "rendezvous in one canonical order"
            % ({a: list(s) for a, s in sorted(seq.items())},
               {a: list(s) for a, s in sorted(prev.items())}),
            key="variant-order"))
    if not _MEM_BASELINE_CACHE:
        _MEM_BASELINE_CACHE.append(load_mem_baseline())
    baseline = _MEM_BASELINE_CACHE[0]
    if baseline is not None:
        budget = baseline.get("programs", {}).get(record.name)
        if budget is not None \
                and int(baseline.get("n_devices", 0)) == _device_count() \
                and int(budget.get("specimens", 1)) == 1 \
                and budget.get("digest") == record_digest(record):
            mem_findings, _report = check_memory(
                [record], baseline, full=False)
            findings.extend(mem_findings)
    return findings


def on_compile(name, fn, args, kwargs):
    """Analyze the program a watched jit just compiled.

    Called from ``telemetry._WatchedJit`` on cache growth when
    ``MXNET_TRACECHECK`` is truthy.  JX105 diffs the call signature
    against this name's previous variants; JX101-JX104 re-trace the
    function from specs (cheap next to the XLA compile that just
    happened).  Findings are booked into the ``tracecheck_findings``
    counter, the flight ring, and one structured log line each; this
    function never raises into the training step.
    """
    findings = []
    try:
        sig = signature(args, kwargs)
    except Exception:
        sig = None
    # keyed per jitted fn, not per watch name: distinct programs sharing
    # a name (a cached op's train/eval pair, every optimizer instance
    # under "optimizer_update_step") are separate compile caches — their
    # first compiles are not recompiles of each other
    history = _SIG_HISTORY.setdefault((name, id(fn)), [])
    if sig is not None:
        if history:
            findings.append(Finding(
                "JX105", "trace://%s" % name, 0, 0,
                explain_retrace(name, history, sig), snippet=name))
        history.append(sig)
        del history[:-8]
    try:
        record = trace_program(name, fn, args, kwargs)
        findings.extend(run_rules(record, config=_RUNTIME_CONFIG))
        findings.extend(_runtime_spmd_checks(name, fn, record))
    except Exception:
        pass                   # analysis must never break a step
    _book(findings)
    return findings


def _book(findings):
    if not findings:
        return
    try:
        from .. import telemetry as _tel
        from ..telemetry import flight as _flight
        _tel.bump("tracecheck_findings", len(findings))
        for f in findings:
            _flight.record("tracecheck", f.rule, detail=f.message[:200])
            _LOG.warning("tracecheck %s", json.dumps(
                {"rule": f.rule, "program": f.path[len("trace://"):],
                 "finding": f.message}, sort_keys=True))
    except Exception:
        pass


